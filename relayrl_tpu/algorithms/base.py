"""Algorithm plugin contract.

Capability parity with the reference's learner plugin interface
(reference: relayrl_framework/src/native/python/_common/_algorithms/
BaseAlgorithm.py:4-39 — ``save``, ``receive_trajectory -> bool``,
``train_model``, ``log_epoch``), extended with the TPU-native pieces the
reference lacks: a pure jitted ``learner_step``, a versioned
:class:`~relayrl_tpu.types.ModelBundle` surface for transport, and full
checkpoint/resume (params + optimizer state + RNG + counters; the
reference checkpoints only the TorchScript policy file — SURVEY.md §5.4).

Algorithms register by name; the training server resolves
``algorithm_name`` through :func:`build_algorithm` the way the reference's
learner subprocess dynamically imports ``{ALGO}.{ALGO}``
(python_algorithm_reply.py:41-46).
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Mapping, Sequence

from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle

_ALGO_REGISTRY: dict[str, Callable[..., "AlgorithmBase"]] = {}


def register_algorithm(name: str):
    def deco(cls):
        _ALGO_REGISTRY[name.upper()] = cls
        return cls
    return deco


def build_algorithm(name: str, **kwargs) -> "AlgorithmBase":
    try:
        cls = _ALGO_REGISTRY[name.upper()]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; registered: {sorted(_ALGO_REGISTRY)}"
        ) from None
    return cls(**kwargs)


def registered_algorithms() -> list[str]:
    return sorted(_ALGO_REGISTRY)


def anchor_path(path: str, env_dir: str | None) -> str:
    """Anchor a relative artifact path (model file, checkpoint dir) under
    ``env_dir`` so default-named run artifacts land in the run's directory
    instead of the caller's cwd. Absolute paths pass through untouched."""
    import os

    if env_dir and not os.path.isabs(path):
        return os.path.join(env_dir, path)
    return path


class AlgorithmBase(abc.ABC):
    """Host-side orchestration wrapper around a pure jitted learner step."""

    # Warmup executes one real (discarded) update per shape, so its cost
    # scales with B*T (times vf iters for the actor-critic families) — a
    # [2001, 1000] placeholder epoch measured 4+ minutes on a 1-core host.
    # Shapes above this B*T bound are skipped and compile on first use
    # instead (the bound covers every default config: traj_per_epoch=8 x
    # the largest default bucket 1000 = 8000; override per-instance when a
    # deployment with bigger epochs wants full pre-compilation anyway).
    warmup_max_elements = 32768

    # Trajectories rejected by the ingest finite-value guard
    # (types/columnar.py trajectory_is_finite); class default so the
    # first increment materializes the instance counter.
    dropped_nonfinite = 0

    # The per-algorithm finite guard's enable flag. The guardrail plane
    # (relayrl_tpu/guardrails) sets it False ONLY in the observe-only
    # "warn" validation mode — the plane then owns the boundary and this
    # belt must stand down or warn-mode silently re-enforces. Everywhere
    # else it stays True (belt-and-suspenders under "enforce").
    ingest_finite_guard = True

    # Divergence-watchdog probe source (guardrails/watchdog.GuardProbes),
    # installed by Guardrails.attach_algorithm; None = no probes, the
    # dispatch paths pay one identity check.
    _guard_probes = None

    # Bounded async-dispatch window (runtime/pipeline.InflightWindow);
    # class defaults so pre-existing subclasses/tests that never touch
    # the pipeline keep working. max_inflight_updates=0 restores the
    # fully synchronous fence-every-dispatch behavior.
    max_inflight_updates = 2
    _inflight = None
    # Host-side mirror of state.step: once updates dispatch async,
    # reading int(state.step) fences the whole in-flight window, so the
    # publish path needs a version that never touches the device. None
    # until the first dispatch (or after a checkpoint restore) — it
    # re-syncs from the (then resolved) device step before dispatching.
    _dispatched_updates = None

    def _drop_nonfinite(self) -> None:
        """Count + log one trajectory rejected by the finite-value guard —
        the single owner of the drop policy for both algorithm families
        (a NaN/inf would not crash; it would silently poison the learner
        state and, through the next publish, the fleet)."""
        self.dropped_nonfinite += 1
        print(f"[{self.ALGO_NAME}] dropped non-finite trajectory "
              f"(#{self.dropped_nonfinite})", flush=True)

    # -- reference contract (BaseAlgorithm.py:4-39) --
    @abc.abstractmethod
    def receive_trajectory(self, actions: Sequence[ActionRecord]) -> bool:
        """Ingest one episode; returns True when a train step ran (the
        training server publishes a new model on True, mirroring
        training_zmq.rs:1016-1029)."""

    @abc.abstractmethod
    def train_model(self) -> Mapping[str, Any]:
        """Run one epoch update; returns metrics."""

    @abc.abstractmethod
    def save(self, path) -> None:
        """Write the distributable model artifact (ref: torch.jit.save)."""

    @abc.abstractmethod
    def log_epoch(self) -> None:
        """Dump the epoch's tabular diagnostics."""

    # -- multi-host contract (optional; the TrainingServer broadcast loop
    # uses it when jax.process_count() > 1 — SURVEY §7.4 item 5). A family
    # supports multi-host by providing:
    #   accumulate(item)       coordinator-side ingest, returns ready host
    #                          batch(es) (dict, list of dicts, or None)
    #   train_on_batch(batch)  the collective update, called on every rank
    #   mh_zero_batch(d1, d2)  shape/dtype placeholder for non-coordinators
    #   maybe_log_epoch()      epoch logging policy after a collective step
    #   enable_multihost(mesh) re-compile the update over the global mesh

    # -- TPU-native surface --
    def warmup(self, should_continue=None) -> int:
        """Pre-compile the jitted update for every batch shape the first
        real epochs can hit, so the first update under load is a cache
        hit instead of a compile. XLA compiles on a learner thread that —
        in a one-process, few-core deployment (a notebook kernel hosting
        both the server and a busy actor loop) — otherwise competes with
        the actor for CPU and can stretch a ~2 s compile past the whole
        example run. Returns the number of shapes compiled; families
        without a known shape set return 0. Best-effort: callers treat
        failures as non-fatal.

        ``should_continue`` (nullary → bool) is consulted before each
        shape: once real work is already queued, compiling on demand is
        just as fast as warming up, so implementations stop early instead
        of pre-paying shapes the caller may never hit.
        """
        return 0

    def checkpoint_aux(self):
        """Host-side arrays to persist alongside the train state (a pytree
        of numpy arrays, or None). The off-policy family returns its
        replay buffer here; on-policy has no host state worth carrying
        (an epoch buffer refills within one epoch)."""
        return None

    def restore_aux(self, aux) -> None:
        """Apply a previously saved :meth:`checkpoint_aux` payload."""

    def _warmup_is_collective(self) -> bool:
        """True when this algorithm's update is a multi-process collective
        (``enable_multihost`` over >1 jax processes) — warming up solo
        would hang every other rank in the collective, so family
        ``warmup()`` implementations refuse and return 0. This guard lives
        at the algorithm altitude on purpose: the server's broadcast loop
        is not the only possible caller."""
        if getattr(self, "_mesh", None) is None:
            return False
        import jax

        return jax.process_count() > 1

    @property
    def inflight(self) -> "InflightWindow":
        """The dispatched-but-unfenced update window, created lazily so
        algorithms built before any training pay nothing. One per
        instance: every family's ``train_on_batch`` pushes its update's
        metric leaves here, which (a) bounds how far the host runs ahead
        of the device and (b) is the fence ledger the server's
        ``drain()`` and the staging-buffer reuse proof rely on."""
        if self._inflight is None:
            from relayrl_tpu.runtime.pipeline import InflightWindow

            self._inflight = InflightWindow(self.max_inflight_updates)
        return self._inflight

    # -- divergence-watchdog probes (guardrails plane) --
    def _guard_probe_tree(self):
        """The param tree the health probes observe. The on-policy and
        value families keep trainable params at ``state.params``; the
        actor-critic families (SAC/DDPG/TD3) split them across
        ``*_params`` fields — collect those, excluding ``target_*``
        (polyak copies of what is already probed). Anything else falls
        back to the whole state tree: the finiteness probe stays
        meaningful on any pytree of arrays."""
        state = self.state
        params = getattr(state, "params", None)
        if params is not None:
            return params
        fields = getattr(type(state), "__dataclass_fields__", None)
        if fields:
            tree = {name: getattr(state, name) for name in fields
                    if name.endswith("_params")
                    and not name.startswith("target_")}
            if tree:
                return tree
        return state

    def _guard_pre_update(self):
        """Async D2D copy of the probe target, taken BEFORE the donating
        update so the old buffers are still live (the update-norm
        probe's base). None when probes are off — one identity check.
        A probe failure DISABLES probes (logged once) instead of
        propagating: the guardrail plane must never break the learner
        it protects."""
        probes = self._guard_probes
        if probes is None:
            return None
        try:
            return probes.pre_update(self._guard_probe_tree())
        except Exception as e:
            self._guard_probes = None
            print(f"[guardrails] health probes DISABLED "
                  f"(pre-update probe failed: {e!r})", flush=True)
            return None

    def _guard_merge_probes(self, metrics, old_copy) -> Mapping[str, Any]:
        """Merge the post-update probe scalars (unresolved device
        arrays) into ``metrics``; pass-through when probes are off. The
        merged dict rides the in-flight window and LazyMetrics exactly
        like the update's own metrics — resolved at the fence, never on
        the dispatch path."""
        probes = self._guard_probes
        if probes is None:
            return metrics
        merged = dict(metrics)
        try:
            merged.update(probes.post_update(old_copy,
                                             self._guard_probe_tree()))
        except Exception as e:
            self._guard_probes = None
            print(f"[guardrails] health probes DISABLED "
                  f"(post-update probe failed: {e!r})", flush=True)
            return metrics
        return merged

    def force_version(self, version: int) -> None:
        """Fast-forward the model version PAST a rolled-back line of
        history (guardrail rollback): the restored params keep training
        under a version higher than anything the poisoned line
        published, so actor swap gates, artifact gates, and checkpoint
        step numbering all stay monotonic. Step numbers are labels — the
        true state is the restored tree (checkpoint/manager.py)."""
        import jax.numpy as jnp

        step = self.state.step
        self.state = self.state.replace(
            step=jnp.asarray(int(version), dtype=step.dtype))
        self._dispatched_updates = None

    def reset_ingest_buffers(self) -> None:
        """Drop partially-accumulated host-side ingest state after a
        rollback (a poisoned stream may have part-filled it). Base:
        nothing to drop; on-policy clears its epoch buffer. The
        off-policy replay ring is restored by the checkpoint's aux
        snapshot instead (or deliberately kept when the step carried
        none — stale-but-finite experience is valid off-policy data)."""

    def _sync_version_mirror(self) -> None:
        """Initialize the host-side step mirror BEFORE the first async
        dispatch — at that point ``state.step`` is resolved (construction
        or checkpoint restore both finish synchronously), so the one
        ``int()`` here is free; after dispatching it would fence."""
        if self._dispatched_updates is None:
            self._dispatched_updates = int(self.version)

    @property
    def dispatched_version(self) -> int:
        """Model version including dispatched-but-unfenced updates —
        what an async publish stamps on its snapshot (``version`` reads
        the device and would fence the in-flight window)."""
        if self._dispatched_updates is not None:
            return self._dispatched_updates
        return int(self.version)

    def snapshot_for_publish(self):
        """Cheap, non-blocking publish handoff: a device-to-device copy
        of the publishable params (dispatched async — the copy runs
        after the last queued update, so it observes it) stamped with
        the host-side version mirror. The publisher thread turns it into
        a :class:`~relayrl_tpu.types.ModelBundle` with the blocking
        ``device_get`` off the learner thread.

        On a mesh (``enable_multihost``) the copy is the jitted
        re-shard-to-replicated ``_gather_params`` — still a non-blocking
        dispatch, but on a multi-process mesh it is a COLLECTIVE: every
        rank must call this at the same point (the server's broadcast
        loop does); the coordinator's publisher thread then reads one
        local shard of the replicated result (``host_params`` handles
        the non-fully-addressable read).
        """
        import jax
        import jax.numpy as jnp

        from relayrl_tpu.runtime.pipeline import PublishSnapshot

        gather = getattr(self, "_gather_params", None)
        if gather is not None:
            # A fresh replicated buffer (jit never aliases output to a
            # non-donated input), so the next update's donation cannot
            # invalidate it — the same safety jnp.copy provides below.
            params = gather(self._publish_params())
        else:
            params = jax.tree_util.tree_map(
                lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
                self._publish_params())
        return PublishSnapshot(version=self.dispatched_version,
                               arch=self._publish_arch(), params=params)

    def _publish_params(self):
        """The param slice a published bundle carries (on-policy: full
        policy params; off-policy: the actor slice)."""
        raise NotImplementedError

    def _publish_arch(self) -> dict:
        """Arch shipped with the bundle (hook for annealing knobs)."""
        return self.arch

    def capture_epoch_stats(self, updated: bool):
        """Snapshot-and-reset the host counters an epoch log needs, at
        DISPATCH time — when the server defers ``log_epoch`` behind the
        in-flight window, episodes arriving for the *next* epoch must
        not leak into this epoch's row. Returns an opaque payload for
        ``log_epoch(stats=...)``, or None when no log is due."""
        return None

    def stage_batch(self, host_batch) -> dict:
        """Prefetch an assembled host batch to the device ahead of
        dispatch. ``jax.device_put`` enqueues the H2D copy without
        waiting, so a batch staged while the previous update still runs
        overlaps its transfer with device compute instead of paying it
        inside the (window-fenced) dispatch path. ``_to_device`` passes
        already-placed arrays through untouched, so a staged batch and a
        host batch are interchangeable downstream. Single-host only —
        mesh placement (``_place``) already owns multihost batches."""
        import jax

        place = getattr(self, "_place", None)
        if place is not None:
            return place(dict(host_batch))
        return jax.device_put(dict(host_batch))

    def _to_device(self, host_batch) -> dict:
        """The single owner of host-batch → device-batch placement
        (mesh-aware ``_place`` when multihost, plain ``asarray``
        otherwise). Both families' ``train_on_batch`` and the warmup path
        share it so a placement change cannot leave warmup compiling cache
        entries the real update never hits."""
        import jax.numpy as jnp

        place = getattr(self, "_place", None)
        if place is not None:
            return place(dict(host_batch))
        return {k: jnp.asarray(v) for k, v in host_batch.items()}

    def _warmup_update(self, host_batch, update_fn=None) -> None:
        """Run ``update_fn`` (default ``self._update``) once on a
        shape/dtype placeholder batch and
        discard every output. The state argument is donated
        (``donate_argnums=0``), so the update consumes a copy — the live
        ``self.state`` buffers, version, metrics, and logger are untouched.
        Non-array state leaves pass through un-copied to keep the call
        signature identical to the real update's (a dtype-changed leaf
        would compile a cache entry the real call never hits).

        Ordering: warmup must finish before any OTHER thread drives
        ``train_on_batch`` — the real update donates its state argument
        (``donate_argnums=0``), so a concurrent update can delete the
        live buffers mid-copy here and this raises (the server's own
        learner thread is already ordered warmup-then-train; out-of-band
        callers should ``server.wait_warmup()`` first — a raise here is
        caught as non-fatal and warmup is merely skipped)."""
        import jax
        import jax.numpy as jnp

        live = self.state  # one read: a swap mid-warmup can't mix trees
        state_copy = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            live)
        fn = update_fn if update_fn is not None else self._update
        _, metrics = fn(state_copy, self._to_device(host_batch))
        jax.block_until_ready(metrics)

    def _jitted_policy_step(self):
        """``self.policy.step`` jitted once per instance — rebuilding the
        wrapper per call would bypass the compile cache and retrace every
        action."""
        if getattr(self, "_jit_step_fn", None) is None:
            import jax

            self._jit_step_fn = jax.jit(self.policy.step)
        return self._jit_step_fn

    @abc.abstractmethod
    def bundle(self) -> ModelBundle:
        """Current policy as a versioned transportable bundle."""

    @property
    @abc.abstractmethod
    def version(self) -> int:
        """Monotonic model version (bumped once per train step)."""
