"""jaxlint rule engine: AST module model, finding type, suppression,
baseline matching, and the file/directory driver.

This module is pure stdlib (``ast`` + ``json``) on purpose: linting must
never require jax — CI can gate a PR on hosts with no accelerator stack.
(Reaching it as ``relayrl_tpu.analysis`` still executes the package root,
which imports the lightweight types/config layer: numpy + msgpack, the
package's base deps — but never jax/flax/optax.)

The unit of identity for a finding is ``(rule, path, stripped source
line)`` — NOT the line number. Line numbers churn on every unrelated
edit; the snippet-keyed baseline survives code motion the way
pylint/ruff per-line suppression cannot (idea borrowed from
mypy/ruff ``--add-noqa`` baselines and Google's Tricorder).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Iterable, Iterator, Mapping, Sequence

__all__ = [
    "Finding",
    "Rule",
    "ModuleInfo",
    "analyze_source",
    "analyze_file",
    "analyze_paths",
    "load_baseline",
    "write_baseline",
    "apply_baseline",
    "qualname",
    "statement_end_line",
]

# Calls that wrap a python function into a traced/compiled one.
JIT_WRAPPERS = frozenset({
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
    "jax.named_call",
})

# Control-flow primitives whose function arguments are traced bodies.
TRACED_HOF = frozenset({
    "jax.lax.scan",
    "jax.lax.while_loop",
    "jax.lax.fori_loop",
    "jax.lax.cond",
    "jax.lax.switch",
    "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.checkpoint",
    "jax.remat",
    "jax.vmap",
    "jax.grad",
    "jax.value_and_grad",
})

_SUPPRESS_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_\-, ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic. ``snippet`` (the stripped source line) is part of
    the identity so baselines survive line-number churn."""

    rule: str       # stable code, e.g. "JAX01"
    name: str       # human slug, e.g. "prng-key-reuse"
    path: str       # posix-style path as reported (relative when possible)
    line: int
    col: int
    message: str
    snippet: str

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}[{self.name}] {self.message}")


class Rule:
    """Base class. Subclasses set ``code``/``name``/``description`` and
    yield ``(node, message)`` from :meth:`check`; the engine attaches
    location, snippet and suppression handling."""

    code: str = "XXX00"
    name: str = "abstract-rule"
    description: str = ""

    def check(self, module: "ModuleInfo") -> Iterator[tuple[ast.AST, str]]:
        raise NotImplementedError

    def finding(self, module: "ModuleInfo", node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        snippet = ""
        if 1 <= line <= len(module.lines):
            snippet = module.lines[line - 1].strip()
        return Finding(rule=self.code, name=self.name, path=module.path,
                       line=line, col=col, message=message, snippet=snippet)


def walk_skip_nested_functions(node: ast.AST) -> Iterator[ast.AST]:
    """Walk a node's subtree without descending into nested
    def/lambda/class bodies (they execute in a different context). The
    shared helper for every rule that reasons about "what runs here"."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        yield child
        yield from walk_skip_nested_functions(child)


def qualname(node: ast.AST) -> str | None:
    """Dotted name of a Name/Attribute chain (``self.x.y`` -> "self.x.y"),
    or None for anything not expressible as one."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ModuleInfo:
    """Parsed module plus the cross-rule facts every rule needs:
    import aliases, which function names are jit-wrapped, and which
    FunctionDef nodes execute under a trace."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.aliases = self._collect_aliases(tree)
        # Function NAMES wrapped by jax.jit(...) somewhere in the module
        # (``self._update = jax.jit(update, ...)`` records "update").
        self.jit_wrapped_names: set[str] = set()
        # Dotted names of jit-compiled CALLABLES — the assignment targets
        # (``self._update``, ``fn``) — consumed by the timing rule.
        self.jitted_callables: set[str] = set()
        # All jit-wrapper call sites: (call, wrapped_arg, target_qualname).
        self.jit_calls: list[tuple[ast.Call, ast.AST, str | None]] = []
        self._collect_jit_facts(tree)
        self.traced_functions = self._collect_traced_functions(tree)

    # -- import alias resolution --
    @staticmethod
    def _collect_aliases(tree: ast.Module) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, dotted: str | None) -> str | None:
        """Expand the leading segment through the module's import aliases
        (``jnp.mean`` -> "jax.numpy.mean", ``jit`` -> "jax.jit")."""
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        expanded = self.aliases.get(head, head)
        return f"{expanded}.{rest}" if rest else expanded

    def resolved_call(self, node: ast.Call) -> str | None:
        return self.resolve(qualname(node.func))

    # -- jit topology --
    def _collect_jit_facts(self, tree: ast.Module) -> None:
        seen: set[int] = set()
        for node in ast.walk(tree):
            target: str | None = None
            call: ast.Call | None = None
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = node.value
                if len(node.targets) == 1:
                    target = qualname(node.targets[0])
            elif isinstance(node, ast.Call):
                call = node
            if call is None or self.resolved_call(call) not in JIT_WRAPPERS:
                continue
            if id(call) in seen:  # the Assign wrapper already recorded it
                continue
            seen.add(id(call))
            wrapped = call.args[0] if call.args else None
            if wrapped is None:
                for kw in call.keywords:
                    if kw.arg in ("fun", "f"):
                        wrapped = kw.value
            if wrapped is None:
                continue
            self.jit_calls.append((call, wrapped, target))
            if target:
                self.jitted_callables.add(target)
            if isinstance(wrapped, ast.Name):
                self.jit_wrapped_names.add(wrapped.id)

    def is_jit_decorator(self, dec: ast.AST) -> bool:
        name = self.resolve(qualname(dec))
        if name in JIT_WRAPPERS:
            return True
        if isinstance(dec, ast.Call):
            inner = self.resolve(qualname(dec.func))
            if inner in JIT_WRAPPERS:
                return True
            # functools.partial(jax.jit, ...) as a decorator factory
            if inner in ("functools.partial", "partial") and dec.args:
                return self.resolve(qualname(dec.args[0])) in JIT_WRAPPERS
        return False

    def jit_decorator_call(self, fn: ast.AST) -> ast.Call | None:
        """The decorator Call carrying jit kwargs, when present."""
        for dec in getattr(fn, "decorator_list", []):
            if isinstance(dec, ast.Call) and self.is_jit_decorator(dec):
                return dec
        return None

    def _collect_traced_functions(self, tree: ast.Module) -> set[ast.AST]:
        """FunctionDefs that execute under jax tracing: jit-decorated,
        jit-wrapped by name, passed to a lax control-flow primitive, or
        lexically nested inside any of those."""
        hof_arg_names: set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and self.resolved_call(node) in TRACED_HOF):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        hof_arg_names.add(arg.id)

        traced: set[ast.AST] = set()

        def visit(node: ast.AST, inside: bool) -> None:
            here = inside
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                direct = (
                    node.name in self.jit_wrapped_names
                    or node.name in hof_arg_names
                    or any(self.is_jit_decorator(d)
                           for d in node.decorator_list)
                )
                here = inside or direct
                if here:
                    traced.add(node)
            elif isinstance(node, ast.Lambda) and inside:
                traced.add(node)
            for child in ast.iter_child_nodes(node):
                visit(child, here)

        visit(tree, False)
        return traced


# -- suppression ---------------------------------------------------------

def _suppressed_rules(lines: Sequence[str], line: int,
                      end_line: int | None = None) -> set[str]:
    """Rule codes/slugs disabled for the statement starting at ``line``
    (1-based): an end-of-line ``# jaxlint: disable=...`` comment on any
    line of the statement's span (``line``..``end_line`` — a wrapped
    call may carry the disable on its closing-paren line), or a
    COMMENT-ONLY preceding line (a trailing disable on the previous code
    line covers that line only — it must not leak onto the next one).
    Only the first word of each comma-separated token counts, so a
    trailing reason (``disable=IMP01 - entry script``) doesn't defeat
    the suppression."""

    def collect(text: str) -> None:
        m = _SUPPRESS_RE.search(text)
        if m:
            for token in m.group(1).split(","):
                words = token.strip().split()
                if words:
                    out.add(words[0].lower())

    out: set[str] = set()
    last = max(line, end_line or line)
    for n in range(line, last + 1):
        if 1 <= n <= len(lines):
            collect(lines[n - 1])
    prev = line - 2
    if 0 <= prev < len(lines) and lines[prev].lstrip().startswith("#"):
        collect(lines[prev])
    return out


def statement_end_line(node: ast.AST) -> int:
    """Last line of the LOGICAL statement a finding anchors to: the full
    node span for simple statements (a wrapped call's continuation lines
    belong to it), but only the header for compound statements — a
    disable inside a ``with``/``except`` BODY must not suppress a
    finding on the header."""
    line = getattr(node, "lineno", 1)
    end = getattr(node, "end_lineno", None) or line
    body = getattr(node, "body", None)
    if isinstance(body, list) and body:
        first = getattr(body[0], "lineno", None)
        if first is not None:
            end = max(line, first - 1)
    return end


def _is_suppressed(finding: Finding, lines: Sequence[str],
                   end_line: int | None = None) -> bool:
    disabled = _suppressed_rules(lines, finding.line, end_line)
    return bool(disabled & {"all", finding.rule.lower(),
                            finding.name.lower()})


# -- drivers -------------------------------------------------------------

def _default_rules() -> list[Rule]:
    from relayrl_tpu.analysis.rules import all_rules

    return all_rules()


def analyze_source(source: str, path: str = "<string>",
                   rules: Sequence[Rule] | None = None) -> list[Finding]:
    """Run the rules over one source string. Syntax errors surface as a
    single ``PARSE`` finding instead of an exception, so one broken file
    can't hide every other file's findings in a directory scan."""
    rules = list(rules) if rules is not None else _default_rules()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="PARSE", name="syntax-error", path=path,
                        line=e.lineno or 1, col=(e.offset or 0) + 1,
                        message=f"cannot parse: {e.msg}", snippet="")]
    module = ModuleInfo(path=path, source=source, tree=tree)
    findings: list[Finding] = []
    for rule in rules:
        for node, message in rule.check(module):
            f = rule.finding(module, node, message)
            if not _is_suppressed(f, module.lines,
                                  statement_end_line(node)):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_file(path: str | os.PathLike, display_path: str | None = None,
                 rules: Sequence[Rule] | None = None) -> list[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    shown = display_path if display_path is not None else str(path)
    return analyze_source(source, path=shown.replace(os.sep, "/"),
                          rules=rules)


# Directories that never hold first-party source: linting a checkout
# root must not descend into virtualenvs, build trees, or tool caches
# (thousands of third-party findings would drown the real ones).
_PRUNE_DIRS = frozenset({
    "__pycache__", "build", "dist", "node_modules",
    ".venv", "venv", "env", ".eggs",
})


def iter_python_files(root: str | os.PathLike) -> Iterator[str]:
    root = str(root)
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        # prune hidden dirs (.git, .tox, .mypy_cache, .claude, ...) and
        # the well-known non-source trees; an explicitly passed root is
        # unaffected (pruning applies to children only)
        dirnames[:] = sorted(
            d for d in dirnames
            if not d.startswith(".") and d not in _PRUNE_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


_REPO_MARKERS = (".git", "pyproject.toml", "setup.py")


def _enclosing_repo_root(path: str) -> str | None:
    """Nearest ancestor directory carrying a repo marker, or None."""
    cur = path if os.path.isdir(path) else os.path.dirname(path)
    while True:
        if any(os.path.exists(os.path.join(cur, m)) for m in _REPO_MARKERS):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def analyze_paths(paths: Sequence[str | os.PathLike],
                  rules: Sequence[Rule] | None = None) -> list[Finding]:
    """Scan files/directories. Baseline keys must come out identical no
    matter how — or from where — the same file is reached, so reported
    paths are anchored at the enclosing REPO root (nearest ancestor with
    a ``.git``/``pyproject.toml``/``setup.py`` marker): ``relayrl_tpu/``,
    ``.``, and ``tests/x.py`` all key ``tests/x.py`` whether the scan
    runs from the repo root or a subdirectory. Outside any repo, a root
    under the cwd anchors at the cwd, and anything else falls back to its
    own parent directory (stable across checkouts, though same-named
    loose files from different out-of-tree parents can collide — scan
    the directory if that matters)."""
    rules = list(rules) if rules is not None else _default_rules()
    findings: list[Finding] = []
    cwd = os.getcwd()
    for root in paths:
        root_abs = os.path.abspath(str(root))
        base = _enclosing_repo_root(root_abs)
        if base is None:
            if root_abs == cwd or root_abs.startswith(cwd + os.sep):
                base = cwd
            else:
                base = os.path.dirname(root_abs)
        for file in iter_python_files(root_abs):
            display = os.path.relpath(file, base)
            findings.extend(analyze_file(file, display_path=display,
                                         rules=rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# -- baseline ------------------------------------------------------------

def load_baseline(path: str | os.PathLike) -> dict[tuple[str, str, str], int]:
    """Baseline file -> multiset of finding keys ({key: count})."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out: dict[tuple[str, str, str], int] = {}
    for entry in data.get("findings", []):
        key = (str(entry["rule"]), str(entry["path"]),
               str(entry["snippet"]))
        out[key] = out.get(key, 0) + int(entry.get("count", 1))
    return out


def write_baseline(path: str | os.PathLike,
                   findings: Iterable[Finding]) -> None:
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": rule, "path": p, "snippet": snippet, "count": n}
        for (rule, p, snippet), n in sorted(counts.items())
    ]
    payload = {
        "version": 1,
        "tool": "jaxlint",
        "comment": ("Grandfathered findings. Entries are keyed by "
                    "(rule, path, stripped source line) so they survive "
                    "line-number churn; regenerate with --write-baseline "
                    "and keep this file shrinking."),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding],
    baseline: Mapping[tuple[str, str, str], int],
) -> tuple[list[Finding], int, list[tuple[str, str, str]]]:
    """Split findings into (new, matched_count, stale_keys).

    Each baseline entry absorbs up to ``count`` findings with the same
    key; the remainder are new. Keys present in the baseline but absent
    from the scan are stale — fixed code whose entry should be pruned.
    """
    remaining = dict(baseline)
    new: list[Finding] = []
    matched = 0
    for f in findings:
        k = f.key()
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            matched += 1
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0)
    return new, matched, stale
