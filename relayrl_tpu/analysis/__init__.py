"""relayrl_tpu.analysis — jaxlint + contracts, the static-analysis gate.

The reference prototype shipped with zero correctness tooling; this
framework's hot paths are exactly the JAX surface where silent hazards
(PRNG key reuse, host syncs under jit, retrace storms, un-donated update
buffers) degrade into throughput cliffs that benchmarks only catch after
the fact. jaxlint is the CI gate that catches them at review time.

The second engine — contracts — guards the cross-artifact agreements
the runtime rests on: metric registrations vs the observability
catalog, config defaults vs loader clamps vs the ops knob tables,
Python wire constants vs ``native/*.cc``, the cross-module lock graph,
and tests/ markers vs pytest.ini. Its machine-readable inventory is
committed as ``contracts.json`` next to ``baseline.json``.

Usage::

    python -m relayrl_tpu.analysis                 # jaxlint + contracts
    python -m relayrl_tpu.analysis --contracts     # contracts only
    python -m relayrl_tpu.analysis path/ --no-baseline
    python -m relayrl_tpu.analysis --list-rules

Suppress one line with ``# jaxlint: disable=JAX01`` (any line of the
statement, or the comment-only line above); grandfathered findings live
in ``baseline.json`` next to this file. See ``docs/static_analysis.md``
for both rule catalogs.

The analyzer itself is stdlib-only and never imports jax, so the gate
runs on accelerator-free CI hosts; importing it as a subpackage pulls
only the framework's lightweight types/config layer (numpy + msgpack).
"""

from relayrl_tpu.analysis.cli import main  # noqa: F401
from relayrl_tpu.analysis.contracts import (  # noqa: F401
    CONTRACT_RULES,
    ContractContext,
    run_contracts,
)
from relayrl_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Rule,
    analyze_file,
    analyze_paths,
    analyze_source,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from relayrl_tpu.analysis.rules import all_rules, rules_by_code  # noqa: F401

__all__ = [
    "Finding",
    "Rule",
    "analyze_source",
    "analyze_file",
    "analyze_paths",
    "load_baseline",
    "write_baseline",
    "apply_baseline",
    "all_rules",
    "rules_by_code",
    "CONTRACT_RULES",
    "ContractContext",
    "run_contracts",
    "main",
]
