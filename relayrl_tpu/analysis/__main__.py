"""``python -m relayrl_tpu.analysis`` — the jaxlint CLI entry point."""

import sys

from relayrl_tpu.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
