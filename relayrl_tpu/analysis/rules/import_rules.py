"""Import-hygiene rules.

``import relayrl_tpu.anything`` must stay side-effect free: actor
processes import types+config only (the lazy ``__getattr__`` in the
package root exists for exactly this), and a module-level backend query
binds the process to a device topology before the runtime has a chance
to configure it (hostpin.py documents the one sanctioned exception).
"""

from __future__ import annotations

import ast
from typing import Iterator

from relayrl_tpu.analysis.engine import ModuleInfo, Rule

_DEVICE_CALLS = frozenset({
    "jax.devices",
    "jax.local_devices",
    "jax.device_count",
    "jax.local_device_count",
    "jax.default_backend",
    "jax.config.update",
    "jax.distributed.initialize",
})

# Files whose whole job is import-time environment setup.
_EXEMPT_BASENAMES = frozenset({"__init__.py", "conftest.py"})


class ModuleLevelDeviceTouch(Rule):
    """``jax.devices()`` / ``jax.config.update`` at module scope runs at
    import time: it initializes the backend (grabbing the TPU for this
    process) or mutates global config for every importer. Both belong
    inside functions, called by whoever owns process setup."""

    code = "IMP01"
    name = "module-level-device-touch"
    description = ("module-scope jax.devices()/jax.config mutation "
                   "outside __init__")

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        basename = module.path.rsplit("/", 1)[-1]
        if basename in _EXEMPT_BASENAMES:
            return
        for node in self._module_scope_nodes(module.tree.body):
            if not isinstance(node, ast.Call):
                continue
            resolved = module.resolved_call(node)
            if resolved in _DEVICE_CALLS:
                yield node, (
                    f"`{resolved}` at module scope runs at import time — "
                    f"it initializes/binds the jax backend (or mutates "
                    f"global config) for every importer; move it inside "
                    f"a function on the process-setup path")

    def _module_scope_nodes(self, stmts) -> Iterator[ast.AST]:
        """Every node that executes at import time: the module body plus
        module-level if/try/with/for blocks and class bodies (a
        class-scope device default is the same hazard) — but nothing
        inside function or lambda bodies, which run later."""

        def walk(node: ast.AST) -> Iterator[ast.AST]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                yield child
                yield from walk(child)

        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield stmt
            yield from walk(stmt)


RULES = [ModuleLevelDeviceTouch]
