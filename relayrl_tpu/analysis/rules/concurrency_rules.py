"""Concurrency rules for the runtime/transport layers.

The server is a lock-coordinated thread fleet (ingest, staging, learner,
publish); the transports park threads in blocking socket calls. The two
hazards below are the ones that turn that design into stalls or
unkillable processes.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from relayrl_tpu.analysis.engine import (
    ModuleInfo,
    Rule,
    qualname,
    walk_skip_nested_functions,
)

_LOCK_NAME_RE = re.compile(r"(lock|mutex)", re.IGNORECASE)

# Attribute calls that park the calling thread regardless of receiver
# (socket/zmq receive & connect surfaces). `join`/`result` are NOT here:
# bare attribute names would also match `", ".join(...)` and
# `os.path.join(...)` — they only count on a receiver that looks like a
# thread/process/future (below).
_BLOCKING_ATTRS = frozenset({
    "recv", "recv_multipart", "recv_string", "recv_json", "recv_pyobj",
    "recv_into", "accept", "connect", "sendall",
})

# .join()/.result()/.wait_for() block only on these receiver shapes.
_BLOCKING_RECEIVER_ATTRS = frozenset({"join", "result"})
_BLOCKING_RECEIVER_RE = re.compile(
    r"(thread|proc|process|worker|listener|future|fut\b|task|call|pool)",
    re.IGNORECASE)

_BLOCKING_CALLS = frozenset({
    "time.sleep",
    "socket.create_connection",
    "urllib.request.urlopen",
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "subprocess.check_call",
})


class BlockingUnderLock(Rule):
    """A sleep or blocking I/O call inside ``with <lock>:`` holds every
    other thread hostage for the duration — the publish/ingest stall mode
    where one slow agent serializes the whole fleet."""

    code = "CONC01"
    name = "blocking-under-lock"
    description = ("time.sleep or blocking I/O while holding a "
                   "threading lock")

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_name = self._held_lock(node)
            if lock_name is None:
                continue
            for stmt in node.body:
                for inner in self._walk_stmt(stmt):
                    if not isinstance(inner, ast.Call):
                        continue
                    label = self._blocking_label(module, inner)
                    if label:
                        yield inner, (
                            f"`{label}` while holding `{lock_name}` — "
                            f"every thread contending for the lock stalls "
                            f"for the full blocking duration; move the "
                            f"blocking call outside the critical section "
                            f"or switch to a Condition wait")

    @staticmethod
    def _walk_stmt(stmt: ast.stmt) -> Iterator[ast.AST]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # defined under the lock, not executed under it
        yield stmt
        yield from walk_skip_nested_functions(stmt)

    @staticmethod
    def _held_lock(node: ast.With | ast.AsyncWith) -> str | None:
        for item in node.items:
            name = qualname(item.context_expr)
            if name and _LOCK_NAME_RE.search(name.split(".")[-1]):
                return name
        return None

    @staticmethod
    def _blocking_label(module: ModuleInfo, call: ast.Call) -> str | None:
        resolved = module.resolved_call(call)
        if resolved in _BLOCKING_CALLS:
            return resolved
        if resolved and resolved.startswith("requests."):
            return resolved
        if not isinstance(call.func, ast.Attribute):
            return None
        if isinstance(call.func.value, ast.Constant):
            return None  # ", ".join(...) and friends
        if call.func.attr in _BLOCKING_ATTRS:
            return f".{call.func.attr}()"
        if call.func.attr in _BLOCKING_RECEIVER_ATTRS:
            receiver = qualname(call.func.value) or ""
            if _BLOCKING_RECEIVER_RE.search(receiver):
                return f"{receiver}.{call.func.attr}()"
        return None


class BareExcept(Rule):
    """``except:`` also swallows KeyboardInterrupt and SystemExit — in a
    server accept/ingest loop that turns Ctrl-C into an unkillable
    process (the shutdown path the signal tests pin)."""

    code = "CONC02"
    name = "bare-except"
    description = "bare except: swallows KeyboardInterrupt/SystemExit"

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield node, (
                    "bare `except:` catches KeyboardInterrupt/SystemExit "
                    "and makes loops unkillable; catch `Exception` (or "
                    "narrower) instead")


RULES = [BlockingUnderLock, BareExcept]
