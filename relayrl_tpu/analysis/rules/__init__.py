"""jaxlint rule registry.

Rules are grouped by the layer they police:

* :mod:`jax_rules` — tracing/PRNG/dispatch hazards in jitted code
  (the throughput cliffs Podracer-class TPU RL stacks die on).
* :mod:`concurrency_rules` — runtime/transport thread hazards.
* :mod:`import_rules` — import-time side effects.
* :mod:`telemetry_rules` — metric-recording hazards (clock choice).

Adding a rule: subclass :class:`relayrl_tpu.analysis.engine.Rule` in the
right module, give it a unique ``code`` + ``name``, yield
``(ast_node, message)`` pairs from ``check``, append it to that module's
``RULES`` list, and add a positive + negative snippet to
``tests/test_jaxlint.py`` (the registry test enforces code uniqueness).
"""

from __future__ import annotations

from relayrl_tpu.analysis.engine import Rule
from relayrl_tpu.analysis.rules.concurrency_rules import RULES as _CONC
from relayrl_tpu.analysis.rules.import_rules import RULES as _IMP
from relayrl_tpu.analysis.rules.jax_rules import RULES as _JAX
from relayrl_tpu.analysis.rules.telemetry_rules import RULES as _TEL

__all__ = ["all_rules", "rules_by_code"]


def all_rules() -> list[Rule]:
    """Fresh instances of every registered rule, stable order."""
    return [cls() for cls in (*_JAX, *_CONC, *_IMP, *_TEL)]


def rules_by_code() -> dict[str, Rule]:
    out: dict[str, Rule] = {}
    for rule in all_rules():
        if rule.code in out:
            raise ValueError(f"duplicate rule code {rule.code}")
        out[rule.code] = rule
    return out
