"""JAX hazard rules: the silent-throughput-killer class.

Every rule here targets a failure mode that produces *wrong numbers or
slow programs without an exception*: reused PRNG keys correlate samples,
host syncs inside traced code serialize the dispatch pipeline, prints
inside jit fire once at trace time, untraceable args retrace per call,
missing donation doubles live buffers, and timing without
``block_until_ready`` measures dispatch latency instead of compute.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from relayrl_tpu.analysis.engine import (
    JIT_WRAPPERS,
    ModuleInfo,
    Rule,
    qualname,
    walk_skip_nested_functions as _walk_skip_nested_functions,
)

# jax.random calls that *produce* keys (assigning their result creates a
# fresh key; passing a key to them still consumes it).
_KEY_MAKERS = frozenset({
    "jax.random.PRNGKey",
    "jax.random.key",
    "jax.random.split",
    "jax.random.fold_in",
    "jax.random.clone",
})

_TIMING_CALLS = frozenset({
    "time.time", "time.time_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "time.monotonic", "time.monotonic_ns",
})


def _first_key_arg(call: ast.Call) -> str | None:
    """The PRNG key operand of a ``jax.random.*`` call: first positional
    arg, or the ``key=`` keyword — only when it is a bare Name (attribute
    keys live across methods; tracking them needs flow analysis a linter
    should not pretend to have)."""
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    for kw in call.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value.id
    return None


class PrngKeyReuse(Rule):
    """A PRNG key consumed by two ``jax.random.*`` calls yields
    *correlated* randomness — exploration noise that repeats, dropout
    masks equal to sampling masks. JAX never warns; the learning curve
    just quietly degrades."""

    code = "JAX01"
    name = "prng-key-reuse"
    description = ("PRNG key passed to more than one jax.random call "
                   "without an intervening split/fold_in")

    # Subtrees that bind their own names: consumption inside them must
    # not leak into the enclosing scope (two lambdas each taking `rng`,
    # or two comprehensions reusing the iteration variable `k`, are zero
    # reuse). Each is scanned as its own scope below.
    _OWN_SCOPE = (ast.Lambda, ast.ListComp, ast.SetComp, ast.DictComp,
                  ast.GeneratorExp)

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        scopes: list[ast.AST] = [module.tree]
        scopes += [n for n in ast.walk(module.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        reported: set[tuple[int, int, str]] = set()
        for scope in scopes:
            body = scope.body if hasattr(scope, "body") else []
            findings: list[tuple[ast.AST, str]] = []
            self._scan_block(module, body, {}, findings, reported)
            yield from findings
        # lambda/comprehension bodies, each as an isolated scope
        for node in ast.walk(module.tree):
            if isinstance(node, self._OWN_SCOPE):
                findings = []
                self._process_expr(module, node, {}, findings, reported,
                                   enter_scope=True)
                yield from findings

    # state: name -> ("alive", line) fresh key | ("used", line) consumed
    def _scan_block(self, module: ModuleInfo, stmts, state: dict,
                    findings: list, reported: set) -> dict:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, visited on its own
            if isinstance(stmt, ast.If):
                s1 = self._scan_block(module, stmt.body, dict(state),
                                      findings, reported)
                s2 = self._scan_block(module, stmt.orelse, dict(state),
                                      findings, reported)
                state = self._merge(s1, s2)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # Two passes: a consume-without-resplit inside a loop body
                # is a reuse across iterations the first pass can't see.
                inner = self._scan_block(module, stmt.body, dict(state),
                                         findings, reported)
                self._scan_block(module, stmt.body, dict(inner),
                                 findings, reported)
                state = self._merge(state, inner)
                state = self._scan_block(module, stmt.orelse, state,
                                         findings, reported)
            elif isinstance(stmt, ast.Try):
                state = self._scan_block(module, stmt.body, state,
                                         findings, reported)
                for h in stmt.handlers:
                    state = self._scan_block(module, h.body, state,
                                             findings, reported)
                state = self._scan_block(module, stmt.orelse, state,
                                         findings, reported)
                state = self._scan_block(module, stmt.finalbody, state,
                                         findings, reported)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._process_expr(module, item.context_expr, state,
                                       findings, reported)
                state = self._scan_block(module, stmt.body, state,
                                         findings, reported)
            else:
                self._process_stmt(module, stmt, state, findings, reported)
        return state

    @staticmethod
    def _merge(s1: dict, s2: dict) -> dict:
        out = {}
        for name in set(s1) | set(s2):
            v1, v2 = s1.get(name), s2.get(name)
            if v1 is None or v2 is None:
                continue  # dropped/opaque in one branch: be conservative
            used = [v for v in (v1, v2) if v[0] == "used"]
            out[name] = min(used) if used else v1
        return out

    def _walk_expr(self, node, top: bool = False):
        """Expression walk that stays in the current binding scope."""
        if not top and isinstance(node, self._OWN_SCOPE + (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        yield node
        for child in ast.iter_child_nodes(node):
            yield from self._walk_expr(child)

    def _process_expr(self, module, expr, state, findings, reported,
                      enter_scope: bool = False):
        calls = [n for n in self._walk_expr(expr, top=enter_scope)
                 if isinstance(n, ast.Call)]
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        for call in calls:
            resolved = module.resolved_call(call)
            if not resolved or not resolved.startswith("jax.random."):
                continue
            if resolved in ("jax.random.PRNGKey", "jax.random.key"):
                continue  # argument is an int seed, not a key
            key = _first_key_arg(call)
            if key is None:
                continue
            prior = state.get(key)
            if prior is not None and prior[0] == "used":
                mark = (call.lineno, call.col_offset, key)
                if mark not in reported:
                    reported.add(mark)
                    findings.append((call, (
                        f"PRNG key `{key}` is reused here (already "
                        f"consumed by a jax.random call on line "
                        f"{prior[1]}); derive fresh keys with "
                        f"`jax.random.split` — reuse silently correlates "
                        f"the two sample streams")))
            else:
                state[key] = ("used", call.lineno)

    def _process_stmt(self, module, stmt, state, findings, reported):
        self._process_expr(module, stmt, state, findings, reported)
        targets: list[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
            value = stmt.value
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
            value = stmt.value
        else:
            return
        fresh = (isinstance(value, ast.Call)
                 and module.resolved_call(value) in _KEY_MAKERS)
        for target in targets:
            elts = target.elts if isinstance(target, ast.Tuple) else [target]
            for el in elts:
                if isinstance(el, ast.Starred):
                    el = el.value
                if isinstance(el, ast.Name):
                    if fresh:
                        state[el.id] = ("alive", stmt.lineno)
                    else:
                        state.pop(el.id, None)


class HostSyncInJit(Rule):
    """Host<->device round-trips inside traced code either fail at trace
    time (``float()`` on a tracer) or — worse — silently pin the value to
    host numpy and fall out of the compiled program."""

    code = "JAX02"
    name = "host-sync-in-jit"
    description = ("host numpy / float() / .item() call inside a "
                   "jit-traced function")

    _CASTS = frozenset({"float", "int", "bool", "complex"})
    _SYNC_ATTRS = frozenset({"item", "tolist"})

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        seen: set[tuple[int, int]] = set()
        for fn in module.traced_functions:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                mark = (node.lineno, node.col_offset)
                if mark in seen:
                    continue
                msg = self._diagnose(module, node)
                if msg:
                    seen.add(mark)
                    yield node, msg

    def _diagnose(self, module: ModuleInfo, call: ast.Call) -> str | None:
        resolved = module.resolved_call(call)
        if resolved and (resolved.startswith("numpy.")
                         or resolved == "numpy"):
            return (f"host numpy call `{qualname(call.func)}` inside a "
                    f"traced function — use jax.numpy; host ops force a "
                    f"sync and fall out of the compiled program")
        # Only bare-Name cast arguments are flagged: `float(len(x))` and
        # `float(x.shape[0])` are trace-time statics (legal under jit),
        # and attribute args are usually static hyperparams — precision
        # over recall.
        if (resolved in self._CASTS and len(call.args) == 1
                and isinstance(call.args[0], ast.Name)):
            return (f"`{resolved}()` on a traced value forces a host "
                    f"sync (or a trace-time error) inside jit; keep the "
                    f"value on device or move the cast outside the "
                    f"traced function")
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in self._SYNC_ATTRS
                and not call.args):
            return (f"`.{call.func.attr}()` inside a traced function "
                    f"synchronizes host and device; compute on-device "
                    f"and convert outside the jit boundary")
        return None


class PrintInJit(Rule):
    """``print`` in traced code fires once, at trace time, with tracer
    reprs — not per step with values. ``jax.debug.print`` is the
    intended tool."""

    code = "JAX03"
    name = "print-in-jit"
    description = "python print() inside a jit-traced function"

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        seen: set[tuple[int, int]] = set()
        for fn in module.traced_functions:
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and module.resolved_call(node) == "print"
                        and (node.lineno, node.col_offset) not in seen):
                    seen.add((node.lineno, node.col_offset))
                    yield node, (
                        "print() inside a traced function executes once "
                        "at trace time with tracer values; use "
                        "jax.debug.print(...) for per-step output")


class UntraceableArgNoStatic(Rule):
    """A jitted function whose signature declares a value jax cannot
    trace (str/bytes/Callable) needs ``static_argnums``/
    ``static_argnames`` — otherwise every call raises, or retraces when
    smuggled through as a weak type."""

    code = "JAX04"
    name = "untraceable-arg-no-static"
    description = ("jit-wrapped function takes str/bytes/Callable "
                   "parameters without static_argnums/static_argnames")

    _UNTRACEABLE = frozenset({
        "str", "bytes", "Callable", "callable",
        "typing.Callable", "collections.abc.Callable",
    })

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        # A bare Name handed to jax.jit refers to a module-level (or
        # local) function — NOT a same-named method somewhere else in the
        # file. Prefer the module-level def; fall back to a name that is
        # unique across the module; skip ambiguous names entirely rather
        # than checking the wrong signature.
        top = {n.name: n for n in module.tree.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        by_name: dict[str, list] = {}
        for n in ast.walk(module.tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name.setdefault(n.name, []).append(n)
        defs = dict(top)
        for name, nodes in by_name.items():
            if name not in defs and len(nodes) == 1:
                defs[name] = nodes[0]
        for call, wrapped, _target in module.jit_calls:
            if not isinstance(wrapped, ast.Name):
                continue
            fn = defs.get(wrapped.id)
            if fn is None or self._has_static_kwarg(call):
                continue
            bad = self._untraceable_params(module, fn)
            if bad:
                yield call, self._message(wrapped.id, bad)
        for fn in defs.values():
            dec_call = module.jit_decorator_call(fn)
            plain_jit = any(module.is_jit_decorator(d)
                            and not isinstance(d, ast.Call)
                            for d in fn.decorator_list)
            if dec_call is None and not plain_jit:
                continue
            if dec_call is not None and self._has_static_kwarg(dec_call):
                continue
            bad = self._untraceable_params(module, fn)
            if bad:
                yield fn, self._message(fn.name, bad)

    @staticmethod
    def _has_static_kwarg(call: ast.Call) -> bool:
        names = {kw.arg for kw in call.keywords}
        return bool(names & {"static_argnums", "static_argnames"})

    def _untraceable_params(self, module: ModuleInfo, fn) -> list[str]:
        bad = []
        params = list(fn.args.posonlyargs) + list(fn.args.args) + list(
            fn.args.kwonlyargs)
        for p in params:
            if p.arg in ("self", "cls") or p.annotation is None:
                continue
            ann = p.annotation
            if isinstance(ann, ast.Subscript):
                ann = ann.value
            if module.resolve(qualname(ann)) in self._UNTRACEABLE:
                bad.append(p.arg)
        return bad

    @staticmethod
    def _message(fn_name: str, bad: list[str]) -> str:
        return (f"jit of `{fn_name}` takes untraceable parameter(s) "
                f"{', '.join(repr(b) for b in bad)} — mark them with "
                f"static_argnums/static_argnames or hoist them out of "
                f"the traced signature")


class MissingDonate(Rule):
    """Train-step/update functions carry the full optimizer + param state
    through every call; without ``donate_argnums`` XLA keeps input AND
    output buffers live across the update — on TPU that halves the
    largest fittable model."""

    code = "JAX05"
    name = "missing-donate"
    description = ("jit of a *train_step*/*update* function without "
                   "donate_argnums/donate_argnames")

    _NAME_RE = re.compile(r"(train_step|update)", re.IGNORECASE)

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        for call, wrapped, target in module.jit_calls:
            if self._has_donate(call):
                continue
            label = None
            if isinstance(wrapped, ast.Name) and self._NAME_RE.search(
                    wrapped.id):
                label = wrapped.id
            elif target and self._NAME_RE.search(target.split(".")[-1]):
                label = target
            if label:
                yield call, self._message(label)
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not self._NAME_RE.search(fn.name):
                continue
            dec_call = module.jit_decorator_call(fn)
            plain = any(module.is_jit_decorator(d)
                        and not isinstance(d, ast.Call)
                        for d in fn.decorator_list)
            if plain or (dec_call is not None
                         and not self._has_donate(dec_call)):
                yield fn, self._message(fn.name)

    @staticmethod
    def _has_donate(call: ast.Call) -> bool:
        names = {kw.arg for kw in call.keywords}
        return bool(names & {"donate_argnums", "donate_argnames"})

    @staticmethod
    def _message(label: str) -> str:
        return (f"jit of `{label}` has no donate_argnums — the old "
                f"state buffers stay live across the update, doubling "
                f"peak memory for the largest training state")


class UntimedJitDispatch(Rule):
    """Jitted calls return before the device finishes (async dispatch);
    a wall-clock pair around one measures *enqueue* latency. Every such
    measurement needs a ``block_until_ready`` before the second
    timestamp."""

    code = "JAX06"
    name = "untimed-jit-dispatch"
    description = ("jitted call timed with time.*() pairs but no "
                   "block_until_ready in the function")

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if self._has_block(module, fn):
                continue
            timings: list[tuple[int, int]] = []
            jit_calls: list[ast.Call] = []
            for node in _walk_skip_nested_functions(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = module.resolved_call(node)
                if resolved in _TIMING_CALLS:
                    timings.append((node.lineno, node.col_offset))
                elif self._is_jitted_dispatch(module, node):
                    jit_calls.append(node)
            if len(timings) < 2 or not jit_calls:
                continue
            first, last = min(timings), max(timings)
            for call in jit_calls:
                pos = (call.lineno, call.col_offset)
                if first < pos < last:
                    yield call, (
                        "jitted call timed without block_until_ready — "
                        "dispatch is async, so this measures enqueue "
                        "latency, not device compute; call "
                        "jax.block_until_ready(result) before the "
                        "closing timestamp")
                    break  # one report per function is enough

    @staticmethod
    def _has_block(module: ModuleInfo, fn: ast.AST) -> bool:
        """True when the function contains an explicit fence:
        ``block_until_ready``, or a ``float(...)`` / ``np.asarray(...)``
        host readback of a non-constant value — the documented
        alternative on platforms where block_until_ready returns at
        dispatch (see bench.py's host-fence note). Any such call anywhere
        in the function counts: this rule deliberately trades recall for
        precision (an incidental float() on host data will mask a real
        unfenced measurement, but a fence-looking call must never be
        flagged — suppression fatigue kills linters faster than missed
        findings do)."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and node.attr == (
                    "block_until_ready"):
                return True
            if isinstance(node, ast.Name) and node.id == "block_until_ready":
                return True
            if not (isinstance(node, ast.Call) and node.args
                    and not isinstance(node.args[0], ast.Constant)):
                continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "float"):
                return True
            if module.resolved_call(node) in ("numpy.asarray",
                                              "numpy.array"):
                return True
        return False

    @staticmethod
    def _is_jitted_dispatch(module: ModuleInfo, call: ast.Call) -> bool:
        target = qualname(call.func)
        if target and target in module.jitted_callables:
            return True
        # inline dispatch: jax.jit(f)(x)
        return (isinstance(call.func, ast.Call)
                and module.resolved_call(call.func) in JIT_WRAPPERS)


class DirectShardMapBinding(Rule):
    """``shard_map`` has lived at three addresses across JAX releases
    (``jax.experimental.shard_map.shard_map`` with ``check_rep``,
    ``jax.experimental.shard_map``, ``jax.shard_map`` with ``check_vma``)
    — binding any of them directly scatters the next rename across every
    mesh-program call site. :mod:`relayrl_tpu.parallel.compat` is the one
    sanctioned resolver: it probes the installed surface, normalizes the
    replication-check kwarg, and fails with the installed version in the
    message when JAX moves the API again."""

    code = "JAX07"
    name = "direct-shard-map-binding"
    description = ("jax.shard_map / jax.experimental.shard_map bound "
                   "outside parallel/compat.py")

    # The one module allowed to touch the raw surfaces.
    _SANCTIONED_SUFFIX = "parallel/compat.py"

    _TARGETS = frozenset({
        "jax.shard_map",
        "jax.experimental.shard_map",
        "jax.experimental.shard_map.shard_map",
    })

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        if module.path.replace("\\", "/").endswith(self._SANCTIONED_SUFFIX):
            return
        reported: set[tuple[int, int]] = set()
        for node in ast.walk(module.tree):
            hit: str | None = None
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in self._TARGETS:
                        hit = a.name
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    dotted = f"{node.module}.{a.name}"
                    if dotted in self._TARGETS or node.module in self._TARGETS:
                        hit = dotted
            elif isinstance(node, ast.Attribute):
                resolved = module.resolve(qualname(node))
                if resolved in self._TARGETS:
                    hit = resolved
            if hit is None:
                continue
            # An Attribute chain yields one node per segment, all sharing
            # the expression's start position — report each site once.
            pos = (node.lineno, node.col_offset)
            if pos in reported:
                continue
            reported.add(pos)
            yield node, (
                f"`{hit}` bound directly — the shard_map surface moves "
                f"between JAX releases (and renames check_rep/check_vma "
                f"with it); import it from relayrl_tpu.parallel.compat, "
                f"the one version-compat resolver")


RULES = [
    PrngKeyReuse,
    HostSyncInJit,
    PrintInJit,
    UntraceableArgNoStatic,
    MissingDonate,
    UntimedJitDispatch,
    DirectShardMapBinding,
]
