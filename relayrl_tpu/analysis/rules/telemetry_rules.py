"""Telemetry timing rules.

The skew-guard convention: wall clocks (``time.time()``) exist to be
*compared across hosts* — every latency/duration a single process
measures and records must come from the monotonic clock
(``time.monotonic()``/``time.perf_counter()``), because NTP steps the
wall clock backwards and forwards under load and a stepped wall clock
turns into negative or wildly inflated latencies on the dashboards.
"""

from __future__ import annotations

import ast
from typing import Iterator

from relayrl_tpu.analysis.engine import (
    ModuleInfo,
    Rule,
    qualname,
    walk_skip_nested_functions,
)

_WALL_CALLS = frozenset({"time.time"})
# The metric-recording surfaces a computed duration flows into.
_RECORD_ATTRS = frozenset({"observe", "set", "inc", "add"})


class WallClockLatency(Rule):
    """``time.time() - t0`` feeding a metric ``observe``/``set`` call:
    the interval is wrong whenever NTP steps the clock. Intervals must
    use ``time.monotonic()``; keep ``time.time()`` only for timestamps
    that cross host boundaries (where the skew guard compensates)."""

    code = "TEL01"
    name = "wall-clock-latency"
    description = ("time.time() interval recorded by telemetry — use "
                   "time.monotonic()")

    def check(self, module: ModuleInfo) -> Iterator[tuple[ast.AST, str]]:
        scopes: list[ast.AST] = [module.tree]
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node)
        for scope in scopes:
            yield from self._check_scope(module, scope)

    def _is_wall_call(self, module: ModuleInfo, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and module.resolved_call(node) in _WALL_CALLS)

    def _check_scope(self, module: ModuleInfo,
                     scope: ast.AST) -> Iterator[tuple[ast.AST, str]]:
        body = walk_skip_nested_functions(scope) \
            if not isinstance(scope, ast.Module) \
            else (n for stmt in scope.body
                  if not isinstance(stmt, (ast.FunctionDef,
                                           ast.AsyncFunctionDef,
                                           ast.ClassDef))
                  for n in (stmt, *walk_skip_nested_functions(stmt)))
        nodes = list(body)

        wall_names: set[str] = set()
        for node in nodes:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and self._is_wall_call(module, node.value)):
                target = qualname(node.targets[0])
                if target:
                    wall_names.add(target)

        def is_wall_operand(op: ast.AST) -> bool:
            if self._is_wall_call(module, op):
                return True
            name = qualname(op)
            return name is not None and name in wall_names

        # wall-clock interval expressions first, THEN the names they
        # land in — an Assign precedes its own BinOp child in walk
        # order, so a single combined pass would miss `dt = time.time()
        # - t0` every time
        wall_subs: dict[int, ast.BinOp] = {}
        for node in nodes:
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and (is_wall_operand(node.left)
                         or is_wall_operand(node.right))):
                wall_subs[id(node)] = node
        interval_names: dict[str, ast.BinOp] = {}
        for node in nodes:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1):
                for sub in ast.walk(node.value):
                    if id(sub) in wall_subs:
                        target = qualname(node.targets[0])
                        if target:
                            interval_names[target] = wall_subs[id(sub)]

        for node in nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _RECORD_ATTRS
                    and not isinstance(node.func.value, ast.Constant)):
                continue
            for arg in (*node.args, *(kw.value for kw in node.keywords)):
                anchor: ast.AST | None = None
                for sub in ast.walk(arg):
                    if id(sub) in wall_subs:
                        anchor = sub
                        break
                    name = qualname(sub)
                    if name is not None and name in interval_names:
                        anchor = interval_names[name]
                        break
                if anchor is not None:
                    yield anchor, (
                        f"wall-clock interval recorded via "
                        f"`.{node.func.attr}()` — time.time() steps "
                        f"under NTP; measure durations with "
                        f"time.monotonic() and keep wall clocks for "
                        f"cross-host timestamps only")
                    break


RULES = [WallClockLatency]
