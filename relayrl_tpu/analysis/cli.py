"""jaxlint + contracts command line.

    python -m relayrl_tpu.analysis [paths...] [options]

Two engines share one gate: jaxlint (per-line AST rules over the given
paths) and contracts (cross-artifact drift checks over the installed
package + repo artifacts). The bare default invocation runs BOTH and
any *new* finding fails the gate; ``--contracts`` runs the contract
engine alone, ``--no-contracts`` the linter alone. Explicit paths scan
with jaxlint only — the contract surfaces are package-wide, not
path-scoped — unless ``--contracts`` is also given.

Exit codes: 0 = clean (every finding baselined or none), 1 = new
findings, 2 = bad invocation. The default baseline is the committed
``relayrl_tpu/analysis/baseline.json``; the committed contract
inventory is ``relayrl_tpu/analysis/contracts.json`` (regenerate with
``--contracts --write-inventory``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

from relayrl_tpu.analysis.engine import (
    analyze_paths,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from relayrl_tpu.analysis.rules import all_rules

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")


def _default_scan_root() -> str:
    """The installed relayrl_tpu package — so a bare ``python -m
    relayrl_tpu.analysis`` lints the framework itself from any cwd."""
    import relayrl_tpu

    return os.path.dirname(os.path.abspath(relayrl_tpu.__file__))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m relayrl_tpu.analysis",
        description=("jaxlint + contracts: static analysis for "
                     "relayrl_tpu"),
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan with jaxlint "
                        "(default: the installed relayrl_tpu package)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline JSON of grandfathered findings "
                        f"(default: {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring any baseline")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline file "
                        "and exit 0 (requires an explicit --baseline "
                        "PATH — never overwrites the default silently)")
    p.add_argument("--contracts", action="store_true",
                   help="run only the contracts engine (cross-artifact "
                        "drift checks)")
    p.add_argument("--no-contracts", action="store_true",
                   help="run only jaxlint, skipping the contracts engine")
    p.add_argument("--inventory", default=None, metavar="FILE",
                   help="committed contract inventory to check against / "
                        "write (default: the packaged contracts.json)")
    p.add_argument("--write-inventory", action="store_true",
                   help="regenerate the contract inventory from the "
                        "current tree (to --inventory, default the "
                        "packaged contracts.json) and exit 0")
    p.add_argument("--select", default=None, metavar="CODES",
                   help="comma-separated rule codes to run (default all)")
    p.add_argument("--ignore", default=None, metavar="CODES",
                   help="comma-separated rule codes to skip")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--list-rules", action="store_true",
                   help="print both engines' rule catalogs and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def _pick_rules(select: str | None, ignore: str | None,
                contract_codes: frozenset[str]):
    """jaxlint rule objects plus the (selected, ignored) contract-code
    filters; unknown codes across BOTH engines' catalogs exit 2."""
    rules = all_rules()
    lint_codes = {r.code for r in rules}
    selected_contracts: set[str] | None = None
    if select:
        wanted = {c.strip().upper() for c in select.split(",") if c.strip()}
        unknown = wanted - lint_codes - contract_codes
        if unknown:
            raise SystemExit(
                f"unknown rule code(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.code in wanted]
        selected_contracts = wanted & contract_codes
    ignored: set[str] = set()
    if ignore:
        ignored = {c.strip().upper() for c in ignore.split(",") if c.strip()}
        rules = [r for r in rules if r.code not in ignored]
    return rules, selected_contracts, ignored


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from relayrl_tpu.analysis.contracts import (
        CONTRACT_CODES,
        CONTRACT_RULES,
        DEFAULT_INVENTORY,
        run_contracts,
        write_inventory,
    )

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.code}  {rule.name}: {rule.description}")
        for code, name, description in CONTRACT_RULES:
            print(f"{code}  {name}: {description}")
        return 0

    if args.contracts and args.no_contracts:
        print("--contracts and --no-contracts are mutually exclusive",
              file=sys.stderr)
        return 2

    try:
        rules, selected_contracts, ignored = _pick_rules(
            args.select, args.ignore, CONTRACT_CODES)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    run_lint = not args.contracts
    # contract surfaces are package-wide: the engine runs on the bare
    # default invocation and on an explicit --contracts, not when the
    # caller aimed jaxlint at specific paths
    run_contract_engine = not args.no_contracts and (
        args.contracts or not args.paths)

    paths = args.paths or [_default_scan_root()]
    if run_lint:
        for path in paths:
            if not os.path.exists(path):
                print(f"no such path: {path}", file=sys.stderr)
                return 2

    findings = []
    if run_lint:
        findings.extend(analyze_paths(paths, rules=rules))

    inventory_path = args.inventory or DEFAULT_INVENTORY
    if run_contract_engine:
        contract_findings, inventory_doc = run_contracts(
            inventory_path=args.inventory,
            check_inventory=not args.write_inventory)
        if args.write_inventory:
            write_inventory(inventory_path, inventory_doc)
            if not args.quiet:
                print(f"contracts: wrote inventory to {inventory_path}")
            return 0
        for f in contract_findings:
            if selected_contracts is not None \
                    and f.rule not in selected_contracts:
                continue
            if f.rule in ignored:
                continue
            findings.append(f)
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    elif args.write_inventory:
        print("--write-inventory requires the contracts engine "
              "(drop --no-contracts / path arguments or pass "
              "--contracts)", file=sys.stderr)
        return 2

    baseline_path = args.baseline or DEFAULT_BASELINE
    if args.write_baseline:
        if args.baseline is None:
            # Any scan (bare default included) sees only its own slice of
            # the gate's coverage; writing it to the shared default
            # baseline would silently drop grandfathered entries from
            # every path outside this scan. Rewriting the committed
            # baseline must name it explicitly.
            print("--write-baseline requires an explicit --baseline PATH "
                  "(refusing to overwrite the shared default baseline "
                  "with this scan's findings)", file=sys.stderr)
            return 2
        write_baseline(baseline_path, findings)
        if not args.quiet:
            print(f"baseline: wrote {len(findings)} finding(s) to "
                  f"{baseline_path}")
        return 0

    baseline = {}
    if not args.no_baseline and os.path.isfile(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, OSError, KeyError, TypeError) as e:
            # exit 2 = bad invocation; 1 is reserved for "new findings"
            print(f"cannot read baseline {baseline_path}: {e!r} — fix or "
                  f"regenerate it with --write-baseline", file=sys.stderr)
            return 2
    new, matched, stale = apply_baseline(findings, baseline)

    if args.format == "json":
        print(json.dumps({
            "new": [vars(f) for f in new],
            "baselined": matched,
            "stale_baseline_entries": [list(k) for k in stale],
        }, indent=2))
    else:
        for f in new:
            print(f.format())
        if not args.quiet:
            for rule, path, snippet in stale:
                print(f"note: stale baseline entry {rule} @ {path} "
                      f"({snippet[:60]!r}) — fixed code, prune it with "
                      f"--write-baseline")
            engines = []
            if run_lint:
                engines.append(f"{len(rules)} jaxlint rule(s)")
            if run_contract_engine:
                engines.append("contracts")
            print(f"jaxlint: {len(new)} new finding(s), {matched} "
                  f"baselined, {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'}, "
                  f"{' + '.join(engines)} active")
    return 1 if new else 0
