"""Config contract: defaults vs loader clamps vs read sites vs docs.

The same knob is written down in up to four places — the
``DEFAULT_CONFIG`` literal, a hardcoded fallback in the loader's
``get_*_params`` clamp, the read site that consumes it, and the
operations.md / observability.md knob tables. Each pair can drift
silently; this pass folds all four out of the AST/markdown and
cross-checks:

* CFG01 — a config key read (loader clamp or section-dict read site)
  that has no shipped default: a typo'd knob or one users can't
  discover from the default config.
* CFG02 — a shipped default whose key name appears nowhere in the
  package: a dead knob nothing will ever read.
* CFG03 — the loader's hardcoded fallback disagrees with the shipped
  default (the two-defaults bug class: behavior depends on whether the
  section is present in the user's file).
* CFG04 — a doc knob-table default disagrees with the shipped default.
* CFG05 — an operational knob with no knob-table row in the docs.
* CFG06 — a documented knob that does not exist in the defaults.
"""

from __future__ import annotations

import ast
import os

from relayrl_tpu.analysis.contracts.base import (
    ContractContext,
    ParsedModule,
    code_spans,
    const_fold,
    iter_md_tables,
    walk_functions,
)
from relayrl_tpu.analysis.engine import Finding, qualname

# Sections whose knobs the operations/observability knob tables own.
DOC_SECTIONS = frozenset({"actor", "transport", "guardrails", "serving",
                          "relay", "rlhf", "telemetry", "learner"})
# The open-ended algorithms section is exempt everywhere: hyperparams
# are a per-plugin namespace, not a closed contract.
_OPEN_SECTIONS = frozenset({"algorithms"})

_GETTER_SECTION = {
    "get_actor_params": "actor",
    "get_transport_params": "transport",
    "get_guardrails_params": "guardrails",
    "get_serving_params": "serving",
    "get_relay_params": "relay",
    "get_rlhf_params": "rlhf",
    "get_telemetry_params": "telemetry",
    "get_learner_params": "learner",
    "get_tb_params": "training_tensorboard",
    "get_max_traj_length": "",
    "get_grpc_idle_timeout_s": "",
}

_UNPARSED = object()

KNOB_DOCS = ("operations.md", "observability.md")


# -- defaults ------------------------------------------------------------

def extract_defaults(ctx: ContractContext) -> tuple[
        dict[str, object], ParsedModule | None, int]:
    """Flatten the ``DEFAULT_CONFIG`` literal to dotted leaf keys
    (``guardrails.strike_threshold``; ``_comment*`` keys and the
    open-ended algorithms section excluded)."""
    mod = ctx.module(os.path.join("config", "default_config.py"))
    if mod is None:
        return {}, None, 1
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target, value_node = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value_node = node.target.id, node.value
        else:
            continue
        if target == "DEFAULT_CONFIG":
            ok, value = const_fold(value_node)
            if not ok or not isinstance(value, dict):
                return {}, mod, node.lineno
            flat: dict[str, object] = {}

            def descend(prefix: str, obj: object) -> None:
                if isinstance(obj, dict):
                    for k, v in obj.items():
                        if str(k).startswith("_comment"):
                            continue
                        descend(f"{prefix}.{k}" if prefix else str(k), v)
                else:
                    flat[prefix] = obj

            for key, val in value.items():
                if str(key).startswith("_comment") or key in _OPEN_SECTIONS:
                    continue
                descend(str(key), val)
            return flat, mod, node.lineno
    return {}, mod, 1


# -- loader clamps -------------------------------------------------------

class Clamp:
    def __init__(self, section: str, key: str, default: object,
                 node: ast.AST):
        self.section = section
        self.key = key
        self.default = default
        self.node = node

    @property
    def dotted(self) -> str:
        return f"{self.section}.{self.key}" if self.section else self.key


def _get_call_clamp(node: ast.Call) -> tuple[str, object] | None:
    """``params.get("key", default)`` (const default) -> (key, default)."""
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)):
        return None
    receiver = qualname(node.func.value) or ""
    if receiver.split(".")[-1] not in ("params", "_raw"):
        return None
    key = node.args[0].value
    if len(node.args) >= 2:
        ok, default = const_fold(node.args[1])
        return (key, default if ok else _UNPARSED)
    return (key, _UNPARSED)


def extract_clamps(ctx: ContractContext) -> tuple[list[Clamp],
                                                  ParsedModule | None]:
    """Hardcoded fallbacks in config/loader.py: ``params.get(key,
    default)`` / ``params.get(key) or default`` call sites and the
    ``for key, default[, lo] in ((...), ...)`` clamp tables, attributed
    to their getter's section; plus the ``_FALLBACK_ENDPOINTS`` ports."""
    mod = ctx.module(os.path.join("config", "loader.py"))
    if mod is None:
        return [], None
    clamps: list[Clamp] = []
    for cls, func in walk_functions(mod.tree):
        section = _GETTER_SECTION.get(func.name)
        if cls != "ConfigLoader" or section is None:
            continue
        for node in ast.walk(func):
            # the clamp-table idiom: for key, default[, lo] in ((...),)
            if (isinstance(node, ast.For)
                    and isinstance(node.target, ast.Tuple)
                    and isinstance(node.iter, (ast.Tuple, ast.List))):
                names = [t.id for t in node.target.elts
                         if isinstance(t, ast.Name)]
                if len(names) < 2 or names[0] != "key" \
                        or names[1] != "default":
                    continue
                for entry in node.iter.elts:
                    ok, row = const_fold(entry)
                    if ok and isinstance(row, tuple) and len(row) >= 2 \
                            and isinstance(row[0], str):
                        clamps.append(Clamp(section, row[0], row[1],
                                            entry))
            elif isinstance(node, ast.BoolOp) and isinstance(node.op,
                                                             ast.Or):
                # params.get("key") or default
                first = node.values[0]
                if isinstance(first, ast.Call):
                    got = _get_call_clamp(first)
                    if got and got[1] is _UNPARSED:
                        ok, default = const_fold(node.values[-1])
                        if ok:
                            clamps.append(Clamp(section, got[0], default,
                                                first))
            elif isinstance(node, ast.Call):
                got = _get_call_clamp(node)
                if got and got[1] is not _UNPARSED:
                    clamps.append(Clamp(section, got[0], got[1], node))
    # endpoint fallbacks: _FALLBACK_ENDPOINTS = {"name": Endpoint(port=..)}
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_FALLBACK_ENDPOINTS"
                and isinstance(node.value, ast.Dict)):
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant)
                        and isinstance(v, ast.Call)):
                    continue
                for kw in v.keywords:
                    if kw.arg == "port":
                        ok, port = const_fold(kw.value)
                        if ok:
                            clamps.append(Clamp(
                                "server", f"{k.value}.port", port, v))
    return clamps, mod


# -- read sites ----------------------------------------------------------

class ReadSite:
    def __init__(self, section: str, key: str, has_default: bool,
                 module: ParsedModule, node: ast.AST):
        self.section = section
        self.key = key
        self.has_default = has_default
        self.module = module
        self.node = node


_GETTER_NAMES = {name: sec for name, sec in _GETTER_SECTION.items()
                 if sec and name.endswith("_params")}


def extract_read_sites(ctx: ContractContext) -> list[ReadSite]:
    """Reads of keys on section dicts obtained from ``get_*_params()``:
    both local variables (``p = cfg.get_serving_params(); p["x"]``) and
    instance attributes assigned anywhere in the same class."""
    sites: list[ReadSite] = []
    for mod in ctx.package_modules():
        if mod.relpath.endswith("config/loader.py"):
            continue  # the loader's own reads are the clamp extraction
        for node in ast.iter_child_nodes(mod.tree):
            if isinstance(node, ast.ClassDef):
                sites.extend(_class_sites(mod, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sites.extend(_function_sites(mod, node, {}))
    return sites


def _getter_section_of(call: ast.Call) -> str | None:
    if isinstance(call.func, ast.Attribute):
        return _GETTER_NAMES.get(call.func.attr)
    return None


def _class_sites(mod: ParsedModule, cls: ast.ClassDef) -> list[ReadSite]:
    attr_sections: dict[str, str] = {}
    for node in ast.walk(cls):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)):
            section = _getter_section_of(node.value)
            target = qualname(node.targets[0])
            if section and target and target.startswith("self."):
                attr_sections[target] = section
    out: list[ReadSite] = []
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.extend(_function_sites(mod, item, attr_sections))
    return out


def _function_sites(mod: ParsedModule, func: ast.AST,
                    outer: dict[str, str]) -> list[ReadSite]:
    env = dict(outer)
    for node in ast.walk(func):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.value, ast.Call)):
            section = _getter_section_of(node.value)
            target = qualname(node.targets[0])
            if section and target:
                env[target] = section
    out: list[ReadSite] = []
    for node in ast.walk(func):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            receiver = qualname(node.value)
            if receiver in env:
                out.append(ReadSite(env[receiver], node.slice.value,
                                    False, mod, node))
        elif isinstance(node, ast.Call):
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            receiver = qualname(node.func.value)
            if receiver in env:
                out.append(ReadSite(env[receiver], node.args[0].value,
                                    len(node.args) >= 2, mod, node))
    return out


# -- docs ----------------------------------------------------------------

_HEADING_SECTIONS = (
    ("guardrail", "guardrails"),
    ("serving", "serving"),
    ("relay", "relay"),
    ("rlhf", "rlhf"),
    ("telemetry", "telemetry"),
    ("observab", "telemetry"),
    ("model distribution", "transport"),
    ("wire", "transport"),
    ("transport", "transport"),
    ("learner", "learner"),
    ("actor", "actor"),
)


def _heading_section(heading: str) -> str | None:
    low = heading.lower()
    for needle, section in _HEADING_SECTIONS:
        if needle in low:
            return section
    return None


def parse_doc_value(text: str):
    """A knob table's default cell -> python value, or _UNPARSED for
    prose the comparison should skip."""
    raw = text.strip().strip("`").strip()
    raw = raw.split(" (")[0].strip().strip("`").strip()
    if not raw or " " in raw:
        return _UNPARSED
    low = raw.lower()
    if low in ("null", "none"):
        return None
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "\"'":
        return raw[1:-1]
    return raw


class DocKnob:
    def __init__(self, dotted: str, value: object, doc_path: str,
                 line: int):
        self.dotted = dotted
        self.value = value
        self.doc_path = doc_path
        self.line = line


def extract_doc_knobs(ctx: ContractContext) -> list[DocKnob]:
    knobs: list[DocKnob] = []
    if ctx.docs_root is None:
        return knobs
    for doc in KNOB_DOCS:
        path = os.path.join(ctx.docs_root, doc)
        text = ctx.read_text(path)
        if text is None:
            continue
        rel = ctx.rel(path)
        for heading, header, rows in iter_md_tables(text):
            if not header or header[0].lower() not in ("knob", "key"):
                continue
            section = _heading_section(heading)
            for line_no, cells in rows:
                if len(cells) < 2:
                    continue
                names = code_spans(cells[0])
                defaults = [c.strip() for c in cells[1].split(" / ")] \
                    if len(names) > 1 else [cells[1]]
                for i, name in enumerate(names):
                    if "." not in name and section is None:
                        continue
                    dotted = name if "." in name else f"{section}.{name}"
                    cell = defaults[i] if i < len(defaults) else ""
                    knobs.append(DocKnob(dotted, parse_doc_value(cell),
                                         rel, line_no))
    return knobs


# -- value comparison ----------------------------------------------------

def _values_agree(doc: object, actual: object) -> bool:
    if doc is _UNPARSED:
        return True
    if isinstance(actual, bool) or isinstance(doc, bool):
        return doc is actual
    if isinstance(doc, (int, float)) and isinstance(actual, (int, float)):
        return float(doc) == float(actual)
    return doc == actual


def _fmt(value: object) -> str:
    return "null" if value is None else repr(value)


# -- the pass ------------------------------------------------------------

def run(ctx: ContractContext) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []

    def add(code: str, name: str, message: str, **kw) -> None:
        f = ctx.finding(code, name, message, **kw)
        if f is not None:
            findings.append(f)

    defaults, defaults_mod, _line = extract_defaults(ctx)
    if not defaults:
        return [], {}
    clamps, loader_mod = extract_clamps(ctx)
    read_sites = extract_read_sites(ctx)

    # CFG01/CFG03 against the loader's clamps
    depth1 = {k for k in defaults}
    for clamp in clamps:
        dotted = clamp.dotted
        if dotted not in defaults:
            # a clamp for a whole sub-dict (e.g. retry) is not a leaf
            if any(k.startswith(dotted + ".") for k in defaults):
                continue
            add("CFG01", "config-read-no-default",
                f"loader falls back for `{dotted}` but default_config.py "
                f"ships no such key — users cannot discover this knob "
                f"from the default config",
                module=loader_mod, node=clamp.node)
        elif clamp.default is not _UNPARSED \
                and not _values_agree(clamp.default, defaults[dotted]):
            add("CFG03", "config-clamp-drift",
                f"loader hardcodes {_fmt(clamp.default)} for `{dotted}` "
                f"but default_config.py ships {_fmt(defaults[dotted])} — "
                f"behavior now depends on whether the user's file has the "
                f"section at all",
                module=loader_mod, node=clamp.node)

    # CFG01 against package read sites on section dicts
    for site in read_sites:
        dotted = f"{site.section}.{site.key}"
        if dotted in depth1:
            continue
        if any(k.startswith(dotted + ".") for k in defaults):
            continue
        how = ("with an inline fallback" if site.has_default
               else "with no fallback")
        add("CFG01", "config-read-no-default",
            f"`{site.section}` section key `{site.key}` is read here "
            f"{how} but default_config.py ships no such key",
            module=site.module, node=site.node)

    # CFG02: dead knobs — the key name appears nowhere in the package
    referenced: set[str] = set()
    for mod in ctx.package_modules():
        if mod is defaults_mod:
            continue
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                            str):
                referenced.add(node.value)
    for dotted in sorted(defaults):
        leaf = dotted.split(".")[-1]
        if leaf in referenced or dotted in referenced:
            continue
        add("CFG02", "config-dead-knob",
            f"default config ships `{dotted}` but the key name appears "
            f"nowhere in the package — a knob nothing reads",
            module=defaults_mod,
            node=_default_key_node(defaults_mod, leaf) or defaults_mod.tree)

    # docs: CFG04 / CFG05 / CFG06
    doc_knobs = extract_doc_knobs(ctx)
    documented: set[str] = set()
    if doc_knobs:
        for knob in doc_knobs:
            documented.add(knob.dotted)
            if knob.dotted not in defaults:
                if any(k.startswith(knob.dotted + ".")
                       for k in defaults):
                    continue
                add("CFG06", "config-doc-unknown-knob",
                    f"docs document knob `{knob.dotted}` but "
                    f"default_config.py ships no such key",
                    path=knob.doc_path, line=knob.line,
                    snippet=knob.dotted)
            elif not _values_agree(knob.value, defaults[knob.dotted]):
                add("CFG04", "config-doc-drift",
                    f"docs say `{knob.dotted}` defaults to "
                    f"{_fmt(knob.value)} but default_config.py ships "
                    f"{_fmt(defaults[knob.dotted])}",
                    path=knob.doc_path, line=knob.line,
                    snippet=knob.dotted)
        for dotted in sorted(defaults):
            parts = dotted.split(".")
            if parts[0] not in DOC_SECTIONS or len(parts) != 2:
                continue  # nested sub-policies document with the consumer
            if dotted not in documented:
                add("CFG05", "config-undocumented-knob",
                    f"operational knob `{dotted}` has no knob-table row "
                    f"in docs/operations.md or docs/observability.md",
                    module=defaults_mod,
                    node=_default_key_node(defaults_mod, parts[1])
                    or defaults_mod.tree)

    inventory = {
        "defaults": {k: _jsonable(v) for k, v in sorted(defaults.items())},
        "clamps": {c.dotted: _jsonable(c.default) for c in clamps
                   if c.default is not _UNPARSED},
        "documented_knobs": sorted(documented),
    }
    return findings, inventory


def _default_key_node(mod: ParsedModule | None, leaf: str) -> ast.AST | None:
    """The literal key node inside DEFAULT_CONFIG, for a precise anchor."""
    if mod is None:
        return None
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and node.value == leaf:
            return node
    return None


def _jsonable(value: object) -> object:
    if isinstance(value, tuple):
        return [_jsonable(v) for v in value]
    if isinstance(value, list):
        return [_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)
