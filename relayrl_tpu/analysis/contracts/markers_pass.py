"""Pytest-marker mini-contract: tests/ vs pytest.ini, both directions.

The tier-1 gate selects suites with ``-m`` marker expressions; a marker
used in a test file but never registered is silently ignored by that
selection (and warns under ``--strict-markers``), while a registered
marker no test carries is a dead selector in CI configs.

* PYT01 — ``@pytest.mark.X`` used in tests/ but ``X`` is not registered
  in pytest.ini's ``markers =`` section.
* PYT02 — a marker registered in pytest.ini that no test file uses.

Skips cleanly when tests/ or pytest.ini is absent (installed wheel).
"""

from __future__ import annotations

import ast
import os
import re

from relayrl_tpu.analysis.contracts.base import ContractContext
from relayrl_tpu.analysis.engine import (
    Finding,
    _suppressed_rules,
    iter_python_files,
    qualname,
    statement_end_line,
)

# pytest's own markers: always registered, never in pytest.ini.
_BUILTIN_MARKERS = frozenset({
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "tryfirst", "trylast",
})

_MARKER_LINE_RE = re.compile(r"^\s+([A-Za-z_][A-Za-z0-9_]*)\s*:")


def parse_registered_markers(ctx: ContractContext) -> dict[str, int]:
    """``{marker: 1-based line}`` from pytest.ini's ``markers=`` block."""
    if ctx.pytest_ini is None:
        return {}
    text = ctx.read_text(ctx.pytest_ini)
    if text is None:
        return {}
    markers: dict[str, int] = {}
    in_block = False
    for i, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if re.match(r"^markers\s*=", stripped):
            in_block = True
            continue
        if in_block:
            m = _MARKER_LINE_RE.match(line)
            if m:
                markers.setdefault(m.group(1), i)
            elif stripped and not line[:1].isspace():
                in_block = False
    return markers


def extract_used_markers(ctx: ContractContext) -> dict[
        str, list[tuple[str, list[str], ast.AST]]]:
    """``{marker: [(relpath, source_lines, node), ...]}`` for every
    ``pytest.mark.X`` attribute in tests/ (decorators, ``pytestmark``
    assignments, inline ``request.applymarker`` — any attribute walk)."""
    used: dict[str, list[tuple[str, list[str], ast.AST]]] = {}
    if ctx.tests_root is None:
        return used
    for path in iter_python_files(ctx.tests_root):
        source = ctx.read_text(path)
        if source is None:
            continue
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        lines = source.splitlines()
        rel = ctx.rel(path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            q = qualname(node) or ""
            parts = q.split(".")
            if len(parts) >= 3 and parts[-2] == "mark" \
                    and parts[-3] == "pytest":
                used.setdefault(parts[-1], []).append((rel, lines, node))
    return used


def run(ctx: ContractContext) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []
    registered = parse_registered_markers(ctx)
    used = extract_used_markers(ctx)
    if not registered and not used:
        return [], {}

    ini_rel = ctx.rel(ctx.pytest_ini) if ctx.pytest_ini else "pytest.ini"
    for marker in sorted(used):
        if marker in _BUILTIN_MARKERS or marker in registered:
            continue
        rel, lines, node = min(
            used[marker],
            key=lambda s: (s[0], getattr(s[2], "lineno", 1)))
        line = getattr(node, "lineno", 1)
        disabled = _suppressed_rules(lines, line,
                                     statement_end_line(node))
        if disabled & {"all", "pyt01", "marker-unregistered"}:
            continue
        snippet = lines[line - 1].strip() if 1 <= line <= len(lines) \
            else ""
        findings.append(Finding(
            rule="PYT01", name="marker-unregistered", path=rel,
            line=line, col=1,
            message=(f"marker `{marker}` is used in tests but not "
                     f"registered in pytest.ini — `-m {marker}` "
                     f"selections silently match nothing under strict "
                     f"marker configs"),
            snippet=snippet))
    for marker in sorted(registered):
        if marker in used:
            continue
        findings.append(Finding(
            rule="PYT02", name="marker-unused", path=ini_rel,
            line=registered[marker], col=1,
            message=(f"pytest.ini registers marker `{marker}` but no "
                     f"test carries it — a dead selector in CI "
                     f"configs"),
            snippet=marker))

    inventory = {
        "registered": sorted(registered),
        "used": sorted(used),
    }
    return findings, inventory
