"""The contracts engine: cross-artifact drift checks.

jaxlint answers "is this line of code wrong"; contracts answers "do the
artifacts still agree" — metric registrations vs the observability
catalog, config defaults vs loader clamps vs the ops knob tables,
Python wire constants vs ``native/*.cc``, the cross-module lock graph,
and tests/ markers vs pytest.ini. :func:`run_contracts` runs every
pass, returns jaxlint-shaped :class:`Finding` objects (same baseline,
same ``# jaxlint: disable=`` suppressions), and the merged machine-
readable inventory whose committed copy (``contracts.json``) anchors
CON01 drift detection.
"""

from __future__ import annotations

import os

from relayrl_tpu.analysis.contracts import (
    concurrency_pass,
    config_pass,
    markers_pass,
    telemetry_pass,
    wire_pass,
)
from relayrl_tpu.analysis.contracts.base import (
    ContractContext,
    sorted_findings,
)
from relayrl_tpu.analysis.contracts.inventory import (
    DEFAULT_INVENTORY,
    diff_inventory,
    load_inventory,
    merge_inventory,
    serialize_inventory,
    write_inventory,
)
from relayrl_tpu.analysis.engine import Finding

__all__ = [
    "CONTRACT_RULES",
    "ContractContext",
    "DEFAULT_INVENTORY",
    "run_contracts",
    "serialize_inventory",
    "write_inventory",
]

# (code, name, one-line description) — the --list-rules catalog and the
# --select/--ignore universe for the contracts half.
CONTRACT_RULES: list[tuple[str, str, str]] = [
    ("MET01", "metric-prefix",
     "metric name lacks the relayrl_ namespace prefix"),
    ("MET02", "counter-suffix", "counter not named *_total"),
    ("MET03", "histogram-unit-suffix",
     "histogram without a unit suffix (_seconds/_bytes/...)"),
    ("MET04", "metric-family-collision",
     "one metric name registered with two kinds or bucket grids"),
    ("MET05", "metric-undocumented",
     "registered metric missing from docs/observability.md"),
    ("MET06", "metric-documented-gone",
     "documented metric with no registration site"),
    ("MET07", "metric-doc-kind-drift",
     "metric kind in code disagrees with the docs"),
    ("EVT01", "event-unregistered",
     "journal event emitted but missing from EVENT_TYPES"),
    ("EVT02", "event-undocumented",
     "EVENT_TYPES entry missing from the docs event table"),
    ("EVT03", "event-documented-gone",
     "documented event not in EVENT_TYPES"),
    ("CFG01", "config-read-no-default",
     "config key read with no shipped default"),
    ("CFG02", "config-dead-knob",
     "shipped default whose key nothing reads"),
    ("CFG03", "config-clamp-drift",
     "loader fallback disagrees with the shipped default"),
    ("CFG04", "config-doc-drift",
     "doc knob table disagrees with the shipped default"),
    ("CFG05", "config-undocumented-knob",
     "operational knob with no doc knob-table row"),
    ("CFG06", "config-doc-unknown-knob",
     "documented knob that does not exist in the defaults"),
    ("WIRE01", "wire-parity-mismatch",
     "wire constant disagrees between python and native"),
    ("WIRE02", "wire-symbol-missing",
     "a parity pair's symbol is no longer extractable"),
    ("LOCK01", "lock-order-cycle",
     "two locks acquired in both orders (potential deadlock)"),
    ("LOCK02", "blocking-under-lock-transitive",
     "call under lock reaches a blocking op through callees"),
    ("THR01", "thread-never-joined",
     "thread neither daemonized nor joined"),
    ("PYT01", "marker-unregistered",
     "pytest marker used but not registered in pytest.ini"),
    ("PYT02", "marker-unused",
     "pytest.ini marker no test carries"),
    ("CON01", "contracts-inventory-drift",
     "committed contracts.json disagrees with a fresh extraction"),
]

CONTRACT_CODES = frozenset(code for code, _n, _d in CONTRACT_RULES)

_PASSES = (
    ("telemetry", telemetry_pass.run),
    ("config", config_pass.run),
    ("wire", wire_pass.run),
    ("concurrency", concurrency_pass.run),
    ("markers", markers_pass.run),
)


def run_contracts(ctx: ContractContext | None = None,
                  inventory_path: str | None = None,
                  check_inventory: bool = True,
                  ) -> tuple[list[Finding], dict]:
    """Run every contract pass. Returns ``(findings, inventory_doc)``.

    When ``check_inventory`` is true and a committed inventory exists
    at ``inventory_path`` (default: the packaged ``contracts.json``),
    CON01 compares it against the fresh extraction — but only when the
    run has full repo context (docs + native + tests + pytest.ini and
    no root overrides), so wheels and fixture-scoped test runs don't
    flag spurious drift.
    """
    if ctx is None:
        ctx = ContractContext()
    findings: list[Finding] = []
    sections: dict[str, dict] = {}
    for name, pass_run in _PASSES:
        pass_findings, inventory = pass_run(ctx)
        findings.extend(pass_findings)
        sections[name] = inventory
    doc = merge_inventory(sections)

    if check_inventory and _full_context(ctx):
        path = inventory_path or DEFAULT_INVENTORY
        if os.path.exists(path):
            committed = load_inventory(path)
            if committed is None:
                findings.append(Finding(
                    rule="CON01", name="contracts-inventory-drift",
                    path=ctx.rel(path), line=1, col=1,
                    message="committed contracts inventory is not "
                            "valid JSON — regenerate it with "
                            "--write-inventory",
                    snippet=""))
            else:
                diffs = diff_inventory(committed, doc)
                if diffs:
                    findings.append(Finding(
                        rule="CON01", name="contracts-inventory-drift",
                        path=ctx.rel(path), line=1, col=1,
                        message=("committed contracts inventory "
                                 "disagrees with a fresh extraction ("
                                 + "; ".join(diffs)
                                 + ") — a contract changed without the "
                                 "inventory; regenerate with "
                                 "--write-inventory and review the "
                                 "diff"),
                        snippet=""))
    return sorted_findings(findings), doc


def _full_context(ctx: ContractContext) -> bool:
    return all(root is not None for root in (
        ctx.repo_root, ctx.docs_root, ctx.native_root, ctx.tests_root,
        ctx.pytest_ini))
