"""Telemetry contract: metric registrations + journal event kinds.

Extracts every ``counter/gauge/gauge_fn/histogram`` registration site
and every journal ``emit`` kind from the package AST, then checks

* naming conventions — ``relayrl_`` prefix (MET01), ``_total`` on
  counters (MET02), a unit suffix on histograms (MET03);
* family coherence — one name registered with two kinds or two bucket
  grids is a scrape-time collision (MET04);
* the docs/observability.md catalog, two ways — undocumented metric
  (MET05), documented-but-gone metric (MET06), kind drift (MET07);
* the event vocabulary — emitted kind missing from ``EVENT_TYPES``
  (EVT01), ``EVENT_TYPES`` entry undocumented (EVT02), documented
  event gone from the vocabulary (EVT03).

The convention checks run everywhere; the doc half degrades to a no-op
when docs/ is absent (installed wheel).
"""

from __future__ import annotations

import ast
import os
import re

from relayrl_tpu.analysis.contracts.base import (
    ContractContext,
    ParsedModule,
    code_spans,
    const_fold,
    first_str,
    iter_md_tables,
)
from relayrl_tpu.analysis.engine import Finding, qualname

_METRIC_FACTORIES = frozenset({"counter", "gauge", "gauge_fn", "histogram"})
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# Histogram unit suffixes: base units per the prometheus convention,
# plus the repo's own dimensioned units (model versions).
_HISTOGRAM_UNITS = ("_seconds", "_bytes", "_ratio", "_versions")
_KIND_CATEGORY = {"counter": "counter", "gauge": "gauge",
                  "gauge_fn": "gauge", "histogram": "histogram"}

OBSERVABILITY_MD = "observability.md"


class MetricSite:
    def __init__(self, name: str, kind: str, module: ParsedModule,
                 node: ast.Call, buckets: str | None):
        self.name = name
        self.kind = kind
        self.module = module
        self.node = node
        self.buckets = buckets


def _bucket_spec(call: ast.Call) -> str | None:
    """Stable string for a histogram's bucket grid: the preset's dotted
    name, or the folded literal, or ``None`` for the default grid."""
    for kw in call.keywords:
        if kw.arg == "buckets":
            name = qualname(kw.value)
            if name:
                return name.split(".")[-1]
            ok, value = const_fold(kw.value)
            return repr(value) if ok else ast.dump(kw.value)
    return None


def extract_metrics(ctx: ContractContext) -> list[MetricSite]:
    sites: list[MetricSite] = []
    for mod in ctx.package_modules():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_FACTORIES):
                continue
            name = first_str(node)
            if name is None:
                continue
            sites.append(MetricSite(name, node.func.attr, mod, node,
                                    _bucket_spec(node)))
    sites.sort(key=lambda s: (s.name, s.module.relpath, s.node.lineno))
    return sites


def extract_event_types(ctx: ContractContext) -> tuple[
        list[str], ParsedModule | None, ast.Assign | None]:
    mod = ctx.module(os.path.join("telemetry", "events.py"))
    if mod is None:
        return [], None, None
    for node in mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "EVENT_TYPES"):
            ok, value = const_fold(node.value)
            if ok and isinstance(value, tuple):
                return [str(v) for v in value], mod, node
    return [], mod, None


def extract_emit_sites(ctx: ContractContext) -> list[
        tuple[str, ParsedModule, ast.Call]]:
    """Call sites of the journal emit surface with a literal kind:
    ``telemetry.emit(...)`` (the package-level helper) and
    ``<...journal...>.emit(...)``."""
    out: list[tuple[str, ParsedModule, ast.Call]] = []
    for mod in ctx.package_modules():
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"):
                continue
            receiver = qualname(node.func.value) or ""
            resolved = mod.info.resolve(receiver) or receiver
            if not (resolved.endswith("telemetry")
                    or "journal" in receiver.lower()):
                continue
            kind = first_str(node)
            if kind is not None:
                out.append((kind, mod, node))
    return out


def _doc_metric_names(cell: str, known: set[str],
                      prev: list[str]) -> list[tuple[str, str]]:
    """Expand a doc cell's code spans to full metric names. A span may
    be a continuation shorthand (``_send_bytes_total`` after
    ``relayrl_transport_send_total``): expand against the longest
    ``_``-prefix of the previous full name that yields a known metric.
    Returns ``(as_written, full_name)`` pairs."""
    out: list[tuple[str, str]] = []
    for span in code_spans(cell):
        name = span.split("{")[0].strip()
        if not name or " " in name:
            continue
        if name.startswith("relayrl_"):
            out.append((span, name))
            prev.append(name)
            continue
        if name.startswith("_") and prev:
            base = prev[-1].split("_")
            for cut in range(len(base) - 1, 0, -1):
                candidate = "_".join(base[:cut]) + name
                if candidate in known:
                    out.append((span, candidate))
                    prev.append(candidate)
                    break
            else:
                out.append((span, name))  # unresolvable shorthand
    return out


def parse_doc_catalog(ctx: ContractContext, known: set[str]) -> tuple[
        dict[str, tuple[str, int]], dict[str, int], str | None]:
    """The observability.md catalog: ``{metric: (kind, line)}`` from
    every ``| metric | kind | ... |`` table and ``{event: line}`` from
    the ``| event | ... |`` table."""
    if ctx.docs_root is None:
        return {}, {}, None
    path = os.path.join(ctx.docs_root, OBSERVABILITY_MD)
    text = ctx.read_text(path)
    if text is None:
        return {}, {}, None
    metrics: dict[str, tuple[str, int]] = {}
    events: dict[str, int] = {}
    for _heading, header, rows in iter_md_tables(text):
        head0 = header[0].lower() if header else ""
        if head0 == "metric" and len(header) >= 2:
            prev: list[str] = []
            for line_no, cells in rows:
                if len(cells) < 2:
                    continue
                kind_words = cells[1].lower().split()
                kind = next((w for w in kind_words if w in
                             ("counter", "gauge", "histogram")), "")
                for _span, name in _doc_metric_names(cells[0], known, prev):
                    metrics.setdefault(name, (kind, line_no))
        elif head0 == "event":
            for line_no, cells in rows:
                for span in code_spans(cells[0]):
                    if re.match(r"^[a-z][a-z0-9_]*$", span):
                        events.setdefault(span, line_no)
    return metrics, events, ctx.rel(path)


def run(ctx: ContractContext) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []

    def add(code: str, name: str, message: str, **kw) -> None:
        f = ctx.finding(code, name, message, **kw)
        if f is not None:
            findings.append(f)

    sites = extract_metrics(ctx)
    families: dict[str, MetricSite] = {}
    for s in sites:
        if not s.name.startswith("relayrl_"):
            add("MET01", "metric-prefix",
                f"metric `{s.name}` lacks the `relayrl_` namespace prefix "
                f"every scrape consumer filters on",
                module=s.module, node=s.node)
        elif not _NAME_RE.match(s.name):
            add("MET01", "metric-prefix",
                f"metric `{s.name}` is not a lower_snake_case metric name",
                module=s.module, node=s.node)
        if s.kind == "counter" and not s.name.endswith("_total"):
            add("MET02", "counter-suffix",
                f"counter `{s.name}` must end in `_total` (the monotonic-"
                f"family convention rate() consumers rely on)",
                module=s.module, node=s.node)
        if (s.kind == "histogram"
                and not s.name.endswith(_HISTOGRAM_UNITS)):
            add("MET03", "histogram-unit-suffix",
                f"histogram `{s.name}` carries no unit suffix "
                f"({'/'.join(_HISTOGRAM_UNITS)}) — dashboards can't tell "
                f"what the buckets measure",
                module=s.module, node=s.node)
        prior = families.get(s.name)
        if prior is None:
            families[s.name] = s
        else:
            if _KIND_CATEGORY[prior.kind] != _KIND_CATEGORY[s.kind]:
                add("MET04", "metric-family-collision",
                    f"metric `{s.name}` is registered as {s.kind} here but "
                    f"as {prior.kind} at {prior.module.relpath}:"
                    f"{prior.node.lineno} — one family, one kind",
                    module=s.module, node=s.node)
            elif (s.kind == "histogram"
                    and prior.buckets != s.buckets):
                add("MET04", "metric-family-collision",
                    f"histogram `{s.name}` uses bucket grid "
                    f"{s.buckets or 'default'} here but "
                    f"{prior.buckets or 'default'} at "
                    f"{prior.module.relpath}:{prior.node.lineno} — merged "
                    f"snapshots would mix incomparable grids",
                    module=s.module, node=s.node)

    event_types, events_mod, types_node = extract_event_types(ctx)
    emit_sites = extract_emit_sites(ctx)
    event_set = set(event_types)
    for kind, mod, node in emit_sites:
        # the events module itself only defines/forwards the vocabulary
        if events_mod is not None and mod is events_mod:
            continue
        if kind not in event_set:
            add("EVT01", "event-unregistered",
                f"journal event `{kind}` is emitted here but missing from "
                f"telemetry/events.py EVENT_TYPES — the closed vocabulary "
                f"docs and dashboards consume",
                module=mod, node=node)

    doc_metrics, doc_events, doc_path = parse_doc_catalog(
        ctx, set(families))
    if doc_path is not None:
        for name in sorted(families):
            s = families[name]
            if name not in doc_metrics:
                add("MET05", "metric-undocumented",
                    f"metric `{name}` ({s.kind}) is registered here but "
                    f"missing from docs/observability.md's catalog",
                    module=s.module, node=s.node)
            else:
                doc_kind, doc_line = doc_metrics[name]
                if doc_kind and doc_kind != _KIND_CATEGORY[s.kind]:
                    add("MET07", "metric-doc-kind-drift",
                        f"metric `{name}` is a {_KIND_CATEGORY[s.kind]} in "
                        f"code but documented as {doc_kind} "
                        f"({doc_path}:{doc_line})",
                        module=s.module, node=s.node)
        for name in sorted(doc_metrics):
            if name not in families:
                _kind, line = doc_metrics[name]
                add("MET06", "metric-documented-gone",
                    f"docs/observability.md documents `{name}` but no "
                    f"registration site exists — stale docs or a renamed "
                    f"metric", path=doc_path, line=line,
                    snippet=name)
        for kind in event_types:
            if kind not in doc_events and events_mod is not None \
                    and types_node is not None:
                f = ctx.finding(
                    "EVT02", "event-undocumented",
                    f"journal event `{kind}` is in EVENT_TYPES but missing "
                    f"from docs/observability.md's event table",
                    path=events_mod.relpath, line=types_node.lineno,
                    snippet=kind)
                if f is not None:
                    findings.append(f)
        for kind in sorted(doc_events):
            if kind not in event_set:
                add("EVT03", "event-documented-gone",
                    f"docs/observability.md's event table documents "
                    f"`{kind}` but it is not in EVENT_TYPES",
                    path=doc_path, line=doc_events[kind], snippet=kind)

    inventory = {
        "metrics": {
            name: {
                "kind": s.kind,
                "sites": sorted({x.module.relpath for x in sites
                                 if x.name == name}),
                **({"buckets": s.buckets} if s.buckets else {}),
            }
            for name, s in families.items()
        },
        "events": sorted(event_set),
        "emitted_event_kinds": sorted({k for k, _m, _n in emit_sites}),
    }
    return findings, inventory
