"""Wire/ABI parity: Python framing constants vs ``native/*.cc`` literals.

The codec is implemented twice — ``types/columnar.py`` / ``types/
tensor.py`` on the Python side and ``native/codec.cc`` + the transport
shims on the C++ side — and the two only interoperate while every
magic, version id, kind byte, dtype tag, and header layout agrees.
This pass folds the Python constants out of the AST and scrapes the
same literals out of the native sources (nothing is hardcoded in the
checker: mutate a byte in either artifact and the check fails), then
asserts pairwise equality:

* WIRE01 — a value disagrees between the two sides (or a Python-side
  self-consistency pair disagrees, e.g. ``MAGIC_BYTES`` vs the folded
  little-endian ``_BLOB_MAGIC``).
* WIRE02 — a symbol one side of a parity pair relies on cannot be
  extracted any more (renamed/deleted): the check would silently stop
  checking, so the disappearance is itself a finding.

Python-only constants with no native twin (``RLW2``/``RLS1``/``RLB1``
magics, nack codes, heartbeat codes) are inventoried so the committed
``contracts.json`` pins them, and their 4-byte-ascii shape is checked.

When ``native/`` is absent (installed wheel), the native half degrades
to inventory-only.
"""

from __future__ import annotations

import ast
import os
import re
import struct

from relayrl_tpu.analysis.contracts.base import (
    ContractContext,
    ParsedModule,
    const_fold,
)
from relayrl_tpu.analysis.engine import Finding, qualname

NATIVE_SOURCES = ("codec.cc", "transport.cc", "grpc_server.cc",
                  "event_hub.h")

_STRUCT_TO_NATIVE = {"u8": "B", "u16": "H", "u32": "I", "u64": "Q"}


# -- python side ---------------------------------------------------------

class PyConst:
    def __init__(self, value: object, module: ParsedModule,
                 node: ast.AST):
        self.value = value
        self.module = module
        self.node = node


def module_constants(mod: ParsedModule) -> dict[str, PyConst]:
    """Module- and class-level constant assignments, including tuple
    unpacking (``_HB_ALIVE, _HB_SLOW, _HB_DEAD = 0, 1, 2``) and
    ``struct.Struct("<fmt")`` (recorded as the format string)."""
    out: dict[str, PyConst] = {}
    scopes: list[list[ast.stmt]] = [mod.tree.body]
    scopes.extend(n.body for n in mod.tree.body
                  if isinstance(n, ast.ClassDef))
    for body in scopes:
        for node in body:
            if not isinstance(node, ast.Assign):
                continue
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                name = node.targets[0].id
                value = node.value
                if (isinstance(value, ast.Call)
                        and (qualname(value.func) or "").endswith("Struct")
                        and value.args
                        and isinstance(value.args[0], ast.Constant)
                        and isinstance(value.args[0].value, str)):
                    out[name] = PyConst(value.args[0].value, mod, node)
                    continue
                ok, folded = const_fold(value)
                if ok:
                    out[name] = PyConst(folded, mod, node)
            elif (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Tuple)
                    and isinstance(node.value, ast.Tuple)):
                names = node.targets[0].elts
                values = node.value.elts
                if len(names) != len(values):
                    continue
                for tgt, val in zip(names, values):
                    if isinstance(tgt, ast.Name):
                        ok, folded = const_fold(val)
                        if ok:
                            out[tgt.id] = PyConst(folded, mod, node)
    return out


def extract_dtype_tags(ctx: ContractContext) -> tuple[
        dict[int, str], ParsedModule | None, ast.AST | None]:
    """The ``DType`` IntEnum: tag value -> member name."""
    mod = ctx.module(os.path.join("types", "dtypes.py"))
    if mod is None:
        return {}, None, None
    for node in mod.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "DType":
            tags: dict[int, str] = {}
            for item in node.body:
                if (isinstance(item, ast.Assign)
                        and len(item.targets) == 1
                        and isinstance(item.targets[0], ast.Name)):
                    ok, value = const_fold(item.value)
                    if ok and isinstance(value, int):
                        tags[value] = item.targets[0].id
            return tags, mod, node
    return {}, mod, None


def _python_itemsizes(tags: dict[int, str]) -> dict[int, int]:
    """Per-tag numpy itemsize via the dtypes module's own mapping.
    Importing types/dtypes.py is the one exception to the no-import
    rule: it is a leaf module (stdlib + numpy) and the itemsize truth
    lives in numpy, not in any literal we could fold. Degrades to {}
    when numpy/ml_dtypes is unavailable on the analysis host."""
    try:
        from relayrl_tpu.types import dtypes as _dt

        return {tag: int(_dt.itemsize(_dt.DType(tag))) for tag in tags}
    except Exception:
        return {}


# -- native side ---------------------------------------------------------

class NativeText:
    def __init__(self, relpath: str, text: str):
        self.relpath = relpath
        self.text = text
        self.lines = text.splitlines()

    def line_of(self, pattern: str) -> int:
        rx = re.compile(pattern)
        for i, line in enumerate(self.lines, start=1):
            if rx.search(line):
                return i
        return 1


def load_native(ctx: ContractContext) -> dict[str, NativeText]:
    out: dict[str, NativeText] = {}
    if ctx.native_root is None:
        return out
    for name in NATIVE_SOURCES:
        path = os.path.join(ctx.native_root, name)
        text = ctx.read_text(path)
        if text is not None:
            out[name] = NativeText(ctx.rel(path), text)
    return out


def scrape_int(native: NativeText, pattern: str) -> tuple[int, int] | None:
    """First regex capture as an int (hex or decimal) plus its 1-based
    line number."""
    rx = re.compile(pattern)
    for i, line in enumerate(native.lines, start=1):
        m = rx.search(line)
        if m:
            return int(m.group(1), 0), i
    return None


def scrape_case_table(native: NativeText,
                      func_name: str) -> dict[int, int]:
    """``case N: return M;`` rows inside one function body."""
    body = _function_body(native, func_name)
    return {int(m.group(1)): int(m.group(2))
            for m in re.finditer(r"case\s+(\d+)\s*:\s*return\s+(\d+)\s*;",
                                 body)}


def _function_body(native: NativeText, func_name: str) -> str:
    start = None
    for i, line in enumerate(native.lines):
        if func_name in line and "(" in line:
            start = i
            break
    if start is None:
        return ""
    depth = 0
    out: list[str] = []
    for line in native.lines[start:]:
        out.append(line)
        depth += line.count("{") - line.count("}")
        if depth <= 0 and "{" in "".join(out):
            break
    return "\n".join(out)


def scrape_writer_layout(native: NativeText, func_name: str) -> str:
    """A ``BlobWriter`` function's fixed-header field sequence as a
    little-endian struct format (``w.u32(..) w.u8(..)`` -> ``<IB``;
    stops at the first variable-length ``raw(id, ..)``). ``raw(&v, 2)``
    of a u16 lvalue counts as ``H``."""
    body = _function_body(native, func_name)
    fmt = ""
    for m in re.finditer(
            r"w\.(u8|u16|u32|u64)\(|w\.raw\(\s*&\w+\s*,\s*(\d+)\s*\)"
            r"|w\.raw\(", body):
        if m.group(1):
            fmt += _STRUCT_TO_NATIVE[m.group(1)]
        elif m.group(2):
            fmt += {1: "B", 2: "H", 4: "I", 8: "Q"}[int(m.group(2))]
        else:
            break  # variable-length payload: fixed header ends here
    return "<" + fmt


def scrape_call_args(native: NativeText,
                     call: str) -> list[tuple[int, int]]:
    """Every ``call(N, ...)`` site with a literal first argument ->
    ``(value, line)`` (the definition ``call(int type`` never matches)."""
    rx = re.compile(re.escape(call) + r"\(\s*(\d+)\s*,")
    return [(int(m.group(1)), i)
            for i, line in enumerate(native.lines, start=1)
            for m in [rx.search(line)] if m]


# -- the pass ------------------------------------------------------------

def run(ctx: ContractContext) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []

    def add(code: str, name: str, message: str, **kw) -> None:
        f = ctx.finding(code, name, message, **kw)
        if f is not None:
            findings.append(f)

    mods = {
        "columnar": ctx.module(os.path.join("types", "columnar.py")),
        "tensor": ctx.module(os.path.join("types", "tensor.py")),
        "modelwire": ctx.module(os.path.join("transport", "modelwire.py")),
        "tbase": ctx.module(os.path.join("transport", "base.py")),
        "aggregate": ctx.module(os.path.join("telemetry", "aggregate.py")),
        "bindings": ctx.module(os.path.join("transport",
                                            "native_bindings.py")),
    }
    consts = {key: (module_constants(m) if m is not None else {})
              for key, m in mods.items()}

    def need(modkey: str, name: str) -> PyConst | None:
        got = consts[modkey].get(name)
        if got is None and mods[modkey] is not None:
            add("WIRE02", "wire-symbol-missing",
                f"expected constant `{name}` is no longer extractable "
                f"from {mods[modkey].relpath} — the parity check went "
                f"blind on it",
                path=mods[modkey].relpath, line=1, snippet=name)
        return got

    # -- python self-consistency pairs ----------------------------------
    blob_magic = need("columnar", "_BLOB_MAGIC")
    magic_bytes = need("columnar", "MAGIC_BYTES")
    if blob_magic and magic_bytes \
            and isinstance(blob_magic.value, int) \
            and isinstance(magic_bytes.value, bytes):
        if struct.pack("<I", blob_magic.value) != magic_bytes.value:
            add("WIRE01", "wire-parity-mismatch",
                f"columnar MAGIC_BYTES {magic_bytes.value!r} is not the "
                f"little-endian encoding of _BLOB_MAGIC "
                f"{blob_magic.value:#x}",
                module=magic_bytes.module, node=magic_bytes.node)

    for modkey, name in (("columnar", "MAGIC_BYTES"),
                         ("modelwire", "MAGIC"),
                         ("tbase", "BATCH_MAGIC"),
                         ("aggregate", "SNAP_MAGIC")):
        c = need(modkey, name)
        if c and (not isinstance(c.value, bytes) or len(c.value) != 4
                  or not c.value.isascii()):
            add("WIRE01", "wire-parity-mismatch",
                f"{name} {c.value!r} must be exactly 4 ascii bytes — "
                f"every peer sniffs frames on a 4-byte magic prefix",
                module=c.module, node=c.node)

    # -- native parity ---------------------------------------------------
    native = load_native(ctx)
    codec = native.get("codec.cc")
    inventory_native: dict[str, object] = {}

    def native_int(src: NativeText | None, symbol: str,
                   pattern: str) -> tuple[int, int] | None:
        if src is None:
            return None
        got = scrape_int(src, pattern)
        if got is None:
            add("WIRE02", "wire-symbol-missing",
                f"`{symbol}` is no longer extractable from {src.relpath} "
                f"— the parity check went blind on it",
                path=src.relpath, line=1, snippet=symbol)
        return got

    def parity(py: PyConst | None, native_got: tuple[int, int] | None,
               src: NativeText, what: str) -> None:
        if py is None or native_got is None:
            return
        value, line = native_got
        if py.value != value:
            add("WIRE01", "wire-parity-mismatch",
                f"{what}: python side has {py.value!r} but "
                f"{src.relpath}:{line} has {value:#x} ({value}) — the "
                f"two codecs no longer interoperate",
                module=py.module, node=py.node)

    if codec is not None:
        k_blob = native_int(codec, "kBlobMagic",
                            r"kBlobMagic\s*=\s*(0x[0-9A-Fa-f]+|\d+)")
        parity(blob_magic, k_blob, codec, "blob magic (RLD1)")
        if k_blob:
            inventory_native["kBlobMagic"] = k_blob[0]

        k_tensor = native_int(codec, "kTensorMagic",
                              r"kTensorMagic\s*=\s*(0x[0-9A-Fa-f]+|\d+)")
        parity(need("tensor", "_MAGIC"), k_tensor, codec,
               "tensor frame magic")
        if k_tensor:
            inventory_native["kTensorMagic"] = k_tensor[0]

        n_version = native_int(codec, "tensor version check",
                               r"buf\[2\]\s*!=\s*(\d+)")
        parity(need("tensor", "_VERSION"), n_version, codec,
               "tensor frame version")

        # raw-blob kind bytes: `is_envelope ? 3 : 1`
        kinds = scrape_int(codec, r"is_envelope\s*\?\s*(\d+)")
        plain = scrape_int(codec, r"is_envelope\s*\?\s*\d+\s*:\s*(\d+)")
        if kinds is None or plain is None:
            add("WIRE02", "wire-symbol-missing",
                "write_raw_blob's `is_envelope ? K : K` kind bytes are "
                f"no longer extractable from {codec.relpath}",
                path=codec.relpath, line=1, snippet="is_envelope")
        else:
            parity(need("columnar", "KIND_RAW_ENVELOPE"), kinds, codec,
                   "raw-envelope blob kind byte")
            parity(need("columnar", "KIND_RAW"), plain, codec,
                   "raw blob kind byte")

        # blob header layout: u32 magic | u8 kind | u32 id_len
        hdr = need("columnar", "_HDR")
        layout = scrape_writer_layout(codec, "write_blob_header")
        if hdr is not None:
            if layout == "<":
                add("WIRE02", "wire-symbol-missing",
                    f"write_blob_header's field sequence is no longer "
                    f"extractable from {codec.relpath}",
                    path=codec.relpath, line=1,
                    snippet="write_blob_header")
            elif hdr.value != layout:
                add("WIRE01", "wire-parity-mismatch",
                    f"blob header layout: python _HDR is "
                    f"{hdr.value!r} but {codec.relpath}'s "
                    f"write_blob_header emits {layout!r}",
                    module=hdr.module, node=hdr.node)

        # tensor frame header: u32 frame length, then the _HEADER fields
        theader = need("tensor", "_HEADER")
        tlayout = scrape_writer_layout(codec, "write_tensor_frame")
        if theader is not None and tlayout.startswith("<I"):
            tlayout = "<" + tlayout[2:]  # drop the frame-length prefix
            if tlayout[:len(str(theader.value))] != theader.value:
                add("WIRE01", "wire-parity-mismatch",
                    f"tensor header layout: python _HEADER is "
                    f"{theader.value!r} but {codec.relpath}'s "
                    f"write_tensor_frame emits {tlayout!r} after the "
                    f"frame-length prefix",
                    module=theader.module, node=theader.node)

        # dtype tag -> itemsize table
        tags, dtypes_mod, dtypes_node = extract_dtype_tags(ctx)
        table = scrape_case_table(codec, "dtype_itemsize")
        if not table:
            add("WIRE02", "wire-symbol-missing",
                f"dtype_itemsize's case table is no longer extractable "
                f"from {codec.relpath}",
                path=codec.relpath, line=1, snippet="dtype_itemsize")
        elif tags and dtypes_mod is not None:
            for tag in sorted(set(tags) - set(table)):
                add("WIRE01", "wire-parity-mismatch",
                    f"dtype tag {tag} ({tags[tag]}) has no itemsize row "
                    f"in {codec.relpath}'s dtype_itemsize — native peers "
                    f"reject frames python emits",
                    module=dtypes_mod, node=dtypes_node)
            for tag in sorted(set(table) - set(tags)):
                add("WIRE01", "wire-parity-mismatch",
                    f"{codec.relpath}'s dtype_itemsize knows tag {tag} "
                    f"but the python DType enum does not",
                    path=codec.relpath,
                    line=codec.line_of(rf"case\s+{tag}\s*:"),
                    snippet=f"case {tag}")
            sizes = _python_itemsizes(tags)
            for tag in sorted(set(tags) & set(table)):
                if tag in sizes and sizes[tag] != table[tag]:
                    add("WIRE01", "wire-parity-mismatch",
                        f"dtype tag {tag} ({tags[tag]}) is "
                        f"{sizes[tag]} bytes in python but "
                        f"{codec.relpath}'s dtype_itemsize says "
                        f"{table[tag]}",
                        module=dtypes_mod, node=dtypes_node)
            inventory_native["dtype_itemsize"] = {
                str(k): v for k, v in sorted(table.items())}

    # event-kind bytes pushed by the native ingest paths
    push_sites: list[tuple[int, int, NativeText]] = []
    for name in ("transport.cc", "grpc_server.cc"):
        src = native.get(name)
        if src is not None:
            push_sites.extend((v, ln, src)
                              for v, ln in scrape_call_args(src,
                                                            "push_event"))
    if push_sites:
        pushed = sorted({v for v, _ln, _src in push_sites})
        ev = {n: need("bindings", n) for n in
              ("_EV_TRAJECTORY", "_EV_REGISTER", "_EV_UNREGISTER")}
        expected = sorted(c.value for c in ev.values()
                          if c is not None and isinstance(c.value, int))
        if expected and pushed != expected:
            first_v, first_ln, first_src = push_sites[0]
            add("WIRE01", "wire-parity-mismatch",
                f"native ingest pushes event-type bytes {pushed} but "
                f"transport/native_bindings.py expects {expected} "
                f"(_EV_TRAJECTORY/_EV_REGISTER/_EV_UNREGISTER)",
                path=first_src.relpath, line=first_ln,
                snippet=f"push_event({first_v}, ...)")
        inventory_native["push_event_types"] = pushed

    hub = native.get("event_hub.h")
    if hub is not None:
        m = re.search(r"e\.type\s*==\s*(\d+)\s*\?\s*(\d+)\s*:\s*(\d+)",
                      hub.text)
        if m is None:
            add("WIRE02", "wire-symbol-missing",
                f"event_hub's register/unregister kind mapping is no "
                f"longer extractable from {hub.relpath}",
                path=hub.relpath, line=1, snippet="e.type")
        else:
            reg, unreg = int(m.group(2)), int(m.group(3))
            line = hub.line_of(r"e\.type\s*==")
            for pyname, nval in (("KIND_REGISTER", reg),
                                 ("KIND_UNREGISTER", unreg)):
                c = need("columnar", pyname)
                if c is not None and c.value != nval:
                    add("WIRE01", "wire-parity-mismatch",
                        f"{hub.relpath}:{line} maps the "
                        f"{pyname.split('_')[1].lower()} event to blob "
                        f"kind {nval} but types/columnar.py's {pyname} "
                        f"is {c.value!r}",
                        module=c.module, node=c.node)
            inventory_native["event_hub_kinds"] = {"register": reg,
                                                  "unregister": unreg}

    # -- inventory -------------------------------------------------------
    def py_inv(modkey: str, names: tuple[str, ...]) -> dict[str, object]:
        out: dict[str, object] = {}
        for name in names:
            c = consts[modkey].get(name)
            if c is not None:
                out[name] = (c.value.decode("ascii", "replace")
                             if isinstance(c.value, bytes) else c.value)
        return out

    inventory = {
        "python": {
            "columnar": py_inv("columnar", (
                "_BLOB_MAGIC", "MAGIC_BYTES", "KIND_COLUMNAR", "KIND_RAW",
                "KIND_REGISTER", "KIND_RAW_ENVELOPE", "KIND_UNREGISTER",
                "FRAME_VERSION", "FLAG_FOOTER", "_HDR", "_COL_FIXED",
                "_META", "_FOOTER")),
            "tensor": py_inv("tensor", ("_MAGIC", "_VERSION", "_HEADER")),
            "modelwire": py_inv("modelwire", (
                "MAGIC", "KIND_KEYFRAME", "KIND_DELTA", "KIND_CHUNK")),
            "transport_base": py_inv("tbase", (
                "BATCH_MAGIC", "BATCH_KIND_ENVELOPES", "BATCH_KIND_FRAMES",
                "NACK_OK", "NACK_MALFORMED", "NACK_QUARANTINED",
                "NACK_OVERLOADED", "NACK_UNAVAILABLE")),
            "aggregate": py_inv("aggregate", ("SNAP_MAGIC",
                                              "FRAME_VERSION")),
            "native_bindings": py_inv("bindings", (
                "_EV_TRAJECTORY", "_EV_REGISTER", "_EV_UNREGISTER",
                "_HB_ALIVE", "_HB_SLOW", "_HB_DEAD")),
        },
        "native": {k: inventory_native[k]
                   for k in sorted(inventory_native)},
    }
    return findings, inventory
