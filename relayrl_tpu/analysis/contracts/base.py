"""Shared infrastructure for the contracts engine.

Everything here is pure stdlib + AST, like the jaxlint engine: contract
extraction must run on accelerator-free CI hosts and must not import the
modules it audits (a module with an import-time bug still gets checked).

:class:`ContractContext` resolves the artifact roots once — the
installed package, the enclosing repo (docs/, native/, tests/,
pytest.ini) — caches parsed modules, and constructs
:class:`~relayrl_tpu.analysis.engine.Finding` objects that honor the
same ``# jaxlint: disable=CODE`` per-line suppression jaxlint uses, so
one suppression mechanism covers both engines.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Iterator, Sequence

from relayrl_tpu.analysis.engine import (
    Finding,
    ModuleInfo,
    _enclosing_repo_root,
    _suppressed_rules,
    iter_python_files,
    statement_end_line,
)

__all__ = [
    "ContractContext",
    "ParsedModule",
    "const_fold",
    "iter_md_tables",
    "strip_cell",
]


class ParsedModule:
    """One parsed source file plus its display path and import aliases
    (reuses :class:`ModuleInfo` so passes get ``resolve``/``qualname``
    semantics identical to the jaxlint rules)."""

    def __init__(self, abspath: str, relpath: str, source: str):
        self.abspath = abspath
        self.relpath = relpath  # posix, repo-root anchored
        self.info = ModuleInfo(path=relpath, source=source,
                               tree=ast.parse(source))

    @property
    def tree(self) -> ast.Module:
        return self.info.tree

    @property
    def lines(self) -> list[str]:
        return self.info.lines

    @property
    def dotted(self) -> str:
        """Dotted module name relative to the scan base
        (``relayrl_tpu/transport/base.py`` -> "relayrl_tpu.transport.base")."""
        name = self.relpath[:-3] if self.relpath.endswith(".py") else self.relpath
        name = name.replace("/", ".")
        return name[:-9] if name.endswith(".__init__") else name


class ContractContext:
    """Artifact roots + parsed-module cache for one contracts run.

    ``package_root`` is the python tree the passes walk (default: the
    installed ``relayrl_tpu`` package). The repo artifacts — docs,
    native sources, tests, pytest.ini — resolve from the enclosing repo
    root when one exists; each pass degrades gracefully (skips its
    cross-artifact half) when its artifact is absent, so the engine
    still runs against an installed wheel. Tests override individual
    roots to aim passes at synthetic fixtures.
    """

    def __init__(self, package_root: str | None = None,
                 repo_root: str | None = None,
                 native_root: str | None = None,
                 docs_root: str | None = None,
                 tests_root: str | None = None,
                 pytest_ini: str | None = None):
        if package_root is None:
            import relayrl_tpu

            package_root = os.path.dirname(
                os.path.abspath(relayrl_tpu.__file__))
        self.package_root = os.path.abspath(str(package_root))
        if repo_root is None:
            repo_root = _enclosing_repo_root(self.package_root)
        self.repo_root = (os.path.abspath(str(repo_root))
                          if repo_root else None)
        base = self.repo_root or os.path.dirname(self.package_root)
        self.display_base = base

        def _default(sub: str) -> str | None:
            if self.repo_root is None:
                return None
            cand = os.path.join(self.repo_root, sub)
            return cand if os.path.exists(cand) else None

        self.native_root = (os.path.abspath(str(native_root))
                            if native_root else _default("native"))
        self.docs_root = (os.path.abspath(str(docs_root))
                          if docs_root else _default("docs"))
        self.tests_root = (os.path.abspath(str(tests_root))
                           if tests_root else _default("tests"))
        self.pytest_ini = (os.path.abspath(str(pytest_ini))
                           if pytest_ini else _default("pytest.ini"))
        self._modules: list[ParsedModule] | None = None
        self._texts: dict[str, str] = {}

    # -- file access -----------------------------------------------------
    def rel(self, abspath: str) -> str:
        return os.path.relpath(abspath, self.display_base).replace(
            os.sep, "/")

    def read_text(self, abspath: str) -> str | None:
        if abspath not in self._texts:
            try:
                with open(abspath, "r", encoding="utf-8") as f:
                    self._texts[abspath] = f.read()
            except OSError:
                return None
        return self._texts[abspath]

    def package_modules(self) -> list[ParsedModule]:
        """Every parseable .py file under ``package_root`` (parse errors
        are jaxlint's PARSE finding's job — contracts skip them)."""
        if self._modules is None:
            mods: list[ParsedModule] = []
            for path in iter_python_files(self.package_root):
                source = self.read_text(path)
                if source is None:
                    continue
                try:
                    mods.append(ParsedModule(path, self.rel(path), source))
                except SyntaxError:
                    continue
            self._modules = mods
        return self._modules

    def module(self, rel_under_package: str) -> ParsedModule | None:
        """Look up one package module by its package-relative path
        (``telemetry/events.py``)."""
        want = os.path.join(self.package_root, rel_under_package)
        want = os.path.abspath(want)
        for mod in self.package_modules():
            if mod.abspath == want:
                return mod
        return None

    # -- findings --------------------------------------------------------
    def finding(self, code: str, name: str, message: str,
                module: ParsedModule | None = None,
                node: ast.AST | None = None,
                path: str | None = None, line: int = 1,
                snippet: str = "") -> Finding | None:
        """Build one contract finding. Anchored in a python module, the
        jaxlint suppression comment applies (``# jaxlint: disable=MET03
        - reason``) and returns None when suppressed; doc/native/json
        anchors have no per-line suppression (use the baseline)."""
        if module is not None and node is not None:
            line = getattr(node, "lineno", 1)
            path = module.relpath
            if 1 <= line <= len(module.lines):
                snippet = module.lines[line - 1].strip()
            disabled = _suppressed_rules(module.lines, line,
                                         statement_end_line(node))
            if disabled & {"all", code.lower(), name.lower()}:
                return None
        return Finding(rule=code, name=name, path=path or "<contracts>",
                       line=line, col=1, message=message, snippet=snippet)


# -- constant folding ----------------------------------------------------

def const_fold(node: ast.AST) -> tuple[bool, Any]:
    """Evaluate a literal-ish expression: constants, +/- and bit-shift
    arithmetic on constants (``64 << 20``), tuples/lists/dicts of the
    same. Returns ``(ok, value)`` — the config/wire extractors must
    never execute repo code, only fold what's written down."""
    if isinstance(node, ast.Constant):
        return True, node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        ok, v = const_fold(node.operand)
        return (True, -v) if ok and isinstance(v, (int, float)) else (False, None)
    if isinstance(node, ast.BinOp):
        lok, lv = const_fold(node.left)
        rok, rv = const_fold(node.right)
        if not (lok and rok):
            return False, None
        try:
            if isinstance(node.op, ast.LShift):
                return True, lv << rv
            if isinstance(node.op, ast.RShift):
                return True, lv >> rv
            if isinstance(node.op, ast.Add):
                return True, lv + rv
            if isinstance(node.op, ast.Sub):
                return True, lv - rv
            if isinstance(node.op, ast.Mult):
                return True, lv * rv
            if isinstance(node.op, ast.Div):
                return True, lv / rv
            if isinstance(node.op, ast.Pow):
                return True, lv ** rv
        except (TypeError, ValueError, ZeroDivisionError):
            return False, None
        return False, None
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            ok, v = const_fold(elt)
            if not ok:
                return False, None
            out.append(v)
        return True, (tuple(out) if isinstance(node, ast.Tuple) else out)
    if isinstance(node, ast.Dict):
        d: dict[Any, Any] = {}
        for k, v in zip(node.keys, node.values):
            if k is None:
                return False, None
            kok, kv = const_fold(k)
            vok, vv = const_fold(v)
            if not (kok and vok):
                return False, None
            d[kv] = vv
        return True, d
    return False, None


# -- markdown tables -----------------------------------------------------

_CODE_SPAN_RE = re.compile(r"`([^`]+)`")


def iter_md_tables(text: str) -> Iterator[tuple[str, list[str],
                                                list[tuple[int, list[str]]]]]:
    """Yield ``(nearest_heading, header_cells, rows)`` for every pipe
    table; each row is ``(1-based line number, cells)``. Good enough for
    the repo's hand-written GFM tables; ``\\|`` inside a cell (label
    enumerations like ``{plane=model\\|trajectory}``) stays one cell."""
    heading = ""
    in_fence = False
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.lstrip().startswith("```"):
            in_fence = not in_fence
            i += 1
            continue
        if in_fence:
            # a shell comment inside a code fence is not a heading, and
            # a table-looking line inside one is not a table
            i += 1
            continue
        if line.startswith("#"):
            heading = line.lstrip("#").strip()
            i += 1
            continue
        if (line.lstrip().startswith("|") and i + 1 < len(lines)
                and re.match(r"^\s*\|[\s:|-]+\|\s*$", lines[i + 1])):
            header = _cells(line)
            rows: list[tuple[int, list[str]]] = []
            j = i + 2
            while j < len(lines) and lines[j].lstrip().startswith("|"):
                rows.append((j + 1, _cells(lines[j])))
                j += 1
            yield heading, header, rows
            i = j
            continue
        i += 1


def _cells(row: str) -> list[str]:
    parts = re.split(r"(?<!\\)\|", row.strip().strip("|"))
    return [p.strip().replace("\\|", "|") for p in parts]


def strip_cell(cell: str) -> str:
    """First code-span content of a table cell, else the bare text."""
    m = _CODE_SPAN_RE.search(cell)
    return m.group(1).strip() if m else cell.strip()


def code_spans(cell: str) -> list[str]:
    return [m.group(1).strip() for m in _CODE_SPAN_RE.finditer(cell)]


def walk_functions(tree: ast.Module) -> Iterator[
        tuple[str | None, ast.FunctionDef | ast.AsyncFunctionDef]]:
    """Yield ``(class_name_or_None, function_def)`` for every module- or
    class-level function (nested defs belong to their parent's body and
    are not separate analysis units here)."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield node.name, item


def first_str(call: ast.Call) -> str | None:
    """The first positional argument when it is a string literal."""
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def sorted_findings(findings: Sequence[Finding]) -> list[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                           f.message))
