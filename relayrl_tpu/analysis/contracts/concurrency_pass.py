"""Concurrency contract: lock-order cycles, transitive blocking, threads.

CONC01 (jaxlint) sees one function at a time. This pass builds the
cross-module picture: which named locks exist (``self._lock =
threading.Lock()`` attributes and module-level lock globals), which
functions acquire them (``with`` statements), and who calls whom — then
checks the properties that only exist at the graph level:

* LOCK01 — two locks are acquired in both orders somewhere in the
  package (an A→B and a B→A path): the classic deadlock shape. Cycles
  are reported with every participating acquisition site.
* LOCK02 — a call made while holding a lock reaches (through one or
  more callees) a blocking operation — ``time.sleep``, a socket recv, a
  thread join. The direct case is CONC01's; this is the interprocedural
  upgrade, so only depth ≥ 1 chains are reported here.
* THR01 — a ``threading.Thread`` that is neither daemonized nor ever
  joined: an unkillable process at shutdown, or a silently leaked
  worker.

Call resolution is best-effort and package-local (same-module
functions, ``self.``-methods of the same class, and module-level
functions reached through import aliases); unresolved calls simply
contribute nothing, so the pass under-reports rather than guessing.
"""

from __future__ import annotations

import ast
from typing import Iterator

from relayrl_tpu.analysis.contracts.base import (
    ContractContext,
    ParsedModule,
)
from relayrl_tpu.analysis.engine import Finding, qualname
from relayrl_tpu.analysis.rules.concurrency_rules import BlockingUnderLock

_LOCK_CTORS = frozenset({"threading.Lock", "threading.RLock",
                         "threading.Condition"})

FuncKey = tuple  # (module_dotted, class_or_None, func_name)


def _is_lock_ctor(mod: ParsedModule, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.info.resolved_call(node) or qualname(node.func) or ""
    return resolved in _LOCK_CTORS or (
        resolved.rsplit(".", 1)[-1] in ("Lock", "RLock", "Condition")
        and "thread" in resolved.lower())


class FuncSummary:
    def __init__(self, key: FuncKey, module: ParsedModule):
        self.key = key
        self.module = module
        # lock_id -> acquisition `with` node (first one wins)
        self.acquires: dict[str, ast.AST] = {}
        # nested-with edges: (held_id, acquired_id, with_node)
        self.direct_edges: list[tuple[str, str, ast.AST]] = []
        # every resolved package-local call: (held_ids, node, callee_key)
        self.calls: list[tuple[tuple[str, ...], ast.Call, FuncKey]] = []
        # direct blocking ops: label -> node
        self.blocks: dict[str, ast.AST] = {}


class ConcurrencyGraph:
    """Locks, per-function summaries, and the call graph for one run."""

    def __init__(self, ctx: ContractContext):
        self.ctx = ctx
        self.module_locks: dict[str, dict[str, str]] = {}  # dotted -> name -> id
        self.class_locks: dict[tuple[str, str], dict[str, str]] = {}
        self.functions: dict[FuncKey, FuncSummary] = {}
        self.thread_sites: list[tuple[ParsedModule, ast.Call,
                                      str | None]] = []
        self._collect_locks()
        self._collect_functions()

    # -- collection ------------------------------------------------------
    def _collect_locks(self) -> None:
        for mod in self.ctx.package_modules():
            mlocks: dict[str, str] = {}
            for node in mod.tree.body:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and _is_lock_ctor(mod, node.value)):
                    name = node.targets[0].id
                    mlocks[name] = f"{mod.dotted}.{name}"
            if mlocks:
                self.module_locks[mod.dotted] = mlocks
            for cls in mod.tree.body:
                if not isinstance(cls, ast.ClassDef):
                    continue
                clocks: dict[str, str] = {}
                for node in ast.walk(cls):
                    if (isinstance(node, ast.Assign)
                            and len(node.targets) == 1
                            and _is_lock_ctor(mod, node.value)):
                        target = qualname(node.targets[0]) or ""
                        if target.startswith("self.") \
                                and target.count(".") == 1:
                            attr = target.split(".", 1)[1]
                            clocks[attr] = (f"{mod.dotted}."
                                            f"{cls.name}.{attr}")
                if clocks:
                    self.class_locks[(mod.dotted, cls.name)] = clocks

    def _collect_functions(self) -> None:
        # two phases: register every key first, THEN walk bodies — call
        # resolution must see functions defined later in the file or in
        # a module not yet visited
        units: list[tuple[ParsedModule, str | None, ast.AST]] = []
        for mod in self.ctx.package_modules():
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    units.append((mod, None, node))
                elif isinstance(node, ast.ClassDef):
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            units.append((mod, node.name, item))
        for mod, cls, func in units:
            key: FuncKey = (mod.dotted, cls, func.name)
            self.functions.setdefault(key, FuncSummary(key, mod))
        for mod, cls, func in units:
            summary = self.functions[(mod.dotted, cls, func.name)]
            for stmt in func.body:
                self._walk(summary, mod, cls, stmt, ())

    def _lock_id(self, mod: ParsedModule, cls: str | None,
                 expr: ast.AST) -> str | None:
        name = qualname(expr)
        if not name:
            return None
        if name.startswith("self.") and name.count(".") == 1 \
                and cls is not None:
            return self.class_locks.get((mod.dotted, cls), {}).get(
                name.split(".", 1)[1])
        if "." not in name:
            return self.module_locks.get(mod.dotted, {}).get(name)
        return None

    def _walk(self, summary: FuncSummary, mod: ParsedModule,
              cls: str | None, node: ast.AST,
              held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate execution context
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[str] = []
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        self._record_call(summary, mod, cls, sub, held)
                lock_id = self._lock_id(mod, cls, item.context_expr)
                if lock_id is not None:
                    summary.acquires.setdefault(lock_id, node)
                    for h in held:
                        if h != lock_id:
                            summary.direct_edges.append((h, lock_id,
                                                         node))
                    acquired.append(lock_id)
            inner = held + tuple(a for a in acquired if a not in held)
            for stmt in node.body:
                self._walk(summary, mod, cls, stmt, inner)
            return
        if isinstance(node, ast.Call):
            self._record_call(summary, mod, cls, node, held)
        for child in ast.iter_child_nodes(node):
            self._walk(summary, mod, cls, child, held)

    def _record_call(self, summary: FuncSummary, mod: ParsedModule,
                     cls: str | None, call: ast.Call,
                     held: tuple[str, ...]) -> None:
        label = BlockingUnderLock._blocking_label(mod.info, call)
        if label:
            summary.blocks.setdefault(label, call)
        callee = self._resolve_call(mod, cls, call)
        if callee is not None:
            summary.calls.append((held, call, callee))

    def _resolve_call(self, mod: ParsedModule, cls: str | None,
                      call: ast.Call) -> FuncKey | None:
        if isinstance(call.func, ast.Name):
            name = call.func.id
            key: FuncKey = (mod.dotted, None, name)
            if key in self.functions:
                return key
            return self._resolve_dotted(mod.info.resolve(name))
        q = qualname(call.func)
        if not q:
            return None
        if q.startswith("self.") and q.count(".") == 1 and cls is not None:
            key = (mod.dotted, cls, q.split(".", 1)[1])
            return key if key in self.functions else None
        return self._resolve_dotted(mod.info.resolve(q) or q)

    def _resolve_dotted(self, dotted: str | None) -> FuncKey | None:
        if not dotted or "." not in dotted:
            return None
        mod_path, name = dotted.rsplit(".", 1)
        key: FuncKey = (mod_path, None, name)
        return key if key in self.functions else None

    # -- closures --------------------------------------------------------
    def acquires_closure(self, key: FuncKey,
                         _memo: dict | None = None,
                         _stack: frozenset = frozenset()
                         ) -> dict[str, tuple[ParsedModule, ast.AST]]:
        memo = _memo if _memo is not None else {}
        if key in memo:
            return memo[key]
        if key in _stack:
            return {}
        summary = self.functions.get(key)
        if summary is None:
            return {}
        out: dict[str, tuple[ParsedModule, ast.AST]] = {
            lock: (summary.module, node)
            for lock, node in summary.acquires.items()}
        stack = _stack | {key}
        for _held, _node, callee in summary.calls:
            for lock, site in self.acquires_closure(callee, memo,
                                                    stack).items():
                out.setdefault(lock, site)
        memo[key] = out
        return out

    def blocking_closure(self, key: FuncKey,
                         _memo: dict | None = None,
                         _stack: frozenset = frozenset()
                         ) -> dict[str, tuple[ParsedModule, ast.AST]]:
        memo = _memo if _memo is not None else {}
        if key in memo:
            return memo[key]
        if key in _stack:
            return {}
        summary = self.functions.get(key)
        if summary is None:
            return {}
        out: dict[str, tuple[ParsedModule, ast.AST]] = {
            label: (summary.module, node)
            for label, node in summary.blocks.items()}
        stack = _stack | {key}
        for _held, _node, callee in summary.calls:
            for label, site in self.blocking_closure(callee, memo,
                                                     stack).items():
                out.setdefault(label, site)
        memo[key] = out
        return out


# -- cycle detection -----------------------------------------------------

def _strongly_connected(graph: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan; returns SCCs with ≥2 nodes, deterministically ordered."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    counter = [0]
    sccs: list[list[str]] = []

    def visit(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                visit(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                scc.append(w)
                if w == v:
                    break
            if len(scc) >= 2:
                sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            visit(v)
    sccs.sort()
    return sccs


def _site(module: ParsedModule, node: ast.AST) -> str:
    return f"{module.relpath}:{getattr(node, 'lineno', 1)}"


# -- the pass ------------------------------------------------------------

def run(ctx: ContractContext) -> tuple[list[Finding], dict]:
    findings: list[Finding] = []

    def add(code: str, name: str, message: str, **kw) -> None:
        f = ctx.finding(code, name, message, **kw)
        if f is not None:
            findings.append(f)

    graph = ConcurrencyGraph(ctx)

    # edges: (A, B) -> (module, node, via_label) — deterministic winner
    edges: dict[tuple[str, str], tuple[ParsedModule, ast.AST, str]] = {}

    def record_edge(a: str, b: str, module: ParsedModule, node: ast.AST,
                    via: str) -> None:
        prior = edges.get((a, b))
        cand = (module, node, via)
        if prior is None or (_site(module, node), via) < (
                _site(prior[0], prior[1]), prior[2]):
            edges[(a, b)] = cand

    memo_acq: dict = {}
    memo_blk: dict = {}
    for key in sorted(graph.functions,
                      key=lambda k: (k[0], k[1] or "", k[2])):
        summary = graph.functions[key]
        for a, b, node in summary.direct_edges:
            record_edge(a, b, summary.module, node, "")
        for held, node, callee in summary.calls:
            if not held:
                continue
            callee_name = ".".join(str(p) for p in callee if p)
            for lock, _acq_site in graph.acquires_closure(
                    callee, memo_acq).items():
                for h in held:
                    if h != lock:
                        record_edge(h, lock, summary.module, node,
                                    f"via {callee_name}()")
            blocked = graph.blocking_closure(callee, memo_blk)
            if blocked:
                label = sorted(blocked)[0]
                bmod, bnode = blocked[label]
                add("LOCK02", "blocking-under-lock-transitive",
                    f"`{callee_name}()` is called while holding "
                    f"`{held[-1]}` and eventually blocks: `{label}` at "
                    f"{_site(bmod, bnode)} — CONC01 can't see through "
                    f"the call; move the call outside the critical "
                    f"section or make the callee non-blocking",
                    module=summary.module, node=node)

    adjacency: dict[str, set[str]] = {}
    for (a, b) in edges:
        adjacency.setdefault(a, set()).add(b)
        adjacency.setdefault(b, set())
    for scc in _strongly_connected(adjacency):
        members = set(scc)
        cycle_edges = sorted((a, b) for (a, b) in edges
                             if a in members and b in members)
        parts = []
        for a, b in cycle_edges:
            module, node, via = edges[(a, b)]
            suffix = f" {via}" if via else ""
            parts.append(f"`{a}` then `{b}` at "
                         f"{_site(module, node)}{suffix}")
        first_mod, first_node, _via = edges[cycle_edges[0]]
        add("LOCK01", "lock-order-cycle",
            "lock-order cycle (potential deadlock): "
            + "; ".join(parts)
            + " — pick one global order and acquire in it everywhere",
            module=first_mod, node=first_node)

    # THR01: threads neither daemonized nor joined
    for mod in ctx.package_modules():
        for module_, node, reason in _unjoined_threads(mod):
            add("THR01", "thread-never-joined",
                f"thread is {reason} — join it on shutdown or mark it "
                f"daemon=True so process exit isn't blocked on a "
                f"forgotten worker",
                module=module_, node=node)

    inventory = {
        "locks": sorted({lid for locks in graph.module_locks.values()
                         for lid in locks.values()}
                        | {lid for locks in graph.class_locks.values()
                           for lid in locks.values()}),
        "lock_edges": [f"{a} -> {b}" for a, b in sorted(edges)],
    }
    return findings, inventory


def _thread_ctor(mod: ParsedModule, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    resolved = mod.info.resolved_call(node) or qualname(node.func) or ""
    return resolved == "threading.Thread" or resolved.endswith(".Thread")


def _unjoined_threads(mod: ParsedModule) -> Iterator[
        tuple[ParsedModule, ast.Call, str]]:
    ctors: list[tuple[ast.Call, str | None]] = []

    class _Finder(ast.NodeVisitor):
        def visit_Assign(self, node: ast.Assign) -> None:
            if len(node.targets) == 1 and _thread_ctor(mod, node.value):
                ctors.append((node.value, qualname(node.targets[0])))
            else:
                self.generic_visit(node)

        def visit_Call(self, node: ast.Call) -> None:
            if _thread_ctor(mod, node):
                ctors.append((node, None))
            self.generic_visit(node)

    _Finder().visit(mod.tree)

    seen: set[int] = set()
    deduped: list[tuple[ast.Call, str | None]] = []
    for call, target in ctors:
        if id(call) in seen:
            continue
        seen.add(id(call))
        deduped.append((call, target))

    joined_receivers: set[str] = set()
    daemon_assigned: set[str] = set()
    any_join = False
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and not isinstance(node.func.value, ast.Constant)):
            any_join = True
            receiver = qualname(node.func.value)
            if receiver:
                joined_receivers.add(receiver)
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = qualname(node.targets[0]) or ""
            if target.endswith(".daemon") and isinstance(
                    node.value, ast.Constant) and node.value.value is True:
                daemon_assigned.add(target[:-len(".daemon")])

    for call, target in deduped:
        daemon_kw = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True for kw in call.keywords)
        if daemon_kw:
            continue
        if target is not None:
            if target in joined_receivers or target in daemon_assigned:
                continue
            yield mod, call, (f"assigned to `{target}` but never "
                              f"joined or daemonized in this module")
        else:
            # anonymous: appended to a pool or started inline — accept
            # if the module joins *anything* (pool-join idiom)
            if any_join:
                continue
            yield mod, call, ("anonymous (never bound) and this module "
                              "joins nothing")
