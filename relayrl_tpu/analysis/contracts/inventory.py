"""The machine-readable contract inventory (``contracts.json``).

Every pass returns its slice; :func:`serialize_inventory` renders the
merged document byte-deterministically (sorted keys, fixed indent,
trailing newline) so two runs over the same tree are byte-identical and
the committed file diffs cleanly. :func:`diff_inventory` is the CON01
regression anchor: the committed inventory vs a fresh extraction —
any drift means a contract changed without the inventory (and therefore
the PR description) saying so.
"""

from __future__ import annotations

import json
import os

INVENTORY_VERSION = 1

# contracts.json ships next to baseline.json as package data.
DEFAULT_INVENTORY = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "contracts.json")


def merge_inventory(sections: dict[str, dict]) -> dict:
    doc = {"version": INVENTORY_VERSION}
    for name in sorted(sections):
        if sections[name]:
            doc[name] = sections[name]
    return doc


def serialize_inventory(doc: dict) -> str:
    return json.dumps(doc, indent=2, sort_keys=True,
                      ensure_ascii=True) + "\n"


def load_inventory(path: str) -> dict | None:
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def write_inventory(path: str, doc: dict) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(serialize_inventory(doc))


def diff_inventory(committed: dict, fresh: dict,
                   max_items: int = 8) -> list[str]:
    """Human-readable leaf-level differences, deterministic order."""
    diffs: list[str] = []

    def descend(prefix: str, a: object, b: object) -> None:
        if len(diffs) >= max_items:
            return
        if isinstance(a, dict) and isinstance(b, dict):
            for key in sorted(set(a) | set(b)):
                where = f"{prefix}.{key}" if prefix else str(key)
                if key not in a:
                    diffs.append(f"`{where}` only in fresh extraction")
                elif key not in b:
                    diffs.append(f"`{where}` only in committed inventory")
                else:
                    descend(where, a[key], b[key])
                if len(diffs) >= max_items:
                    return
        elif a != b:
            diffs.append(f"`{prefix}`: committed {a!r} != extracted {b!r}")

    descend("", committed, fresh)
    return diffs
