"""The RLHF dataflow scheduler: generate → score → update as decoupled
stages over the existing tiers (ISSUE 13 tentpole; the MindSpeed RL /
RLAX disaggregated pattern).

Stage map — every stage rides machinery that already exists:

* **generate** — a :class:`GenerationStage` steps ``rlhf.lanes``
  TokenGen lanes through ONE batched jitted policy dispatch per round.
  Sequence (transformer) policies run the vector tier's vmapped
  ``step_window`` path (``runtime/vector_actor.py`` — generation through
  this stage is BIT-identical to a local ``PolicyActor`` at the same
  seed + params version, the lock tests/test_rlhf.py holds);
  ``rlhf.generation_tier: "anakin"`` moves generation INSIDE the fused
  scan (:class:`FusedGenerationStage` — TokenGen as pure JAX in the
  ``lax.scan`` with the rolling-window carry, ``lanes × unroll`` tokens
  per device dispatch instead of one per-step round-trip); thin-client
  generation via the serving plane serves sequence policies too since
  serving v2 — the service holds each lane's rolling window in its
  session table, capacity bounded by ``serving.max_sessions`` (size it
  to the lane count; an evicted lane resyncs from its client mirror,
  it does not fail). Behavior policy
  evidence is recorded per token at generation time: ``logp_a`` (the
  V-trace numerator's denominator) already rides every record's aux;
  the stage adds ``bver``, the params version the token was sampled
  under.
* **score** — completed generations are WITHHELD from the wire (the
  ``VectorAgent.send_interceptor`` seam) and handed to a
  :class:`ScoreStage` thread, which batches them into one jitted scorer
  dispatch, writes the terminal reward into the episode's marker
  record, and re-injects via ``VectorAgent.emit_lane`` — sequence
  numbers are assigned at emission, so the spool's at-least-once window
  only ever holds FINAL (scored) bytes and a crash replay can never
  deliver an unscored episode.
* **update** — the unmodified training server: scored episodes flow
  through spool/seq-dedup/columnar ingest into the IMPALA learner,
  whose V-trace correction (``ops/vtrace.py``) importance-weights each
  token from its recorded behavior log-prob back to the current policy
  — the off-policy lag between ``bver`` and the learner's version is
  exactly what it exists for. ``learner.freeze`` masks
  (``algorithms/freeze.py``) make the fine-tune recipe first-class.

Telemetry: ``relayrl_rlhf_generated_tokens_total``,
``relayrl_rlhf_scored_episodes_total``,
``relayrl_rlhf_stage_seconds{stage=generate|score|emit}``, and
``relayrl_rlhf_lag_versions`` (behavior-vs-actor-held version distance
observed at emission). docs/observability.md has the catalog;
docs/operations.md the runbook.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

import numpy as np

from relayrl_tpu.types.columnar import (
    DecodedTrajectory,
    encode_columnar_frame,
    is_columnar_frame,
    parse_frame,
)
from relayrl_tpu.types.trajectory import (
    deserialize_actions,
    serialize_actions,
)

#: Version-lag buckets: unit-ish resolution near on-policy, coarse tail.
LAG_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def extract_generation(records, prompt_len: int):
    """Serialized-episode records → ``(tokens[i32], gen_len, marker)``.

    ``records`` is one episode as shipped by an actor tier: real steps
    (obs = the pre-action token context window, act = the token) plus
    the trailing terminal marker from ``flag_last_action``. The full
    generated sequence is the LAST real step's context with its action
    written at the final write position — observations are recorded
    before the action lands, so only the last token is missing from the
    last observation. Token values are small integers, exact in the
    float32 the wire normalizes observations to."""
    real = [r for r in records if r.act is not None]
    if not real:
        raise ValueError("episode has no real steps to score")
    marker = records[-1] if records[-1].act is None else None
    gen_len = len(real)
    last = real[-1]
    tokens = np.asarray(last.obs).astype(np.int32).reshape(-1).copy()
    write = int(prompt_len) + gen_len - 1
    if write >= tokens.shape[0]:
        raise ValueError(
            f"generation of {gen_len} tokens overflows the context window "
            f"({tokens.shape[0]} with prompt_len {prompt_len})")
    tokens[write] = int(np.asarray(last.act).reshape(-1)[0])
    return tokens, gen_len, marker


def extract_generation_frame(dt: DecodedTrajectory, prompt_len: int):
    """Columnar twin of :func:`extract_generation`: one decoded frame
    (the anakin tier ships whole episodes as contiguous columnar frames,
    markers pre-folded) → ``(tokens[i32], gen_len)``. The terminal
    marker is folded into the frame (``n_records == n_steps + 1``), so
    there is no marker object to patch — the score lands on ``r[-1]``
    directly, which is exactly where the server's native decoder folds a
    scored marker's reward."""
    if dt.n_steps < 1:
        raise ValueError("frame has no real steps to score")
    if dt.n_records != dt.n_steps + 1:
        raise ValueError(
            f"frame is not one terminated episode (n_steps {dt.n_steps}, "
            f"n_records {dt.n_records}) — the score stage patches the "
            f"folded terminal reward, which a mid-episode chunk lacks")
    gen_len = int(dt.n_steps)
    tokens = np.asarray(
        dt.columns["o"][-1]).astype(np.int32).reshape(-1).copy()
    write = int(prompt_len) + gen_len - 1
    if write >= tokens.shape[0]:
        raise ValueError(
            f"generation of {gen_len} tokens overflows the context window "
            f"({tokens.shape[0]} with prompt_len {prompt_len})")
    tokens[write] = int(np.asarray(dt.columns["a"][-1]).reshape(-1)[0])
    return tokens, gen_len


class ScoreStage:
    """Decoupled scoring: batches completed generations into one scorer
    dispatch, assigns the terminal reward, re-emits.

    ``submit`` runs on the generation thread and BLOCKS when
    ``max_queue`` episodes are parked (bounded hand-off = backpressure:
    a slow scorer throttles generation instead of growing unbounded —
    the pipeline/serving precedent). The worker gathers up to ``batch``
    episodes, waiting ``linger_s`` after the first for siblings (size-
    or-linger close, the dynamic-batching shape), scores them in ONE
    ``score_batch_np`` dispatch (short batches are padded with repeats
    of row 0 — inert under vmap, sliced off), patches each episode's
    terminal marker reward, and hands the re-serialized bytes to
    ``emit_fn(lane, payload)``.
    """

    def __init__(self, scorer, prompt_len: int, emit_fn: Callable,
                 batch: int = 8, linger_s: float = 0.02,
                 max_queue: int = 256, version_fn: Callable | None = None):
        from relayrl_tpu import telemetry

        self.scorer = scorer
        self.prompt_len = int(prompt_len)
        self.emit_fn = emit_fn
        self.batch = max(1, int(batch))
        self.linger_s = max(0.0, float(linger_s))
        self.version_fn = version_fn
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(max_queue)))
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self.scored: list[float] = []  # per-episode scores, arrival order
        self._scored_lock = threading.Lock()
        reg = telemetry.get_registry()
        self._m_scored = reg.counter(
            "relayrl_rlhf_scored_episodes_total",
            "completed generations scored and re-emitted")
        self._m_score_s = reg.histogram(
            "relayrl_rlhf_stage_seconds",
            "wall seconds per stage dispatch on the RLHF dataflow",
            labels={"stage": "score"})
        self._m_emit_s = reg.histogram(
            "relayrl_rlhf_stage_seconds",
            "wall seconds per stage dispatch on the RLHF dataflow",
            labels={"stage": "emit"})
        self._m_lag = reg.histogram(
            "relayrl_rlhf_lag_versions",
            "behavior version vs actor-held version at emission "
            "(tokens sampled N publishes behind the model they train)",
            buckets=LAG_BUCKETS)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rlhf-score")
        self._thread.start()

    def submit(self, lane: int, payload: bytes) -> None:
        # Bounded put in a re-checking loop, NOT one blocking put: if the
        # worker dies while the queue is full, nothing ever drains it —
        # a single q.put() would block the generation thread forever
        # (inside the host lock, wedging model swaps too) instead of
        # surfacing the worker's error.
        while True:
            if self._error is not None:
                raise RuntimeError("score stage died") from self._error
            if self._stop.is_set():
                raise RuntimeError("score stage is closed")
            try:
                self._q.put((lane, payload), timeout=0.5)
                return
            except queue.Full:
                continue

    def _gather(self):
        """One batch: block for the first episode, then linger for
        siblings up to ``batch``."""
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        out = [first]
        deadline = time.monotonic() + self.linger_s
        while len(out) < self.batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                out.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return out

    def _score_batch(self, episodes):
        """(lane, records, tokens, gen_len, marker) rows → scores [n]."""
        n = len(episodes)
        batched = getattr(self.scorer, "score_batch_np", None)
        if batched is None:
            return [float(self.scorer.score_np(tok, self.prompt_len, gl))
                    for (_l, _r, tok, gl, _m) in episodes]
        width = self.batch if n <= self.batch else n
        tokens = np.stack(
            [episodes[i % n][2] for i in range(width)])  # pad: repeat rows
        gen_lens = np.asarray(
            [episodes[i % n][3] for i in range(width)], np.int32)
        scores = batched(tokens, self.prompt_len, gen_lens)
        return [float(s) for s in scores[:n]]

    def _loop(self) -> None:
        try:
            while not (self._stop.is_set() and self._q.empty()):
                batch = self._gather()
                if not batch:
                    continue
                from relayrl_tpu.telemetry import trace as trace_mod

                tracer = trace_mod.get_tracer()
                trace_id = tracer.sample_id("rlhf")
                t0_ns = time.monotonic_ns() if trace_id else 0
                t0 = time.monotonic()
                episodes = []
                for lane, payload in batch:
                    if is_columnar_frame(payload):
                        # Anakin-tier generation: one whole episode per
                        # frame, markers pre-folded. The decoded frame
                        # stands in for the record list; the marker slot
                        # is None (the terminal reward lives in r[-1]).
                        dt = parse_frame(payload)
                        tokens, gen_len = extract_generation_frame(
                            dt, self.prompt_len)
                        episodes.append((lane, dt, tokens, gen_len, None))
                    else:
                        records = deserialize_actions(payload)
                        tokens, gen_len, marker = extract_generation(
                            records, self.prompt_len)
                        episodes.append(
                            (lane, records, tokens, gen_len, marker))
                scores = self._score_batch(episodes)
                self._m_score_s.observe(time.monotonic() - t0)
                if trace_id:
                    t1_ns = time.monotonic_ns()
                    tracer.span("rlhf", trace_id, "score", t0_ns, t1_ns,
                                episodes=len(episodes))
                t1 = time.monotonic()
                held = (int(self.version_fn())
                        if self.version_fn is not None else None)
                for (lane, records, _tok, _gl, marker), score in zip(
                        episodes, scores):
                    if isinstance(records, DecodedTrajectory):
                        # Columnar patch: the marker is folded, so the
                        # score IS the terminal row's reward (the
                        # terminal record's own rew is always masked to
                        # 0 — "the reward rides the marker" — and
                        # update_reward REPLACES, so folded terminal =
                        # 0 + score). ``u`` stays untouched: u[-1]=0
                        # mirrors the per-record fold exactly.
                        r_col = np.array(records.columns["r"], copy=True)
                        r_col[-1] = r_col.dtype.type(score)
                        records.columns = dict(records.columns)
                        records.columns["r"] = r_col
                        if held is not None:
                            bvers = records.aux.get("bver")
                            if bvers is not None:
                                for bver in np.asarray(
                                        bvers).reshape(-1).tolist():
                                    self._m_lag.observe(
                                        max(0, held - int(bver)))
                        payload_out = encode_columnar_frame(records)
                    else:
                        if marker is not None:
                            marker.update_reward(float(score))
                        else:  # defensive: episode ended without a marker
                            records[-1].update_reward(
                                records[-1].rew + float(score))
                        if held is not None:
                            for r in records:
                                bver = (r.data or {}).get("bver")
                                if bver is not None:
                                    self._m_lag.observe(
                                        max(0, held - int(bver)))
                        payload_out = serialize_actions(records)
                    self.emit_fn(lane, payload_out)
                    self._m_scored.inc()
                    with self._scored_lock:
                        self.scored.append(float(score))
                self._m_emit_s.observe(time.monotonic() - t1)
                if trace_id:
                    tracer.span("rlhf", trace_id, "emit", t1_ns,
                                time.monotonic_ns(),
                                episodes=len(episodes))
        except BaseException as e:  # surfaced on the next submit/close
            self._error = e
            print(f"[rlhf] score stage died: {e!r}", flush=True)

    def scored_snapshot(self) -> list[float]:
        with self._scored_lock:
            return list(self.scored)

    def close(self, timeout_s: float = 30.0) -> None:
        """Drain-and-stop: everything submitted before close() is scored
        and emitted (the flush contract a final spool replay relies
        on)."""
        self._stop.set()
        self._thread.join(timeout=timeout_s)
        if self._error is not None:
            raise RuntimeError("score stage died") from self._error


class GenerationStage:
    """The generate stage: one batched policy dispatch per round across
    ``lanes`` TokenGen lanes (scorer=None — the decoupled mode; rewards
    are the score stage's job), stamping each record with the behavior
    version ``bver``. Works against anything exposing the batched
    actor-host surface (``request_for_actions`` / per-lane
    ``flag_last_action`` / ``version``): a raw
    :class:`~relayrl_tpu.runtime.vector_actor.VectorActorHost` (the
    bit-identity tests), a live :class:`~relayrl_tpu.runtime.agent.
    VectorAgent`, or the scheduler's remote-lane adapter."""

    def __init__(self, host, venv, seed: int | None = None):
        from relayrl_tpu import telemetry

        self.host = host
        self.venv = venv
        self.obs, _ = venv.reset(seed=seed)
        self.episodes_started = venv.num_envs
        self.episodes_done = 0
        self.tokens_generated = 0
        reg = telemetry.get_registry()
        self._m_tokens = reg.counter(
            "relayrl_rlhf_generated_tokens_total",
            "tokens generated (one per lane per batched dispatch)")
        self._m_gen_s = reg.histogram(
            "relayrl_rlhf_stage_seconds",
            "wall seconds per stage dispatch on the RLHF dataflow",
            labels={"stage": "generate"})

    def run_round(self) -> int:
        """One token per lane: dispatch, stamp ``bver``, step the envs,
        flag finished lanes (terminal reward 0.0 — the score stage owns
        it). Returns the number of episodes that completed."""
        from relayrl_tpu.runtime.agent import coerce_env_action

        t0 = time.monotonic()
        records = self.host.request_for_actions(self.obs)
        bver = np.int32(self.host.version)
        for r in records:
            # The version the batch's single params read served — the
            # V-trace lag evidence. Stamped before the episode's flush
            # (records live in the lane trajectory until the terminal
            # marker ships them).
            r.data["bver"] = bver
        actions = [coerce_env_action(r.act) for r in records]
        self.obs, _rews, terms, truncs, _infos = self.venv.step(actions)
        done = 0
        for lane in range(self.venv.num_envs):
            if terms[lane] or truncs[lane]:
                self.host.flag_last_action(lane, 0.0, terminated=True)
                done += 1
        self._m_tokens.inc(self.venv.num_envs)
        gen_dt = time.monotonic() - t0
        self._m_gen_s.observe(gen_dt)
        if done:
            # Trace draw at EPISODE granularity only (this round closed
            # at least one generation) — a per-token draw would churn
            # the sampling lock and, at rate 1.0, flood the flight
            # recorder with one span per token across all lanes.
            from relayrl_tpu.telemetry import trace as trace_mod

            tracer = trace_mod.get_tracer()
            if tracer.enabled:
                trace_id = tracer.sample_id("rlhf")
                if trace_id:
                    now_ns = time.monotonic_ns()
                    tracer.span("rlhf", trace_id, "generate",
                                now_ns - int(gen_dt * 1e9), now_ns,
                                lanes=self.venv.num_envs,
                                episodes=done)
        self.tokens_generated += self.venv.num_envs
        self.episodes_done += done
        self.episodes_started += done  # autoreset: a new one began
        return done


class FusedGenerationStage:
    """Anakin-tier generate stage (``rlhf.generation_tier: "anakin"``):
    generation happens INSIDE the fused scan — TokenGen runs as pure JAX
    in the ``lax.scan`` with the rolling-window carry, so one
    ``rollout()`` dispatch produces ``lanes × unroll_length`` tokens
    with zero per-token host round-trips. ``bver`` is stamped at unstack
    (``record_bver=True`` — the whole window is one model version by
    construction) and ``logp_a`` rides each record's aux as everywhere
    else, so the per-token behavior evidence the V-trace correction and
    the lag histogram read is identical to the vector tier's. Episodes
    still leave through the interceptor seam (withheld → scored →
    re-injected); this object only drives rollouts and keeps the pacing
    loop's accounting surface (``host``/``episodes_done``/
    ``run_round``/``tokens_generated``)."""

    def __init__(self, agent):
        from relayrl_tpu import telemetry

        self.agent = agent
        self.host = agent.host
        self.episodes_done = 0
        self.tokens_generated = 0
        reg = telemetry.get_registry()
        self._m_tokens = reg.counter(
            "relayrl_rlhf_generated_tokens_total",
            "tokens generated (one per lane per batched dispatch)")
        self._m_gen_s = reg.histogram(
            "relayrl_rlhf_stage_seconds",
            "wall seconds per stage dispatch on the RLHF dataflow",
            labels={"stage": "generate"})

    def run_round(self) -> int:
        """One fused window: ``lanes × unroll_length`` tokens in a
        single device dispatch. Returns completed episodes (TokenGen
        ends every episode as ``terminated``, so in-scan autoreset
        starts the next prompt without leaving the device)."""
        t0 = time.monotonic()
        stats = self.agent.rollout()
        self._m_tokens.inc(int(stats["steps"]))
        self._m_gen_s.observe(time.monotonic() - t0)
        self.tokens_generated += int(stats["steps"])
        done = int(stats["episodes"])
        self.episodes_done += done
        return done


class _RemoteLanes:
    """Thin-client generation tier: N ``RemoteActorClient`` lanes against
    the serving plane, adapted to the batched actor-host surface the
    GenerationStage drives. Sequence policies serve through the
    service's per-session window table (serving v2) — keep
    ``serving.max_sessions`` at or above the lane count so steady-state
    generation never cycles through eviction/resync.

    The N round-trips fire CONCURRENTLY (one worker per lane): serial
    requests would cost N x the round-trip per token AND present the
    service's size-or-linger batcher with batch-of-1 forever — in-flight
    overlap is exactly the concurrency the dynamic batching was built
    for. Each client has its own lock, so cross-client concurrency is
    safe; per-lane episode assembly stays on its lane's worker."""

    def __init__(self, clients):
        import concurrent.futures

        self.clients = clients
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(clients), thread_name_prefix="rlhf-remote")

    @property
    def version(self) -> int:
        return max(c.version for c in self.clients)

    def request_for_actions(self, obs, masks=None, rewards=None):
        futures = [self._pool.submit(c.request_for_action, obs[i])
                   for i, c in enumerate(self.clients)]
        return [f.result() for f in futures]

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def flag_last_action(self, lane: int, reward: float = 0.0,
                         truncated: bool = False, final_obs=None,
                         terminated: bool | None = None, final_mask=None):
        self.clients[lane].flag_last_action(
            reward, truncated=truncated, final_obs=final_obs,
            terminated=terminated, final_mask=final_mask)


class RlhfScheduler:
    """Wires the three stages against a live training server.

    ``server_type``/``addr_overrides`` point at the server exactly like
    an Agent's; the learner side (algorithm, ``learner.freeze``,
    V-trace knobs) is the server's config — this object is purely the
    actor-plane orchestrator. ``scorer`` overrides the config-resolved
    one (any object with ``score_np``/``score_batch_np``); ``rng_keys``
    feeds the vector host's per-lane key override (bit-identity locks).
    """

    def __init__(
        self,
        config_path: str | None = None,
        server_type: str = "zmq",
        seed: int = 0,
        identity: str | None = None,
        lanes: int | None = None,
        scorer=None,
        generation_tier: str | None = None,
        rng_keys=None,
        handshake_timeout_s: float = 60.0,
        **addr_overrides,
    ):
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.envs import SyncVectorEnv, TokenGenEnv

        self.config = ConfigLoader(None, config_path)
        p = self.config.get_rlhf_params()
        self.params = p
        self.lanes = int(lanes if lanes is not None else p["lanes"])
        self.tier = str(generation_tier or p["generation_tier"])
        self.prompt_len = p["prompt_len"]
        self.scorer = scorer if scorer is not None else self._make_scorer(p)

        # Env lanes run scorer-less: the terminal reward is the score
        # stage's to assign (the whole point of the decoupled dataflow).
        # The anakin tier has no host-side envs at all — TokenGen runs
        # as pure JAX inside the fused scan.
        if self.tier == "anakin":
            self.venv = None
        else:
            def env_fn():
                return TokenGenEnv(vocab_size=p["vocab_size"],
                                   prompt_len=p["prompt_len"],
                                   max_new_tokens=p["max_new_tokens"],
                                   scorer=None)

            self.venv = SyncVectorEnv([env_fn for _ in range(self.lanes)])

        if self.tier == "remote":
            from relayrl_tpu.runtime.inference import RemoteActorClient

            base = identity or f"rlhf-{seed}"
            clients = []
            for k in range(self.lanes):
                client = RemoteActorClient(
                    config_path=config_path, server_type=server_type,
                    seed=seed + k, identity=f"{base}.lane{k}",
                    handshake_timeout_s=handshake_timeout_s,
                    **addr_overrides)
                # Interpose the score stage on this lane's episode flow
                # (the VectorAgent seam, client-shaped): the original
                # sender becomes the stage's emit target.
                clients.append(client)
            self.agent = None
            self._clients = clients
            host = _RemoteLanes(clients)
            sends = [c.trajectory._on_send for c in clients]
            for k, c in enumerate(clients):
                c.trajectory._on_send = (
                    lambda payload, _k=k: self._withhold(_k, payload))
            self._emit = lambda lane, payload: sends[lane](payload)
            version_fn = lambda: host.version  # noqa: E731
        elif self.tier == "anakin":
            from relayrl_tpu.runtime.agent import VectorAgent

            # Fused generation: TokenGen-v0 inside the scan, whole
            # episodes shipped as columnar frames (the anakin default),
            # bver stamped at unstack. The interceptor seam is the SAME
            # one the vector tier uses — withheld episodes come back
            # through emit_lane with spool seqs assigned at emission, so
            # the at-least-once window only ever holds scored bytes.
            self.agent = VectorAgent(
                num_envs=self.lanes, server_type=server_type, seed=seed,
                identity=identity, host_mode="anakin",
                unroll_length=p["generation_unroll"],
                jax_env="TokenGen-v0",
                jax_env_kwargs={"vocab_size": p["vocab_size"],
                                "prompt_len": p["prompt_len"],
                                "max_new_tokens": p["max_new_tokens"]},
                record_bver=True,
                handshake_timeout_s=handshake_timeout_s,
                send_interceptor=self._withhold, rng_keys=rng_keys,
                config_path=config_path, **addr_overrides)
            self._clients = []
            host = self.agent.host
            self._emit = self.agent.emit_lane
            version_fn = lambda: self.agent.host.version  # noqa: E731
        else:
            from relayrl_tpu.runtime.agent import VectorAgent

            self.agent = VectorAgent(
                num_envs=self.lanes, server_type=server_type, seed=seed,
                identity=identity, host_mode="vector",
                handshake_timeout_s=handshake_timeout_s,
                send_interceptor=self._withhold, rng_keys=rng_keys,
                config_path=config_path, **addr_overrides)
            self._clients = []
            host = self.agent.host
            self._emit = self.agent.emit_lane
            version_fn = lambda: self.agent.host.version  # noqa: E731

        self.score_stage = ScoreStage(
            self.scorer, prompt_len=p["prompt_len"], emit_fn=self._emit,
            batch=p["score_batch"], max_queue=p["score_queue"],
            version_fn=version_fn)
        self.generation = (FusedGenerationStage(self.agent)
                           if self.tier == "anakin"
                           else GenerationStage(host, self.venv, seed=seed))

    def _make_scorer(self, p: dict):
        from relayrl_tpu.rlhf.scorers import make_scorer

        if p["scorer"] == "reward_model":
            return make_scorer(
                "reward_model", vocab_size=p["vocab_size"],
                context_len=p["prompt_len"] + p["max_new_tokens"],
                d_model=p["rm_d_model"], n_layers=p["rm_n_layers"],
                seed=p["rm_seed"])
        return make_scorer("programmatic", vocab_size=p["vocab_size"])

    def _withhold(self, lane: int, payload: bytes):
        self.score_stage.submit(lane, payload)
        return None  # the stage re-injects via emit after scoring

    # -- driving --
    def run(self, episodes: int, deadline_s: float = 300.0) -> dict:
        """Generate until ``episodes`` generations have been scored and
        emitted (or the deadline passes), pacing against the learner:
        once ``rlhf.max_episodes_per_version`` episodes completed under
        one held model version, generation waits (bounded by
        ``rlhf.pace_timeout_s``) for a newer swap before continuing — a
        fast actor host can outrun the learner 10-30x, and V-trace's
        clipped-rho correction tolerates bounded lag rather than making
        free throughput of unbounded lag. Returns run stats including
        the arrival-ordered score curve."""
        pace = int(self.params.get("max_episodes_per_version", 0))
        pace_timeout = float(self.params.get("pace_timeout_s", 5.0))
        deadline = time.monotonic() + deadline_s
        pace_version = self.generation.host.version
        pace_done = self.generation.episodes_done
        while (len(self.score_stage.scored_snapshot()) < episodes
               and time.monotonic() < deadline):
            held = self.generation.host.version
            if held != pace_version:
                pace_version, pace_done = held, self.generation.episodes_done
            elif (pace and
                  self.generation.episodes_done - pace_done >= pace):
                # Staleness bound hit: wait (briefly) for a newer swap.
                # A timeout WITHOUT a swap falls through to exactly one
                # liveness round and re-enters this wait — the anchor
                # does NOT advance, so a stalled learner gets a trickle
                # of fresh episodes (the crash-drill heartbeat) instead
                # of an unbounded pile-up of stale ones.
                wait_until = min(deadline,
                                 time.monotonic() + pace_timeout)
                while (self.generation.host.version == pace_version
                       and time.monotonic() < wait_until):
                    time.sleep(0.005)
                held = self.generation.host.version
                if held != pace_version:
                    pace_version = held
                    pace_done = self.generation.episodes_done
            self.generation.run_round()
        scores = self.score_stage.scored_snapshot()
        return {
            "episodes_scored": len(scores),
            "scores": scores,
            "tokens_generated": self.generation.tokens_generated,
        }

    def flush(self, timeout_s: float = 30.0) -> None:
        """Finish any open lane episodes are NOT flushed (mid-generation
        tokens stay local); everything already terminal is scored and
        emitted."""
        self.score_stage.close(timeout_s=timeout_s)

    def close(self) -> None:
        try:
            self.score_stage.close()
        finally:
            if self.agent is not None:
                self.agent.disable_agent()
            host = self.generation.host
            if hasattr(host, "close"):
                host.close()  # remote tier: drain the lane worker pool
            for c in self._clients:
                c.disable_agent()
