"""RLHF workload plane: the generate → score → update dataflow
(ISSUE 13; RLAX arXiv:2512.06392 and MindSpeed RL arXiv:2507.19017
organize LLM-scale RL exactly this way).

Pieces:

* :mod:`relayrl_tpu.rlhf.scorers`   — the pluggable terminal-boundary
  scorer interface with two built-ins (programmatic CI scorer, frozen
  transformer reward model);
* :mod:`relayrl_tpu.rlhf.scheduler` — the dataflow scheduler wiring
  token generation through the existing actor tiers, decoupled scoring,
  and emission into the live spool/seq/ingest machinery; off-policy lag
  between behavior and learner versions is corrected by the existing
  V-trace learner (``algorithms/impala.py`` over ``ops/vtrace.py``)
  using the behavior log-probs recorded per token at generation time.

The environment half lives in the env registries (``TokenGen-v0`` —
``envs/tokengen.py`` + the pure-JAX twin), the frozen-layer optimizer
masks in ``algorithms/freeze.py`` (the ``learner.freeze`` knob), and
the end-to-end scenario in ``benches/bench_rlhf.py``.
"""

from relayrl_tpu.rlhf.scorers import (  # noqa: F401
    SCORERS,
    ProgrammaticScorer,
    RewardModelScorer,
    make_scorer,
)

__all__ = ["SCORERS", "ProgrammaticScorer", "RewardModelScorer",
           "make_scorer"]
