"""Sequence scorers for the RLHF workload plane.

A scorer assigns the whole-generation reward paid at the episode's
terminal boundary (``envs/tokengen.py``). The interface is deliberately
dual-plane:

* ``score_np(tokens, prompt_len, gen_len) -> float`` — host-side, what
  the numpy twin env and the decoupled score stage
  (``rlhf/scheduler.py``) call;
* ``score_jax(tokens, prompt_len, gen_len) -> f32`` — traceable, what
  the pure-JAX env closes into the fused anakin rollout;
* ``score_batch_np(tokens [B, L], prompt_len, gen_lens [B]) -> [B]`` —
  the score stage's batched dispatch (ONE jitted vmap per batch of
  completed generations, the TorchBeast batching insight applied to
  scoring).

Both built-ins route every plane through ONE implementation (the numpy
paths call the same jitted function), so a generation scored on-device,
host-side, or in the decoupled stage earns bit-identical reward — the
parity goldens in tests/test_rlhf.py rely on exactly this.

Built-ins:

* ``ProgrammaticScorer`` ("programmatic") — an all-integer successor-
  pattern count: +1 for every generated non-EOS token equal to
  ``(previous token + 1) % vocab``. Cheap, deterministic, and learnable
  by construction — the CI scorer.
* ``RewardModelScorer`` ("reward_model") — a learned reward model: a
  frozen randomly-initialized transformer critic
  (``transformer_discrete``, ``has_critic=True``) over one-hot token
  sequences; the score is ``tanh(v)`` read at the last generated
  position. It holds its OWN params (never trained, never published) —
  the standard RLHF topology where the RM is a separate frozen network
  from the policy being optimized.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

EOS_TOKEN = 0


class ProgrammaticScorer:
    """Successor-pattern count: the reward-maximizing generation
    continues the prompt's token chain ``t -> (t + 1) % vocab`` for
    ``max_new_tokens`` steps without emitting EOS. Integer arithmetic
    end to end, so every plane agrees bit-for-bit."""

    name = "programmatic"

    def __init__(self, vocab_size: int = 8):
        self.vocab_size = int(vocab_size)

    def score_np(self, tokens, prompt_len: int, gen_len: int) -> float:
        tokens = np.asarray(tokens, np.int32)
        lo, hi = int(prompt_len), int(prompt_len) + int(gen_len)
        gen = tokens[lo:hi]
        prev = tokens[lo - 1:hi - 1]
        correct = (gen == (prev + 1) % self.vocab_size) & (gen != EOS_TOKEN)
        return float(np.sum(correct))

    def score_jax(self, tokens, prompt_len, gen_len):
        tokens = jnp.asarray(tokens, jnp.int32)
        idx = jnp.arange(tokens.shape[0], dtype=jnp.int32)
        in_gen = jnp.logical_and(idx >= prompt_len, idx < prompt_len + gen_len)
        prev = jnp.concatenate([jnp.zeros(1, jnp.int32), tokens[:-1]])
        correct = jnp.logical_and(
            jnp.logical_and(tokens == (prev + 1) % self.vocab_size,
                            tokens != EOS_TOKEN),
            in_gen)
        return jnp.sum(correct).astype(jnp.float32)

    def score_batch_np(self, tokens, prompt_len: int, gen_lens) -> np.ndarray:
        tokens = np.asarray(tokens, np.int32)
        gen_lens = np.asarray(gen_lens, np.int64)
        lo = int(prompt_len)
        idx = np.arange(tokens.shape[1])
        in_gen = (idx[None, :] >= lo) & (idx[None, :] < lo + gen_lens[:, None])
        prev = np.concatenate(
            [np.zeros((tokens.shape[0], 1), np.int32), tokens[:, :-1]],
            axis=1)
        correct = ((tokens == (prev + 1) % self.vocab_size)
                   & (tokens != EOS_TOKEN) & in_gen)
        return np.sum(correct, axis=1).astype(np.float32)


class RewardModelScorer:
    """Frozen transformer reward model over one-hot token sequences.

    ``score = tanh(v[prompt_len + gen_len - 1])`` — the critic head's
    value at the last generated position, squashed so the reward scale
    stays bounded for the V-trace learner regardless of the random
    init. The params are created once from ``seed`` and NEVER updated;
    two instances with the same (shape, seed) score identically, which
    is how the decoupled score stage and a self-contained env can hold
    the same RM without shipping params between them.
    """

    name = "reward_model"

    def __init__(self, vocab_size: int = 8, context_len: int = 11,
                 d_model: int = 32, n_layers: int = 1, n_heads: int = 2,
                 seed: int = 7):
        from relayrl_tpu.models import build_policy

        self.vocab_size = int(vocab_size)
        self.context_len = int(context_len)
        self.arch = {
            "kind": "transformer_discrete",
            "obs_dim": self.vocab_size,
            "act_dim": self.vocab_size,
            "d_model": int(d_model),
            "n_layers": int(n_layers),
            "n_heads": int(n_heads),
            "max_seq_len": self.context_len,
            "has_critic": True,
        }
        self._policy = build_policy(self.arch)
        self.params = self._policy.init_params(jax.random.PRNGKey(int(seed)))
        # One compiled scorer serves every plane: score_np/score_batch_np
        # call these EXACT programs, so host and device scoring can never
        # drift by a ulp (the bit-parity contract of the module docs).
        self._jit_one = jax.jit(self.score_jax)
        self._jit_batch = jax.jit(jax.vmap(self.score_jax,
                                           in_axes=(0, None, 0)))

    def score_jax(self, tokens, prompt_len, gen_len):
        tokens = jnp.asarray(tokens, jnp.int32)
        onehot = jax.nn.one_hot(tokens, self.vocab_size, dtype=jnp.float32)
        # evaluate() is the public sequence ABI: (logp, ent, v) per
        # position; the actions argument only feeds logp, which is
        # discarded — v is the RM readout.
        _logp, _ent, v = self._policy.evaluate(self.params, onehot, tokens)
        read = jnp.clip(prompt_len + gen_len - 1, 0, tokens.shape[-1] - 1)
        return jnp.tanh(v[read])

    def score_np(self, tokens, prompt_len: int, gen_len: int) -> float:
        return float(self._jit_one(np.asarray(tokens, np.int32),
                                   jnp.int32(prompt_len),
                                   jnp.int32(gen_len)))

    def score_batch_np(self, tokens, prompt_len: int, gen_lens) -> np.ndarray:
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32))
        gen_lens = np.asarray(gen_lens, np.int32)
        return np.asarray(self._jit_batch(tokens, jnp.int32(prompt_len),
                                          gen_lens))


SCORERS = {
    ProgrammaticScorer.name: ProgrammaticScorer,
    RewardModelScorer.name: RewardModelScorer,
}


def make_scorer(name: str, **kwargs):
    """Scorer by registered name (the ``rlhf.scorer`` config knob)."""
    if name not in SCORERS:
        raise ValueError(
            f"unknown scorer {name!r}; registered: {sorted(SCORERS)}")
    return SCORERS[name](**kwargs)
