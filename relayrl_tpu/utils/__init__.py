"""Observability + misc utilities (ref layer L8, SURVEY.md §1)."""

from relayrl_tpu.utils.logger import (
    EpochLogger,
    Logger,
    colorize,
    setup_logger_kwargs,
    statistics_scalar,
)

__all__ = [
    "EpochLogger",
    "Logger",
    "colorize",
    "setup_logger_kwargs",
    "statistics_scalar",
]
