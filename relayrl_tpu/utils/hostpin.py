"""Process-level CPU pinning for actor hosts, benches, and examples.

Pinning JAX to CPU via the ``JAX_PLATFORMS`` env var alone is NOT reliable
on images whose sitecustomize imports jax at interpreter startup (the
config snapshots the env before user code runs); the live
``jax.config.update`` is the lever that works, valid until the backend
initializes. This is the single shared implementation — examples, benches,
and multi-process workers all call it instead of hand-rolling the block.
"""

from __future__ import annotations

import os


def pin_cpu(virtual_devices: int | None = None) -> None:
    """Force this process onto the CPU JAX backend.

    ``virtual_devices`` additionally requests an N-device host platform
    (``--xla_force_host_platform_device_count``) for testing sharded code
    without hardware; it must run before jax creates its backend AND
    before anything latches XLA_FLAGS, so the env mutation happens ahead
    of the jax import below.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if virtual_devices:
        # Strip any pre-existing count and append ours: trailing flags win,
        # but relying on that is fragile and a stale smaller count from the
        # ambient environment must never shrink the requested mesh.
        import re

        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags.strip() +
            f" --xla_force_host_platform_device_count={virtual_devices}"
        ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        # Backend already initialized: the env vars were either respected
        # (fine) or it's too late to change platform — nothing to do.
        pass
