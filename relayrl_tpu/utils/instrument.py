"""Lightweight agent instrumentation (wire bytes + env steps).

One shared implementation for every harness that needs to know what an
actor actually puts on the wire (benches/bench_pixel_wire.py, the e2e
byte-plane guard test): wrapping ``transport.send_trajectory`` counts
REAL serialized payload bytes identically on all three transports, and
wrapping ``request_for_action`` counts one per env step — dividing one
by the other gives the true per-step wire cost, framing and scalar
overhead included.
"""

from __future__ import annotations


def instrument_agent(agent) -> dict:
    """Wrap ``agent``'s send + step paths with counters, in place.

    Returns the live counter dict ``{"bytes", "sends", "steps"}``.
    Wrappers forward to the originals, so behavior is unchanged; safe
    because Agent's trajectory ``on_send`` hook late-binds
    ``self.transport.send_trajectory``."""
    counters = {"bytes": 0, "sends": 0, "steps": 0}
    inner_send = agent.transport.send_trajectory
    inner_step = agent.request_for_action

    def counting_send(raw: bytes, agent_id: str | None = None):
        # agent_id: the transports' logical-lane attribution kwarg — the
        # spool also rides its sequence tag on it; forward verbatim.
        counters["bytes"] += len(raw)
        counters["sends"] += 1
        return inner_send(raw, agent_id=agent_id)

    def counting_step(obs, **kw):
        counters["steps"] += 1
        return inner_step(obs, **kw)

    agent.transport.send_trajectory = counting_send
    agent.request_for_action = counting_step
    return counters
