"""Epoch logging: aligned console table + TSV ``progress.txt``.

Capability parity with the reference's SpinningUp-lineage logger
(reference: relayrl_framework/src/native/python/utils/logger.py:103-386 —
``store()`` accumulates per-epoch values, ``log_tabular`` computes
mean/std/min/max, ``dump_tabular`` writes an aligned console table plus a TSV
row to ``<output_dir>/progress.txt``; directory layout
``logs/<exp>/<exp>_s<seed>`` at :388-448; ``save_config`` dumps a JSON of the
run config at :171-198).

The TSV column layout is kept byte-compatible (tab-separated, header row
first) so the reference's TensorBoard tailer/plotting workflow applies
unchanged to our output.
"""

from __future__ import annotations

import atexit
import json
import os
import os.path as osp
import time
from typing import Any, Mapping

import numpy as np

_COLOR_CODES = {
    "gray": 30, "red": 31, "green": 32, "yellow": 33,
    "blue": 34, "magenta": 35, "cyan": 36, "white": 37,
}


def colorize(string: str, color: str, bold: bool = False) -> str:
    num = _COLOR_CODES.get(color, 37)
    if bold:
        return f"\x1b[{num};1m{string}\x1b[0m"
    return f"\x1b[{num}m{string}\x1b[0m"


def statistics_scalar(values, with_min_and_max: bool = False):
    """Mean/std(/min/max) of a list of scalars
    (ref: BaseReplayBuffer.statistics_scalar)."""
    arr = np.asarray(values, dtype=np.float32).ravel()
    if arr.size == 0:
        nan = float("nan")
        return (nan, nan, nan, nan) if with_min_and_max else (nan, nan)
    mean = float(arr.mean())
    std = float(arr.std())
    if with_min_and_max:
        return mean, std, float(arr.min()), float(arr.max())
    return mean, std


def setup_logger_kwargs(
    exp_name: str, seed: int | None = None, data_dir: str | None = None
) -> dict[str, Any]:
    """Standard run-directory layout (ref: logger.py:388-448):
    ``<data_dir>/<exp_name>/<exp_name>_s<seed>``."""
    data_dir = data_dir or osp.join(os.getcwd(), "logs")
    relpath = exp_name if seed is None else osp.join(exp_name, f"{exp_name}_s{seed}")
    return {"output_dir": osp.join(data_dir, relpath), "exp_name": exp_name}


class Logger:
    """Tabular logger writing ``progress.txt`` (ref: logger.py:103-296)."""

    def __init__(
        self,
        output_dir: str | None = None,
        output_fname: str = "progress.txt",
        exp_name: str | None = None,
    ):
        self.output_dir = output_dir or f"/tmp/experiments/{int(time.time())}"
        os.makedirs(self.output_dir, exist_ok=True)
        self.output_file = open(osp.join(self.output_dir, output_fname), "a")
        atexit.register(self.output_file.close)
        self.first_row = True
        self.log_headers: list[str] = []
        self.log_current_row: dict[str, Any] = {}
        self.exp_name = exp_name

    def log(self, msg: str, color: str = "green") -> None:
        print(colorize(msg, color, bold=True), flush=True)

    def log_tabular(self, key: str, val: Any) -> None:
        if self.first_row:
            self.log_headers.append(key)
        elif key not in self.log_headers:
            raise KeyError(
                f"new key {key!r} introduced after the first epoch; the TSV "
                "schema is fixed at the first dump_tabular"
            )
        if key in self.log_current_row:
            raise KeyError(f"key {key!r} already logged this epoch")
        self.log_current_row[key] = val

    def save_config(self, config: Mapping[str, Any]) -> None:
        """JSON dump of the run config (ref: logger.py:171-198)."""
        def _default(obj):
            return repr(obj)

        out = dict(config)
        if self.exp_name is not None:
            out["exp_name"] = self.exp_name
        serialized = json.dumps(out, indent=2, sort_keys=True, default=_default)
        with open(osp.join(self.output_dir, "config.json"), "w") as f:
            f.write(serialized)

    def dump_tabular(self) -> None:
        # Console rendering: left-aligned keys dot-padded to the value
        # column, values right-aligned — an original layout; only the TSV
        # half below preserves the reference's progress.txt schema.
        vals = [self.log_current_row.get(key, "") for key in self.log_headers]
        # One source, many consumers (ISSUE 4 satellite): the SAME row
        # that renders to console/TSV/TensorBoard mirrors into the
        # telemetry registry as `relayrl_epoch_stat{stat=...}` gauges,
        # so exported epoch metrics can never drift from the logged
        # ones. Looked up per dump (epoch cadence, not hot path) so a
        # registry installed after construction still gets the rows; a
        # NullRegistry makes this a no-op.
        from relayrl_tpu import telemetry

        registry = telemetry.get_registry()
        if registry.enabled:
            for key, val in zip(self.log_headers, vals):
                if hasattr(val, "__float__"):
                    registry.gauge(
                        "relayrl_epoch_stat",
                        "latest epoch-log row value, one child per column",
                        labels={"stat": key}).set(float(val))
        rendered = [
            f"{v:.4g}" if hasattr(v, "__float__") else str(v) for v in vals
        ]
        key_w = max((len(k) for k in self.log_headers), default=0)
        val_w = max((len(s) for s in rendered), default=0)
        lines = [f"epoch {'=' * max(4, key_w + val_w)}"]
        for key, valstr in zip(self.log_headers, rendered):
            pad = "." * (key_w - len(key) + 2)
            lines.append(f"  {key} {pad} {valstr:>{val_w}}")
        print("\n".join(lines), flush=True)
        if self.output_file is not None:
            if self.first_row:
                self.output_file.write("\t".join(self.log_headers) + "\n")
            self.output_file.write("\t".join(map(str, vals)) + "\n")
            self.output_file.flush()
        self.log_current_row.clear()
        self.first_row = False


class EpochLogger(Logger):
    """Logger + per-epoch value accumulation (ref: logger.py:299-386)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.epoch_dict: dict[str, list] = {}

    def store(self, **kwargs) -> None:
        for k, v in kwargs.items():
            self.epoch_dict.setdefault(k, []).append(v)

    def log_tabular(
        self,
        key: str,
        val: Any = None,
        with_min_and_max: bool = False,
        average_only: bool = False,
    ) -> None:
        if val is not None:
            super().log_tabular(key, val)
        else:
            values = self.epoch_dict.get(key, [])
            stats = statistics_scalar(values, with_min_and_max=with_min_and_max)
            super().log_tabular("Average" + key if not average_only else key, stats[0])
            if not average_only:
                super().log_tabular("Std" + key, stats[1])
            if with_min_and_max:
                super().log_tabular("Max" + key, stats[3])
                super().log_tabular("Min" + key, stats[2])
            self.epoch_dict[key] = []

    def get_stats(self, key: str, with_min_and_max: bool = False):
        return statistics_scalar(self.epoch_dict.get(key, []), with_min_and_max)
