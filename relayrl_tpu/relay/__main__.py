"""``python -m relayrl_tpu.relay`` — run one relay node as a process.

Two configuration surfaces:

* human flags (``--upstream-type zmq --upstream-listener tcp://... ``
  etc.) layered over the ``relay.*`` config section, for operators;
* ``--json '{...}'`` — a dict of :class:`RelayNode` ctor kwargs, for
  drivers (benches, tests) that already hold the topology as data.

The process relays until ``--duration`` lapses, ``--stop-file``
appears, or SIGTERM/SIGINT arrives; on the way out it flushes the
spool, and with ``--result-path`` writes a JSON result (relay stats +
the full telemetry snapshot in the production ``/snapshot`` schema) for
the driver to embed — the bench's relay-counter evidence.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m relayrl_tpu.relay",
        description="one hop of the hierarchical relay tree")
    parser.add_argument("--json", default=None,
                        help="RelayNode ctor kwargs as a JSON object "
                             "(driver surface; flags below override)")
    parser.add_argument("--config", default=None, help="config file path")
    parser.add_argument("--name", default=None)
    parser.add_argument("--upstream-type", default=None,
                        choices=("zmq", "grpc", "native", "auto"))
    parser.add_argument("--upstream-listener", default=None,
                        help="parent agent_listener addr (zmq)")
    parser.add_argument("--upstream-trajectory", default=None,
                        help="parent trajectory addr (zmq)")
    parser.add_argument("--upstream-model", default=None,
                        help="parent model pub addr (zmq)")
    parser.add_argument("--upstream-server", default=None,
                        help="parent server addr (grpc/native)")
    parser.add_argument("--downstream-type", default=None,
                        choices=("zmq", "grpc"))
    parser.add_argument("--fanout-port", type=int, default=None,
                        help="bind the zmq fan-out triple at this base "
                             "port (listener, +1 trajectory, +2 model)")
    parser.add_argument("--spool-dir", default=None)
    parser.add_argument("--batch-max", type=int, default=None)
    parser.add_argument("--duration", type=float, default=None,
                        help="relay for this many seconds then exit")
    parser.add_argument("--stop-file", default=None,
                        help="exit when this file appears")
    parser.add_argument("--ready-file", default=None,
                        help="touch this file once the relay is serving")
    parser.add_argument("--result-path", default=None,
                        help="write stats + telemetry snapshot here on exit")
    parser.add_argument("--no-telemetry", action="store_true",
                        help="skip installing a live metrics registry")
    args = parser.parse_args(argv)

    kwargs: dict = {}
    if args.json:
        kwargs.update(json.loads(args.json))
    if args.config:
        kwargs["config_path"] = args.config
    if args.name:
        kwargs["name"] = args.name
    if args.upstream_type:
        kwargs["upstream_type"] = args.upstream_type
    upstream = dict(kwargs.get("upstream") or {})
    if args.upstream_listener:
        upstream["agent_listener_addr"] = args.upstream_listener
    if args.upstream_trajectory:
        upstream["trajectory_addr"] = args.upstream_trajectory
    if args.upstream_model:
        upstream["model_sub_addr"] = args.upstream_model
    if args.upstream_server:
        upstream["server_addr"] = args.upstream_server
    if upstream:
        kwargs["upstream"] = upstream
    if args.downstream_type:
        kwargs["downstream_type"] = args.downstream_type
    if args.fanout_port is not None:
        kwargs["fanout_port"] = args.fanout_port
    if args.spool_dir:
        kwargs["spool_dir"] = args.spool_dir
    if args.batch_max is not None:
        kwargs["batch_max"] = args.batch_max

    from relayrl_tpu import telemetry

    if not args.no_telemetry:
        # A live registry regardless of config telemetry.enabled: the
        # relay's result file must carry its counters (the bench/test
        # workers' chaos_telemetry convention).
        telemetry.set_registry(telemetry.Registry(
            run_id=f"relay-{kwargs.get('name') or 'node'}"))

    from relayrl_tpu.relay import RelayNode

    node = RelayNode(**kwargs)

    stopping = []

    def _stop_signal(signum, frame):
        stopping.append(signum)
        node._stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _stop_signal)
        except ValueError:
            pass  # not the main thread (embedded use)

    if args.ready_file:
        with open(args.ready_file, "w") as f:
            f.write(node.name)
    print(f"[relay/{node.name}] relaying "
          f"(upstream={node.upstream_type}, "
          f"downstream={node.downstream_type})", flush=True)
    try:
        node.run(duration_s=args.duration, stop_file=args.stop_file)
    finally:
        stats = node.stats()
        node.close()
        if args.result_path:
            result = {"relay": node.name, "stats": stats,
                      "telemetry": telemetry.get_registry().snapshot()}
            with open(args.result_path, "w") as f:
                json.dump(result, f)
        print(f"[relay/{node.name}] down: {json.dumps(stats)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
