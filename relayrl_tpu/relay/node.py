"""The relay node: one hop of the hierarchical distribution tree.

A :class:`RelayNode` stands between the training server (or a parent
relay) and an actor subtree and turns BOTH planes into a tree
(ROADMAP item 2; RLAX arXiv:2512.06392 makes the parameter-distribution
layer a first-class component, MindSpeed RL arXiv:2507.19017 the same
disaggregated-dataflow shape):

**Downstream (model wire).** The relay subscribes ONCE upstream through
a normal agent transport and re-publishes every delivered frame
VERBATIM on its own fan-out plane (zmq PUB, or a grpc long-poll plane)
— so the root publisher pays O(relays) streams per publish instead of
O(actors). Wire-v2 frames are treated as opaque-but-versioned: the CRC
is re-verified per hop (a corrupt frame dies here, never reaches the
subtree), chunked keyframes are reassembled by the upstream listener
before this node sees them (and re-chunked per the downstream plane's
own ``transport.chunk_bytes``), keyframes and v1 bundles are cached,
and deltas pass straight through. A subtree resync (CMD_RESYNC from an
actor whose delta base diverged) is served from the cached keyframe
without ever reaching the root; only a relay whose own cache is cold
escalates upstream.

**Upstream (trajectory wire).** The same node ingests the subtree's
trajectory envelopes — columnar RLD1 frames and per-record payloads
alike, both opaque bytes here — and batch-forwards them upstream over
ONE connection, with every leaf agent's id + ``#s`` seq tag carried
verbatim (``transport.base`` batch containers; the server's ingest
funnel splits them back into per-agent envelopes). The relay runs its
own :class:`~relayrl_tpu.runtime.spool.TrajectorySpool` on behalf of
the subtree, retaining forwards as VERBATIM entries (no relay-level seq
space — a restarted relay minting fresh seqs would be deduplicated into
silence), so a relay crash is exactly the PR 6 drill one level up:
spool replay on reconnect + the root ledger's per-leaf dedup ⇒ zero
loss, zero double-train.

On the wire a relay is indistinguishable from a training server:
actors point their ordinary transport config at the relay's fan-out
addresses. Start one with ``python -m relayrl_tpu.relay``.
"""

from __future__ import annotations

import os
import threading
import time

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.telemetry.aggregate import is_snapshot_frame


class RelayNode:
    """One relay hop. ``config`` carries the ``relay.*`` section
    (knob-by-knob ctor overrides win); ``upstream_transport`` /
    ``downstream_transport`` are test seams that skip transport
    construction entirely."""

    def __init__(
        self,
        config_path: str | None = None,
        name: str | None = None,
        upstream_type: str | None = None,
        upstream: dict | None = None,
        downstream_type: str | None = None,
        downstream: dict | None = None,
        fanout_port: int | None = None,
        keyframe_cache: bool | None = None,
        batch_max: int | None = None,
        batch_linger_ms: float | None = None,
        spool_entries: int | None = None,
        spool_bytes: int | None = None,
        spool_dir: str | None = None,
        resync_min_interval_s: float | None = None,
        handshake_timeout_s: float = 60.0,
        start: bool = True,
        upstream_transport=None,
        downstream_transport=None,
    ):
        from relayrl_tpu import faults, telemetry

        self.config = ConfigLoader(None, config_path)
        telemetry.configure_from_config(self.config)
        faults.maybe_install_from_env()
        params = self.config.get_relay_params()

        def pick(value, key):
            return params[key] if value is None else value

        self.name = pick(name, "name") or f"relay-{os.getpid()}"
        self.upstream_type = pick(upstream_type, "upstream_type")
        self.downstream_type = pick(downstream_type, "downstream_type")
        self._upstream_overrides = dict(pick(upstream, "upstream"))
        self._downstream_overrides = dict(pick(downstream, "downstream"))
        self._fanout_port = int(pick(fanout_port, "fanout_port"))
        self.keyframe_cache_enabled = bool(pick(keyframe_cache,
                                                "keyframe_cache"))
        self.batch_max = max(1, int(pick(batch_max, "batch_max")))
        self.batch_linger_s = float(pick(batch_linger_ms,
                                         "batch_linger_ms")) / 1000.0
        self._spool_entries = int(pick(spool_entries, "spool_entries"))
        self._spool_bytes = int(pick(spool_bytes, "spool_bytes"))
        self._spool_dir = pick(spool_dir, "spool_dir")
        self.resync_min_interval_s = float(pick(resync_min_interval_s,
                                                "resync_min_interval_s"))
        self._handshake_timeout_s = float(handshake_timeout_s)
        # Upstream wire id for multi-envelope containers: untagged on
        # purpose (see spool.send_verbatim — only LEAF seq tags dedup).
        self.batch_id = f"@relay/{self.name}"

        # -- model cache (one lock guards all three slots) --
        self._model_lock = threading.Lock()
        self._handshake: tuple[int, bytes] | None = None  # v1 bundle
        self._keyframe: tuple[int, bytes] | None = None   # verbatim frame
        self._latest: tuple[int, bytes, int | None] | None = None
        self._latest_version = -1
        self._last_handshake_refresh = -1e9
        self._last_resync_serve = -1e9

        # -- subtree registry (bounded: ids only, for the gauge) --
        self._subtree_lock = threading.Lock()
        self._subtree_agents: set[str] = set()

        # -- forward buffer (downstream ingest -> upstream batches) --
        self._fwd_cond = threading.Condition()
        self._fwd_buf: list[tuple[str, bytes]] = []  # (tagged_id, payload)
        self._fwd_thread: threading.Thread | None = None
        self._stop = threading.Event()

        # -- fault plane (relay hook sites; None without a plan) --
        self._fault_model = faults.site("relay.model")
        self._fault_forward = faults.site("relay.forward")
        self._fault_step = faults.site("relay.step")

        # -- telemetry (the ISSUE 11 metric set) --
        reg = telemetry.get_registry()
        self._m_fwd_model = reg.counter(
            "relayrl_relay_frames_forwarded_total",
            "frames re-published/forwarded by this relay",
            {"plane": "model"})
        self._m_fwd_traj = reg.counter(
            "relayrl_relay_frames_forwarded_total",
            "frames re-published/forwarded by this relay",
            {"plane": "trajectory"})
        self._m_bytes_model = reg.counter(
            "relayrl_relay_bytes_total",
            "bytes re-published/forwarded by this relay",
            {"plane": "model"})
        self._m_bytes_traj = reg.counter(
            "relayrl_relay_bytes_total",
            "bytes re-published/forwarded by this relay",
            {"plane": "trajectory"})
        self._m_cache_hits = reg.counter(
            "relayrl_relay_keyframe_cache_hits_total",
            "downstream deliveries served from the relay keyframe cache")
        self._m_resyncs = reg.counter(
            "relayrl_relay_resyncs_served_total",
            "subtree resyncs answered by this relay (never reached root)")
        self._m_resync_escalated = reg.counter(
            "relayrl_relay_resyncs_escalated_total",
            "subtree resyncs forwarded upstream (cold/disabled cache)")
        self._m_dropped = reg.counter(
            "relayrl_relay_frames_dropped_total",
            "frames refused at this hop (CRC mismatch / undecodable)")
        self._m_batches = reg.counter(
            "relayrl_relay_batches_forwarded_total",
            "multi-envelope containers sent upstream")
        self._m_fwd_fleet = reg.counter(
            "relayrl_relay_frames_forwarded_total",
            "frames re-published/forwarded by this relay",
            {"plane": "fleet"})
        self._m_bytes_fleet = reg.counter(
            "relayrl_relay_bytes_total",
            "bytes re-published/forwarded by this relay",
            {"plane": "fleet"})
        reg.gauge_fn("relayrl_relay_subtree_agents",
                     self._subtree_count,
                     "distinct logical agents seen from this subtree")

        # Fleet telemetry fan-in (ISSUE 15, telemetry/aggregate.py):
        # subtree snapshot frames are sniffed out of the trajectory
        # ingest, buffered latest-per-proc, and forwarded as ONE
        # multi-proc frame (plus this relay's own section) per
        # ``telemetry.fleet_interval_s`` — root ingest stays O(relays)
        # exactly like the model plane. interval 0 = plane off: frames
        # fall through the normal forward path verbatim.
        tel_params = self.config.get_telemetry_params()
        self._fleet_interval_s = float(tel_params.get("fleet_interval_s")
                                       or 0.0)
        self._fleet_buf = None
        self._fleet_seq = 0
        self._fleet_thread: threading.Thread | None = None
        if self._fleet_interval_s > 0:
            from relayrl_tpu.telemetry.aggregate import FleetRelayBuffer

            self._fleet_buf = FleetRelayBuffer()

        self.spool = None
        self.up = upstream_transport
        self.down = downstream_transport
        self.active = False
        if start:
            self.enable_relay()

    # -- lifecycle --
    def enable_relay(self) -> None:
        if self.active:
            return
        if self.up is None:
            from relayrl_tpu.transport import make_agent_transport

            overrides = dict(self._upstream_overrides)
            overrides.setdefault("identity", self.batch_id)
            self.up = make_agent_transport(self.upstream_type, self.config,
                                           **overrides)
        # Handshake FIRST: the downstream plane must never come up with
        # nothing to serve (an actor's fetch_model would get b"").
        version, bundle = self.up.fetch_model(self._handshake_timeout_s)
        with self._model_lock:
            self._handshake = (int(version), bundle)
            self._keyframe = (int(version), bundle)  # v1 IS a keyframe
            self._latest = (int(version), bundle, None)
            self._latest_version = int(version)
        self.up.register(self.up.identity)
        self._bind_spool()
        if self.down is None:
            self.down = self._build_downstream()
        self.down.get_model = self._get_model
        self.down.get_model_update = self._get_model_update
        self.down.get_model_version = lambda: self._latest_version
        self.down.on_trajectory = self._on_subtree_trajectory
        self.down.on_register = self._on_subtree_register
        self.down.on_unregister = self._on_subtree_unregister
        self.down.on_resync = self._serve_subtree_resync
        self.down.start()
        self._stop.clear()
        if self.batch_max > 1:
            self._fwd_thread = threading.Thread(
                target=self._forward_loop, name="relay-forward", daemon=True)
            self._fwd_thread.start()
        self.up.on_model = self._on_upstream_model
        self.up.on_reconnect = self._on_upstream_reconnect
        self.up.start_model_listener()
        if self._fleet_buf is not None:
            self._fleet_thread = threading.Thread(
                target=self._fleet_loop, name="relay-fleet", daemon=True)
            self._fleet_thread.start()
        self.active = True
        from relayrl_tpu import telemetry

        telemetry.emit("relay_up", name=self.name, version=version,
                       upstream=self.upstream_type,
                       downstream=self.downstream_type)

    def _build_downstream(self):
        cfg = self.config
        over = self._downstream_overrides
        if self.downstream_type == "grpc":
            from relayrl_tpu.transport.grpc_backend import GrpcServerTransport

            bind = over.get("bind_addr")
            if bind is None and self._fanout_port:
                bind = f"0.0.0.0:{self._fanout_port}"
            return GrpcServerTransport(
                bind_addr=bind or cfg.get_train_server().host_port,
                idle_timeout_s=cfg.get_grpc_idle_timeout_s())
        from relayrl_tpu.transport.zmq_backend import ZmqServerTransport

        if self._fanout_port:
            base = self._fanout_port
            defaults = {
                "agent_listener_addr": f"tcp://0.0.0.0:{base}",
                "trajectory_addr": f"tcp://0.0.0.0:{base + 1}",
                "model_pub_addr": f"tcp://0.0.0.0:{base + 2}",
            }
        else:
            defaults = {
                "agent_listener_addr": cfg.get_agent_listener().address,
                "trajectory_addr": cfg.get_traj_server().address,
                "model_pub_addr": cfg.get_train_server().address,
            }
        return ZmqServerTransport(
            agent_listener_addr=over.get("agent_listener_addr",
                                         defaults["agent_listener_addr"]),
            trajectory_addr=over.get("trajectory_addr",
                                     defaults["trajectory_addr"]),
            model_pub_addr=over.get("model_pub_addr",
                                    defaults["model_pub_addr"]),
            chunk_bytes=cfg.get_transport_params()["chunk_bytes"],
        )

    def _bind_spool(self) -> None:
        if self._spool_entries <= 0:
            self.spool = None
            return
        from relayrl_tpu.runtime.spool import TrajectorySpool
        from relayrl_tpu.transport.retry import breaker_from_config

        retry_cfg = self.config.get_transport_params()["retry"]
        if self.spool is None:
            self.spool = TrajectorySpool(
                send_fn=self._wire_forward,
                max_entries=self._spool_entries,
                max_bytes=self._spool_bytes,
                directory=self._spool_dir,
                name=f"relay-{self.name}",
                breaker=breaker_from_config(f"relay:{self.name}", retry_cfg))
            if self._spool_dir and self.spool.depth:
                # A prior relay life left subtree forwards in flight
                # (the relay crash drill): replay them now — leaf seq
                # tags ride verbatim, the root ledger dedups.
                self.spool.replay()
        else:
            self.spool.send_fn = self._wire_forward

    def close(self, flush_timeout_s: float = 10.0) -> None:
        if not self.active:
            return
        self._stop.set()
        # Downstream FIRST: stop() joins the ingest threads, so no new
        # subtree envelope can arrive after this line — everything
        # already delivered sits in the forward buffer or the spool,
        # and the flush below is genuinely final (an envelope landing
        # in a closed spool would get one unretained wire attempt,
        # exactly the loss the spool exists to prevent).
        if self.down is not None:
            self.down.stop()
        with self._fwd_cond:
            self._fwd_cond.notify_all()
        if self._fwd_thread is not None:
            self._fwd_thread.join(timeout=5)
            self._fwd_thread = None
        if self._fleet_thread is not None:
            self._fleet_thread.join(timeout=5)
            self._fleet_thread = None
            # Final flush: whatever the subtree reported last (plus this
            # relay's closing section) still reaches the root.
            self._fleet_flush()
        self._drain_forward_buffer()
        if self.spool is not None:
            if flush_timeout_s > 0:
                self.spool.flush(deadline_s=flush_timeout_s)
            self.spool.close()
        if self.up is not None:
            self.up.close()
        self.active = False

    # -- model plane (upstream subscription -> downstream fan-out) --
    def _on_upstream_model(self, version: int, blob: bytes) -> None:
        """One upstream delivery (upstream listener thread): per-hop
        verify, cache, re-broadcast VERBATIM. Chunked frames never reach
        here — the upstream agent transport's listener reassembles
        before ``on_model`` fires — and the downstream plane re-chunks
        per its own ``transport.chunk_bytes``. Isolated like the actor's
        ``_deliver_model``: the transports call ``on_model`` unguarded,
        so ANY escape here would kill the listener thread and silently
        freeze model distribution for the whole subtree."""
        try:
            self._handle_upstream_model(version, blob)
        except Exception as e:
            self._m_dropped.inc()
            print(f"[relay/{self.name}] model delivery failed "
                  f"(frame dropped): {e!r}", flush=True)

    def _handle_upstream_model(self, version: int, blob: bytes) -> None:
        from relayrl_tpu.transport.modelwire import (
            KIND_CHUNK,
            KIND_KEYFRAME,
            WireFrameError,
            is_wire_frame,
            verify_frame,
        )

        base: int | None = None
        keyframe_like = True
        if is_wire_frame(blob):
            try:
                kind, version, base = verify_frame(blob)
            except WireFrameError as e:
                # Corrupt at THIS hop: never re-broadcast rot to the
                # subtree; ask upstream for a keyframe instead.
                self._m_dropped.inc()
                print(f"[relay/{self.name}] dropped corrupt model frame: "
                      f"{e}", flush=True)
                self.up.request_resync()
                return
            if kind == KIND_CHUNK:  # listener contract violation
                self._m_dropped.inc()
                return
            keyframe_like = kind == KIND_KEYFRAME
        with self._model_lock:
            if version <= self._latest_version:
                return  # stale/duplicate delivery: never rebroadcast
            if keyframe_like:
                if is_wire_frame(blob):
                    self._keyframe = (int(version), blob)
                else:
                    # v1 full bundle: doubles as the handshake model.
                    self._handshake = (int(version), blob)
                    self._keyframe = (int(version), blob)
            self._latest = (int(version), blob, base)
            self._latest_version = int(version)
        self._rebroadcast(version, blob)

    def _rebroadcast(self, version: int, blob: bytes) -> None:
        from relayrl_tpu.telemetry import trace as trace_mod

        tracer = trace_mod.get_tracer()
        traced = tracer.enabled and tracer.sample_version(version)
        t0_ns = time.monotonic_ns() if traced else 0
        parts = (((0.0, blob),) if self._fault_model is None
                 else self._fault_model.inject(blob))
        for delay_s, part in parts:
            if delay_s > 0:
                time.sleep(delay_s)
            try:
                self.down.publish_model(int(version), part)
            except Exception as e:
                print(f"[relay/{self.name}] downstream publish failed: "
                      f"{e!r}", flush=True)
                return
            self._m_fwd_model.inc()
            self._m_bytes_model.inc(len(part))
        if traced:
            # The re-broadcast hop of a sampled version's downstream
            # trace: upstream receipt already stamped by the agent
            # transport; this span is the subtree fan-out itself.
            tracer.span("model", trace_mod.model_trace_id(version),
                        "relay", t0_ns, time.monotonic_ns(),
                        version=int(version), relay=self.name)

    def _get_model(self) -> tuple[int, bytes]:
        """Downstream handshake: the cached v1 bundle. When the relay
        has seen newer wire frames than the bundle it holds, refresh it
        from upstream (rate-limited — one root round-trip per window,
        shared by every joiner in the subtree); a refresh failure serves
        the older bundle, and the joiner catches up through the normal
        delta/resync path."""
        with self._model_lock:
            hv, hb = self._handshake
            stale = self._latest_version > hv
            due = (time.monotonic() - self._last_handshake_refresh) >= 2.0
            if stale and due:
                self._last_handshake_refresh = time.monotonic()
            else:
                stale = False
        if stale:
            try:
                version, bundle = self.up.fetch_model(timeout_s=10.0)
                with self._model_lock:
                    if version > self._handshake[0]:
                        self._handshake = (int(version), bundle)
                    hv, hb = self._handshake
            except Exception as e:
                print(f"[relay/{self.name}] handshake refresh failed "
                      f"({e!r}) — serving cached v{hv}", flush=True)
        else:
            self._m_cache_hits.inc()
        return hv, hb

    def _get_model_update(self, known_version: int) -> tuple[int, bytes]:
        """Downstream pull surface (grpc long-polls): the latest frame
        when the subscriber can decode it, else the cached keyframe
        (the subtree resync that never touches the root), else the
        handshake bundle. NEVER a blob older than ``known_version`` —
        the poll client adopts the reply's version, so a stale bundle
        would REGRESS the subscriber and re-arm its poll in a hot loop.
        When only the undecodable latest delta is newer, serve it: the
        subscriber's decoder raises a base mismatch, its explicit
        ``ver=-1`` resync re-polls, and by then the rate-limited
        handshake refresh has a current bundle."""
        with self._model_lock:
            latest = self._latest
            keyframe = self._keyframe
        if latest is not None:
            version, blob, base = latest
            if version > known_version and (base is None
                                            or base == known_version):
                return version, blob
        if (self.keyframe_cache_enabled and keyframe is not None
                and keyframe[0] > known_version):
            self._m_cache_hits.inc()
            self._m_resyncs.inc()
            return keyframe
        hv, hb = self._get_model()
        if hv > known_version or latest is None \
                or latest[0] <= known_version:
            return hv, hb
        return latest[0], latest[1]

    def _serve_subtree_resync(self, held_version: int = -1) -> None:
        """CMD_RESYNC from the subtree (downstream ROUTER thread),
        decided on the requester's held version:

        * held BELOW the cached keyframe (late joiner, long blackout):
          re-broadcast the cache — rate-limited, one re-broadcast per
          window no matter how many lanes diverged; healthy actors drop
          it as stale, the diverged ones reseed. The root is never
          touched.
        * held AT/ABOVE the cache (mid-stream divergence): the cache
          CANNOT heal it — decoders drop versions at or below their
          own — so escalate upstream (the root's forced keyframe, or a
          parent relay's same decision), rate-limited by the upstream
          transport's own request floor.
        * held unknown (-1): do both — the cache serve is free for any
          lane it can help, the escalation guarantees the heal."""
        with self._model_lock:
            keyframe = (self._keyframe if self.keyframe_cache_enabled
                        else None)
            serve = (keyframe is not None
                     and (held_version < 0 or keyframe[0] > held_version))
            escalate = (keyframe is None or held_version < 0
                        or keyframe[0] <= held_version)
            if serve:
                now = time.monotonic()
                if now - self._last_resync_serve < self.resync_min_interval_s:
                    serve = False  # coalesced into the window's serve
                else:
                    self._last_resync_serve = now
        if serve:
            self._m_resyncs.inc()
            self._m_cache_hits.inc()
            self._rebroadcast(keyframe[0], keyframe[1])
        if escalate:
            self._m_resync_escalated.inc()
            self.up.request_resync(held_version)

    # -- fleet telemetry plane (subtree frames -> one merged frame) --
    def _fleet_loop(self) -> None:
        while not self._stop.wait(self._fleet_interval_s):
            self._fleet_flush()

    def _fleet_flush(self) -> None:
        """One fan-in interval: sections the subtree updated since the
        last flush + this relay's own registry section, forwarded
        upstream as ONE frame. Sections ride VERBATIM — the root's
        epoch-aware counter baselines need the leaf's own stamps.
        Spool-less on purpose: telemetry is latest-wins, and replaying
        a retained stale snapshot would regress the root's table."""
        from relayrl_tpu import telemetry
        from relayrl_tpu.telemetry.aggregate import (
            encode_snapshot_frame,
            fleet_wire_id,
            snapshot_section,
        )

        sections = self._fleet_buf.drain()
        reg = telemetry.get_registry()
        if reg.enabled:
            self._fleet_seq += 1
            sections.append(snapshot_section(
                reg.snapshot(), self.name, "relay",
                getattr(reg, "created_unix", 0.0), self._fleet_seq))
        if not sections:
            return
        frame = encode_snapshot_frame(sections)
        try:
            self.up.send_trajectory(frame,
                                    agent_id=fleet_wire_id(self.name))
        except Exception as e:
            print(f"[relay/{self.name}] fleet forward failed (dropped; "
                  f"next interval is fresher anyway): {e!r}", flush=True)
            return
        self._m_fwd_fleet.inc()
        self._m_bytes_fleet.inc(len(frame))

    def _ingest_subtree_snapshot(self, payload: bytes) -> None:
        from relayrl_tpu.transport.base import swallow_decode_error

        try:
            self._fleet_buf.ingest_frame(payload)
        except ValueError as e:
            self._m_dropped.inc()
            swallow_decode_error(self.downstream_type, "fleet_frame", e)

    # -- trajectory plane (downstream ingest -> upstream forward) --
    def _on_subtree_trajectory(self, tagged_id: str, payload: bytes) -> None:
        """One subtree envelope (downstream transport thread). The id
        arrives with the leaf's seq tag intact and MUST leave with it
        intact — attribution and dedup belong to the leaves. Fleet
        snapshot frames (RLS1) peel off into the fan-in buffer instead
        of the forward path; with the fleet plane off they fall through
        and forward verbatim like any other opaque payload."""
        if self._fleet_buf is not None and is_snapshot_frame(payload):
            self._ingest_subtree_snapshot(payload)
            return
        from relayrl_tpu.transport.base import (
            split_agent_seq,
            split_agent_trace,
        )

        clean_id, _seq = split_agent_seq(tagged_id)
        clean_id, _trace = split_agent_trace(clean_id)
        with self._subtree_lock:
            if len(self._subtree_agents) < 65536:
                self._subtree_agents.add(clean_id)
        if self.batch_max <= 1:
            self._forward_one(tagged_id, payload)
            return
        with self._fwd_cond:
            self._fwd_buf.append((tagged_id, payload))
            self._fwd_cond.notify_all()

    def _forward_loop(self) -> None:
        """Dedicated forwarder: drains the ingest buffer into upstream
        sends, coalescing up to ``batch_max`` envelopes per send after a
        ``batch_linger_ms`` wait for siblings — the same shave the
        anakin hosts' ``actor.emit_coalesce_frames`` applies at the
        leaf, one level up."""
        while True:
            with self._fwd_cond:
                while not self._fwd_buf and not self._stop.is_set():
                    self._fwd_cond.wait(0.2)
                if self._stop.is_set() and not self._fwd_buf:
                    return
                if (len(self._fwd_buf) < self.batch_max
                        and self.batch_linger_s > 0
                        and not self._stop.is_set()):
                    deadline = time.monotonic() + self.batch_linger_s
                    while (len(self._fwd_buf) < self.batch_max
                           and not self._stop.is_set()):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._fwd_cond.wait(remaining)
                group = self._fwd_buf[:self.batch_max]
                del self._fwd_buf[:self.batch_max]
            self._flush_group(group)

    def _drain_forward_buffer(self) -> None:
        while True:
            with self._fwd_cond:
                group = self._fwd_buf[:self.batch_max]
                del self._fwd_buf[:self.batch_max]
            if not group:
                return
            self._flush_group(group)

    def _flush_group(self, group: list[tuple[str, bytes]]) -> None:
        from relayrl_tpu.transport.base import (
            BATCH_KIND_ENVELOPES,
            pack_batch,
            pack_trajectory_envelope,
        )

        if not group:
            return
        if len(group) == 1:
            self._forward_one(*group[0])
            return
        container = pack_batch(
            BATCH_KIND_ENVELOPES,
            [pack_trajectory_envelope(tid, payload)
             for tid, payload in group])
        self._m_batches.inc()
        self._m_fwd_traj.inc(len(group))
        self._m_bytes_traj.inc(len(container))
        t0_ns = time.monotonic_ns()
        if self.spool is not None:
            self.spool.send_verbatim(container, self.batch_id)
        else:
            self._try_forward(container, self.batch_id)
        for tid, _payload in group:
            self._trace_forward_span(tid, t0_ns)

    def _trace_forward_span(self, tagged_id: str, t0_ns: int) -> None:
        """Upstream-trace relay hop: a sampled trajectory's context
        rides the forwarded envelope id verbatim — peel it (without
        touching the wire id) and record this hop's forward time."""
        from relayrl_tpu.telemetry import trace as trace_mod
        from relayrl_tpu.transport.base import split_agent_seq

        tracer = trace_mod.get_tracer()
        if not tracer.enabled:
            return
        base, _seq = split_agent_seq(tagged_id)
        _clean, ctx = trace_mod.split_ctx(base)
        if ctx is None:
            return
        tracer.span("traj", ctx.trace_id, "relay", t0_ns,
                    time.monotonic_ns(), relay=self.name)

    def _forward_one(self, tagged_id: str, payload: bytes) -> None:
        self._m_fwd_traj.inc()
        self._m_bytes_traj.inc(len(payload))
        t0_ns = time.monotonic_ns()
        if self.spool is not None:
            self.spool.send_verbatim(payload, tagged_id)
        else:
            self._try_forward(payload, tagged_id)
        self._trace_forward_span(tagged_id, t0_ns)

    def _try_forward(self, payload: bytes, wire_id: str) -> None:
        """Spool-less direct forward: drop on failure, never crash the
        ingest thread (the spooled path owns retention + replay)."""
        try:
            self._wire_forward(payload, wire_id)
        except Exception as e:
            self._m_dropped.inc()
            print(f"[relay/{self.name}] upstream forward failed "
                  f"(no spool): {e!r}", flush=True)

    def _wire_forward(self, payload: bytes, wire_id: str) -> None:
        """One upstream wire attempt (the spool's send_fn) through the
        ``relay.forward`` fault site."""
        if self._fault_forward is None:
            self.up.send_trajectory(payload, agent_id=wire_id)
            return
        for delay_s, part in self._fault_forward.inject(payload):
            if delay_s > 0:
                time.sleep(delay_s)
            self.up.send_trajectory(part, agent_id=wire_id)

    # -- registry plane --
    def _on_subtree_register(self, agent_id: str) -> None:
        with self._subtree_lock:
            if len(self._subtree_agents) < 65536:
                self._subtree_agents.add(agent_id)
        # Forward so the ROOT registry still sees every logical agent
        # (best-effort: registration is observability, not correctness).
        try:
            self.up.register(agent_id, timeout_s=5.0)
        except Exception as e:
            print(f"[relay/{self.name}] upstream register {agent_id!r} "
                  f"failed: {e!r}", flush=True)

    def _on_subtree_unregister(self, agent_id: str) -> None:
        with self._subtree_lock:
            self._subtree_agents.discard(agent_id)

    def _subtree_count(self) -> int:
        with self._subtree_lock:
            return len(self._subtree_agents)

    def _on_upstream_reconnect(self) -> None:
        """Upstream heal (transport thread): re-register and replay the
        retained subtree window — leaf tags verbatim, root dedup makes
        it exactly-once. The PR 6 reconnect contract, one level up."""
        from relayrl_tpu import telemetry

        try:
            self.up.register(self.up.identity, timeout_s=5.0)
        except Exception:
            pass
        replayed = self.spool.replay() if self.spool is not None else 0
        telemetry.emit("relay_reconnect", name=self.name,
                       replayed=replayed)

    # -- operator surface --
    def stats(self) -> dict:
        return {
            "name": self.name,
            "latest_version": self._latest_version,
            "handshake_version": (self._handshake[0]
                                  if self._handshake else -1),
            "keyframe_version": (self._keyframe[0]
                                 if self._keyframe else -1),
            "subtree_agents": self._subtree_count(),
            "model_frames_forwarded": self._m_fwd_model.total(),
            "trajectory_frames_forwarded": self._m_fwd_traj.total(),
            "resyncs_served": self._m_resyncs.total(),
            "keyframe_cache_hits": self._m_cache_hits.total(),
            "frames_dropped": self._m_dropped.total(),
            "spool_depth": self.spool.depth if self.spool else 0,
        }

    def run(self, duration_s: float | None = None,
            stop_file: str | None = None, poll_s: float = 0.25) -> None:
        """Foreground loop for the ``python -m relayrl_tpu.relay``
        entrypoint: idles while the transport threads relay, honoring
        the ``relay.step`` kill_process site (the relay crash drill)
        and the stop conditions."""
        deadline = (None if duration_s is None
                    else time.monotonic() + duration_s)
        while not self._stop.is_set():
            if self._fault_step is not None \
                    and self._fault_step.take_kill_process():
                import signal

                os.kill(os.getpid(), signal.SIGKILL)
            if deadline is not None and time.monotonic() >= deadline:
                return
            if stop_file is not None and os.path.exists(stop_file):
                return
            time.sleep(poll_s)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


__all__ = ["RelayNode"]
