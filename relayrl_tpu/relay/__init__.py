"""Hierarchical relay tree (ROADMAP item 2 / ISSUE 11).

:class:`RelayNode` is one hop: it subscribes ONCE upstream (training
server or parent relay), re-broadcasts verbatim model frames to its own
fan-out plane, and batch-forwards + spools the subtree's trajectory
envelopes upstream — turning both distribution planes into a tree so
the root's publish cost is O(relays), not O(actors).

``python -m relayrl_tpu.relay`` runs one as a process.
"""

from relayrl_tpu.relay.node import RelayNode  # noqa: F401

__all__ = ["RelayNode"]
