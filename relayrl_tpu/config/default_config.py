"""Embedded default configuration.

Schema parity with the reference's embedded default
(reference: relayrl_framework/src/default_config.json and the
DEFAULT_CONFIG_CONTENT string in src/sys_utils/config_loader.rs:66-113):
per-algorithm hyperparams, three endpoint addresses, model paths, tensorboard
settings, max trajectory length. TPU-native additions live under "learner"
(mesh/batching knobs absent from the reference, which has no device story).

Model artifacts are `.rlx` ModelBundles (params + arch + version), not
TorchScript `.pt`.
"""

from __future__ import annotations

import copy

DEFAULT_CONFIG: dict = {
    "algorithms": {
        "REINFORCE": {
            "discrete": True,
            "with_vf_baseline": False,
            "seed": 1,
            "traj_per_epoch": 8,
            "gamma": 0.98,
            "lam": 0.97,
            "pi_lr": 3e-4,
            "vf_lr": 1e-3,
            "train_vf_iters": 80,
            "hidden_sizes": [128, 128],
        },
        "PPO": {
            "discrete": True,
            "seed": 1,
            "traj_per_epoch": 8,
            "gamma": 0.99,
            "lam": 0.95,
            "clip_ratio": 0.2,
            "pi_lr": 3e-4,
            "vf_lr": 1e-3,
            "train_iters": 4,
            "minibatch_count": 4,
            "ent_coef": 0.0,
            "vf_coef": 0.5,
            "target_kl": 0.015,
            "hidden_sizes": [128, 128],
        },
    },
    "grpc_idle_timeout_s": 30.0,
    "max_traj_length": 1000,
    "model_paths": {
        "client_model": "client_model.rlx",
        "server_model": "server_model.rlx",
    },
    "server": {
        "training_server": {"prefix": "tcp://", "host": "127.0.0.1", "port": "50051"},
        "trajectory_server": {"prefix": "tcp://", "host": "127.0.0.1", "port": "7776"},
        "agent_listener": {"prefix": "tcp://", "host": "127.0.0.1", "port": "7777"},
    },
    "training_tensorboard": {
        "launch_tb_on_startup": False,
        "scalar_tags": "AverageEpRet;LossPi",
        "global_step_tag": "Epoch",
    },
    "learner": {
        "batch_trajectories": 8,
        "bucket_lengths": [64, 256, 1000],
        "mesh": {"dp": -1, "fsdp": 1, "tp": 1, "sp": 1},
        # compute dtype for policy trunks: float32 on CPU actors/tests;
        # set "bfloat16" on TPU learners to feed the MXU (bench configs do).
        "precision": "float32",
        "checkpoint_dir": "checkpoints",
        "checkpoint_every_epochs": 10,
    },
}

# Algorithm whitelist, matching the reference's registry
# (config_loader.rs:397-433 lists C51/DDPG/DQN/PPO/REINFORCE/SAC/TD3 even
# though only REINFORCE is implemented there).
SUPPORTED_ALGORITHMS = ("C51", "DDPG", "DQN", "PPO", "REINFORCE", "SAC", "TD3")


def default_config() -> dict:
    return copy.deepcopy(DEFAULT_CONFIG)
