"""Embedded default configuration.

Schema parity with the reference's embedded default
(reference: relayrl_framework/src/default_config.json and the
DEFAULT_CONFIG_CONTENT string in src/sys_utils/config_loader.rs:66-113):
per-algorithm hyperparams, three endpoint addresses, model paths, tensorboard
settings, max trajectory length. TPU-native additions live under "learner"
(mesh/batching knobs absent from the reference, which has no device story).

Model artifacts are `.rlx` ModelBundles (params + arch + version), not
TorchScript `.pt`.
"""

from __future__ import annotations

import copy

DEFAULT_CONFIG: dict = {
    "algorithms": {
        "REINFORCE": {
            "discrete": True,
            "with_vf_baseline": False,
            "seed": 1,
            "traj_per_epoch": 8,
            "gamma": 0.98,
            "lam": 0.97,
            "pi_lr": 3e-4,
            "vf_lr": 1e-3,
            "train_vf_iters": 80,
            "hidden_sizes": [128, 128],
        },
        "PPO": {
            "discrete": True,
            "seed": 1,
            "traj_per_epoch": 8,
            "gamma": 0.99,
            "lam": 0.95,
            "clip_ratio": 0.2,
            "pi_lr": 3e-4,
            "vf_lr": 1e-3,
            "train_iters": 4,
            "minibatch_count": 4,
            "ent_coef": 0.0,
            "vf_coef": 0.5,
            "target_kl": 0.015,
            "hidden_sizes": [128, 128],
        },
        "DQN": {
            "discrete": True,
            "seed": 1,
            "gamma": 0.99,
            "lr": 1e-3,
            "batch_size": 256,
            "buffer_size": 100_000,
            "update_after": 1000,
            "updates_per_step": 1.0,
            "updates_per_dispatch": 1,
            "polyak": 0.995,
            "double_q": True,
            "epsilon_start": 1.0,
            "epsilon_end": 0.05,
            "epsilon_decay_steps": 10_000,
            "traj_per_epoch": 8,
            "hidden_sizes": [128, 128],
        },
        "C51": {
            "discrete": True,
            "seed": 1,
            "gamma": 0.99,
            "lr": 1e-3,
            "batch_size": 256,
            "buffer_size": 100_000,
            "update_after": 1000,
            "updates_per_step": 1.0,
            "updates_per_dispatch": 1,
            "polyak": 0.995,
            "n_atoms": 51,
            "v_min": -10.0,
            "v_max": 10.0,
            "epsilon_start": 1.0,
            "epsilon_end": 0.05,
            "epsilon_decay_steps": 10_000,
            "traj_per_epoch": 8,
            "hidden_sizes": [128, 128],
        },
        "DDPG": {
            "discrete": False,
            "seed": 1,
            "gamma": 0.99,
            "pi_lr": 1e-3,
            "q_lr": 1e-3,
            "batch_size": 256,
            "buffer_size": 100_000,
            "update_after": 1000,
            "updates_per_step": 1.0,
            "updates_per_dispatch": 1,
            "polyak": 0.995,
            "act_limit": 1.0,
            "act_noise": 0.1,
            "traj_per_epoch": 8,
            "hidden_sizes": [128, 128],
        },
        "TD3": {
            "discrete": False,
            "seed": 1,
            "gamma": 0.99,
            "pi_lr": 1e-3,
            "q_lr": 1e-3,
            "batch_size": 256,
            "buffer_size": 100_000,
            "update_after": 1000,
            "updates_per_step": 1.0,
            "updates_per_dispatch": 1,
            "polyak": 0.995,
            "act_limit": 1.0,
            "act_noise": 0.1,
            "target_noise": 0.2,
            "noise_clip": 0.5,
            "policy_delay": 2,
            "traj_per_epoch": 8,
            "hidden_sizes": [128, 128],
        },
        "IMPALA": {
            "discrete": True,
            "seed": 1,
            "traj_per_epoch": 16,
            "gamma": 0.99,
            "lr": 3e-4,
            "vf_coef": 0.5,
            "ent_coef": 0.01,
            "rho_bar": 1.0,
            "c_bar": 1.0,
            "max_grad_norm": 40.0,
            "hidden_sizes": [128, 128],
        },
        "SAC": {
            "discrete": False,
            "seed": 1,
            "gamma": 0.99,
            "pi_lr": 3e-4,
            "q_lr": 3e-4,
            "alpha_lr": 3e-4,
            "alpha": 0.2,
            "batch_size": 256,
            "buffer_size": 100_000,
            "update_after": 1000,
            "updates_per_step": 1.0,
            "updates_per_dispatch": 1,
            "polyak": 0.995,
            "act_limit": 1.0,
            "traj_per_epoch": 8,
            "hidden_sizes": [128, 128],
        },
    },
    "grpc_idle_timeout_s": 30.0,
    "max_traj_length": 1000,
    # -- actor plane (docs/architecture.md "actor topology") --
    "actor": {
        # Environment lanes per actor process. 1 = the reference's
        # one-env-per-process shape; >1 turns the process into a vector
        # actor host: one batched jitted policy step serves num_envs
        # logical agents over a single transport connection
        # (runtime/vector_actor.py). The north-star "64 actors" row runs
        # as e.g. 4 processes x 16 lanes instead of 64 processes.
        "num_envs": 1,
        # "process" = one Agent per env (reference parity);
        # "vector" = VectorAgent host stepping num_envs lanes;
        # "anakin" = fused on-device rollout (runtime/anakin.py): the env
        # itself runs as pure JAX (actor.jax_env) and one
        # jit(vmap(lax.scan)) dispatch produces num_envs x unroll_length
        # env steps — the fastest tier, for envs in the JAX registry;
        # "remote" = thin client (runtime/inference.py
        # RemoteActorClient): no local params or model subscription —
        # actions come from the serving plane (serving.enabled on the
        # training server), the "millions of users" topology.
        # examples/train_distributed.py reads it to pick the actor
        # topology (--num-envs overrides); benches/bench_soak.py's
        # --vector/--anakin flags are the bench-plane equivalents.
        "host_mode": "process",
        # -- anakin tier (actor.host_mode: "anakin") --
        # Env steps per lane per fused dispatch: each dispatch returns a
        # [num_envs, unroll_length] trajectory window. Bigger amortizes
        # the dispatch further but widens the model-staleness window (a
        # hot-swap lands between windows, never inside one) and the
        # host-side unstack burst. 32 is past the knee of the committed
        # scaling curve (benches/results/anakin_rollout.json).
        "unroll_length": 32,
        # On-device env id for the anakin tier, resolved through the JAX
        # env registry (envs/jax/__init__.py; see envs.list_envs()).
        "jax_env": "CartPole-v1",
        # Rolling observation-window rows for sequence policies
        # (windowed transformers), shared by every tier that serves
        # them: the vector host's stacked per-lane windows, the serving
        # plane's session windows, and the anakin scan carry. null (the
        # default) uses the model's full serving context
        # (min(actor_context, max_seq_len)); an explicit value narrows
        # it — it is clamped to [1, model context], never widened.
        # Narrower windows cut the fused step's attention cost
        # (O(W^2 d) per step) at the price of shorter memory.
        "window_size": None,
        # Anakin host shave (ROADMAP item 1): move the frame
        # encode/unstack + send onto a dedicated emitter thread so it
        # overlaps the next window's device dispatch (bounded depth-2
        # hand-off — a slow wire backpressures the rollout loop).
        # Worth it when host_share_of_wall is high and a spare core
        # exists; single-core hosts should leave it off. False is the
        # MEASURED default: the committed A/B
        # (benches/results/anakin_rollout.json,
        # speedup_async_emit_vs_sync) shows 0.89-1.18x (median ~0.97)
        # on the soak host — the hand-off overhead eats the overlap
        # when rollout and emitter share a core.
        "async_emit": False,
        # Coalesce up to this many completed columnar segments (per
        # logical lane, per rollout window) into ONE transport send —
        # the ROADMAP item 5 host-emit shave: short-episode envs can
        # complete many segments per window, and each send pays the
        # envelope + spool + socket path. 1 keeps the one-frame-per-send
        # behavior; relays batch-forward the same container upstream
        # (relay.batch_max), so the framing helper is shared. 1 is the
        # MEASURED default: the committed A/B (anakin_rollout.json,
        # speedup_emit_coalesce_vs_single) is neutral at 0.87-1.13x
        # (median ~0.99) on CartPole-length episodes — raise it only
        # when episodes are much shorter than unroll_length AND the
        # per-send envelope cost shows up in host_share_of_wall.
        "emit_coalesce_frames": 1,
        # Trajectory wire form. "auto" (the default) picks per tier:
        # anakin hosts ship whole rollout segments as contiguous columnar
        # frames (types/columnar.py — decoded server-side straight into
        # the staging slabs, no per-step objects or per-record msgpack
        # on either end); process/vector hosts keep the per-record
        # ActionRecord wire (their steps are host-bound anyway). true /
        # false force the form on anakin hosts (false = rolling compat
        # with pre-columnar servers).
        "columnar_wire": "auto",
        # -- trajectory spool (runtime/spool.py, crash-recovery plane) --
        # Outbound trajectories are retained in a bounded window and
        # replayed on reconnect; the server's sequence-number dedup makes
        # the replay exactly-once. spool_entries=0 disables the spool
        # entirely (sends go straight to the transport, untagged — the
        # pre-recovery wire shape).
        "spool_entries": 512,
        "spool_bytes": 67108864,  # 64 MiB retained-payload bound
        # Directory for the file-backed spool (survives an actor process
        # crash — the restarted actor replays what the dead one had in
        # flight). null = in-memory only.
        "spool_dir": None,
    },
    # -- transport plane (docs/operations.md knob table) --
    "transport": {
        # Native-transport liveness cadence: the agent pings the control
        # channel every heartbeat_s from its SUB thread (detects a dead
        # server and heals the connection C++-side; the server's idle
        # reaper keys off the same traffic). Was a hard-coded 5.0 in
        # native_bindings.start_model_listener. <= 0 disables the beat.
        "heartbeat_s": 5.0,
        # -- model-wire v2 (transport/modelwire.py, docs/architecture.md
        #    "model distribution") --
        # 2 = delta-compressed per-leaf publish frames with periodic
        # keyframes; 1 = the legacy full-ModelBundle blob every publish
        # (the rolling-compat escape hatch — v2 actors still decode it).
        "wire_version": 2,
        # Every Nth publish is a full keyframe; it bounds how long a
        # broadcast subscriber that missed a delta (drop, late join)
        # stays stale before resyncing. <= 1 makes every frame a
        # keyframe (== v1 bytes, framed).
        "keyframe_interval": 10,
        # Per-frame payload codec: "auto" walks zstd > lz4 > zlib
        # (stdlib; Z_RLE strategy for delta planes), a codec name pins
        # it, false/"none" ships raw. Incompressible payloads are
        # skipped automatically; the codec id rides the frame header.
        "compress": "auto",
        # Models whose raw params are smaller than this ship as v1
        # passthrough instead of delta frames (at two-packet sizes the
        # encode work only costs publish→swap latency — the measured PR 5
        # policy). null = the encoder's built-in 256 KiB. Scenarios that
        # must measure delta-plane accounting (frozen-leaf savings) on a
        # small model set 0 to force the delta path.
        "small_model_bytes": None,
        # Split broadcast frames larger than this many bytes into
        # ordered chunk frames (ZMQ HWM-friendly bounded messages; the
        # native plane passes them through opaquely and Python listeners
        # reassemble). 0 disables chunking.
        "chunk_bytes": 0,
        # Broadcast-plane resync requests (CMD_RESYNC): a diverged
        # subscriber asks the publisher to make its NEXT publish a
        # keyframe (blackout <= 1 publish instead of <= the interval).
        # Requests inside this window of an already-granted force
        # coalesce away — one subtree-wide divergence storm costs one
        # keyframe.
        "resync_min_interval_s": 0.25,
        # -- unified retry/backoff (transport/retry.py) --
        # One policy drives every bounded retry loop on the agent side
        # (handshake, connect, spooled sends): jittered exponential
        # backoff base*multiplier^k capped at max_delay_s, bounded by
        # deadline_s per op (max_attempts=0 = deadline-only). The breaker
        # knobs bound how fast a dead learner trips send paths into
        # spool-only mode and how often a half-open probe retests it.
        "retry": {
            "base_delay_s": 0.05,
            "max_delay_s": 2.0,
            "multiplier": 2.0,
            "jitter": 0.5,
            "deadline_s": 30.0,
            "max_attempts": 0,
            "breaker_threshold": 3,
            "breaker_reset_s": 2.0,
        },
    },
    # -- training-health guardrails (relayrl_tpu/guardrails/,
    #    docs/operations.md "Training-health guardrails") --
    "guardrails": {
        # false = no guardrail object is built at all: ingest validation,
        # quarantine, watchdog, rollback, and backpressure all disappear
        # and every hook site costs one identity check (the telemetry/
        # faults process-model precedent).
        "enabled": True,
        # Ingest validation posture: "enforce" rejects invalid
        # trajectories before they touch the staging slabs; "warn"
        # counts + strikes but ADMITS them (observe-only — the
        # defense-in-depth drill posture; also stands the per-algorithm
        # finite guard down); "off" skips validation entirely.
        "ingest_validation": "enforce",
        # Per-trajectory length bound for the validator; null derives
        # from max_traj_length.
        "max_steps": None,
        # -- poison-agent quarantine --
        # Strikes (validation rejections) within strike_window_s before
        # an agent is quarantined; quarantined sends are rejected (typed
        # nack on ack-capable transports) until the cooldown paroles it.
        "strike_threshold": 3,
        "strike_window_s": 60.0,
        "quarantine_cooldown_s": 300.0,
        # -- divergence watchdog --
        "watchdog": True,
        # Device-side probes merged into each update's metrics (resolved
        # lazily at the in-flight fence; observers — bit-identical
        # params on vs off). update_norm_probe adds a pre-update D2D
        # params copy to compute ||new - old|| (the grad-norm proxy).
        "probes": True,
        "update_norm_probe": True,
        # Trip thresholds; 0/null disables that detector. param-norm
        # and update-norm are global L2 over float leaves.
        "max_param_norm": 1000000.0,
        "max_update_norm": 0,
        # Loss spike: |loss| beyond factor x rolling-median(loss_window)
        # trips; loss_key "auto" picks LossPi/LossQ/Loss. 0 = off
        # (non-finite loss always trips while the watchdog is on).
        "loss_spike_factor": 0,
        "loss_window": 16,
        "loss_key": "auto",
        # Reward collapse: rolling mean (reward_window trajectories)
        # dropping more than this many reward units below its best trips
        # the watchdog. Workload-specific — 0 = off by default.
        "reward_collapse_drop": 0,
        "reward_window": 32,
        # -- last-known-good auto-rollback --
        "rollback": True,
        # Retained checkpoints (the ring the rollback searches for the
        # newest healthy-tagged step); raises the effective orbax
        # max_to_keep to at least this.
        "checkpoint_ring": 5,
        # Rollbacks allowed within rollback_window_s before guardrails
        # degrade to halt-and-alarm (training stops, process survives).
        "max_rollbacks": 3,
        "rollback_window_s": 600.0,
        # -- ingest backpressure --
        # Soft admission bound on the raw ingest queue (the 100k hard
        # cap is the OOM guard, not a policy). 0 disables backpressure.
        "ingest_soft_limit": 8192,
        # "drop_oldest" evicts the globally oldest queued trajectory
        # (freshest-wins; the victim's seq is retracted so spool replay
        # can redeliver) | "nack" refuses the arrival with a typed
        # retry-after where the transport can answer.
        "shed_policy": "drop_oldest",
        # One agent may hold at most this fraction of the soft limit;
        # beyond it the agent sheds its OWN arrivals (flood fairness).
        "agent_share": 0.5,
        "nack_retry_after_s": 1.0,
    },
    # -- disaggregated batched-inference serving plane
    #    (runtime/inference.py, docs/architecture.md "serving tier") --
    "serving": {
        # false = no InferenceService is built: the training server
        # serves no action plane and thin clients cannot connect.
        "enabled": False,
        # Batch close triggers (TorchBeast's dynamic-batching server):
        # a batch closes at max_batch requests OR batch_timeout_ms after
        # its first request enqueued, whichever fires first. Bigger
        # batches amortize the dispatch; the timeout bounds worst-case
        # action latency (see docs/operations.md sizing note).
        "max_batch": 16,
        "batch_timeout_ms": 5.0,
        # Compiled batch shapes (pick_bucket): null derives powers of
        # two up to max_batch. Short batches pad to the nearest bucket
        # (pad rows are sliced off; vmap rows are independent).
        "buckets": None,
        # Requests allowed to wait in the batching queue; beyond it new
        # arrivals nack NACK_OVERLOADED with retry_after_s — bounded
        # queue = bounded worst-case latency, and an inference flood
        # cannot starve the learner's ingest plane.
        "queue_limit": 1024,
        "retry_after_s": 0.05,
        # Ghost-work guard: a queued request older than this was
        # abandoned by its timed-out client (whose retry is already
        # queued behind it) — it is nacked unserved at batch-gather
        # time instead of double-serving every retry round under
        # backlog. Keep it above request_timeout_s. 0 disables.
        "stale_after_s": 5.0,
        # Thin-client budgets: per-attempt wire timeout, and the total
        # per-action budget (covers a service restart window before the
        # env loop gives up).
        "request_timeout_s": 2.0,
        "infer_deadline_s": 60.0,
        # -- serving v2: sessions / streaming / replicas --
        # Server-side session table (sequence policies): one rolling
        # observation window per client session, LRU-evicted past
        # max_sessions and reaped after session_ttl_s idle. Eviction is
        # a resync, not a failure — the client answers the typed
        # NACK_SESSION_EVICTED by resending its episode window. Size it
        # to the concurrent-client count; each session costs
        # ctx * obs_dim float32s.
        "max_sessions": 4096,
        "session_ttl_s": 600.0,
        # Streamed channel: in-flight requests per client connection
        # before the multiplexing client stops submitting and drains —
        # bounds client-side memory and keeps a dead service from
        # swallowing an unbounded pipeline.
        "stream_window": 32,
        # Horizontal serving: list of replica serving endpoints (e.g.
        # ["tcp://hostA:6671", "tcp://hostB:6671"]). null = single
        # endpoint (server.inference_server). Clients route
        # session-affine by crc32(session_id) % len(replicas) and
        # rotate + resync on replica death.
        "replicas": None,
    },
    # -- hierarchical relay tree (relayrl_tpu/relay/,
    #    docs/architecture.md "relay tree") --
    "relay": {
        # false = this process is not a relay. A relay stands between
        # the training server (or a parent relay) and an actor subtree:
        # it subscribes ONCE upstream and re-broadcasts verbatim model
        # frames to its own fan-out plane (publisher cost becomes
        # O(relays), not O(actors)), and batch-forwards the subtree's
        # trajectory envelopes upstream over one connection with its
        # own spool (a relay crash is the PR 6 drill one level up).
        # Start one with `python -m relayrl_tpu.relay`.
        "enabled": False,
        # Operator-visible relay name (telemetry run id, logs); null
        # derives one from pid.
        "name": None,
        # Upstream (parent) endpoint: the transport kind plus the same
        # agent-side address overrides an actor would use to reach the
        # parent (zmq: agent_listener_addr/trajectory_addr/
        # model_sub_addr; grpc/native: server_addr). Empty = the
        # config's server.* endpoints — i.e. the root training server.
        "upstream_type": "zmq",
        "upstream": {},
        # Downstream (fan-out) plane this relay BINDS for its subtree.
        # Actors point their normal transport config at these addresses
        # — a relay is indistinguishable from a training server on the
        # wire. fanout_port > 0 binds the zmq triple at three
        # consecutive ports (listener, trajectory, model pub); the
        # "downstream" dict overrides individual addresses instead.
        "downstream_type": "zmq",
        "fanout_port": 0,
        "downstream": {},
        # Serve subtree resyncs and late joiners from the relay's cached
        # keyframe (false = forward every resync upstream — only useful
        # for measuring what the cache saves).
        "keyframe_cache": True,
        # Batch-forward: coalesce up to batch_max subtree envelopes
        # (waiting at most batch_linger_ms for siblings) into one
        # upstream send. 1 forwards each envelope individually.
        "batch_max": 8,
        "batch_linger_ms": 5.0,
        # The relay's own trajectory spool (runtime/spool.py), retained
        # at BATCH granularity with leaf seq tags carried verbatim:
        # size it >= the subtree's in-flight window (docs/operations.md
        # sizing rule). spool_dir makes it survive a relay crash.
        "spool_entries": 2048,
        "spool_bytes": 134217728,  # 128 MiB
        "spool_dir": None,
        # Rate limit for serving cached-keyframe resyncs downstream
        # (one re-broadcast per window, shared by the whole subtree).
        "resync_min_interval_s": 0.25,
    },
    # -- RLHF workload plane (relayrl_tpu/rlhf/, docs/operations.md
    #    "RLHF workload plane") --
    "rlhf": {
        # Token-level generation env knobs (envs/tokengen.py + the pure-
        # JAX twin): vocabulary INCLUDING the reserved EOS/pad token 0,
        # sampled-prompt length, and the generation budget per episode.
        "vocab_size": 8,
        "prompt_len": 3,
        "max_new_tokens": 8,
        # Terminal-boundary scorer: "programmatic" (all-integer
        # successor-pattern count — the CI scorer) or "reward_model"
        # (frozen randomly-initialized transformer critic holding its
        # OWN params — rlhf/scorers.py; rm_* size it, rm_seed fixes it
        # so the score stage and any self-contained env agree).
        "scorer": "programmatic",
        "rm_d_model": 32,
        "rm_n_layers": 1,
        "rm_seed": 7,
        # Generation lanes per scheduler (the vector host's batched
        # step_window width for sequence policies).
        "lanes": 4,
        # "vector" = local batched generation (sequence policies: the
        # vmapped step_window path); "anakin" = fused on-device
        # generation (runtime/anakin.py): TokenGen-v0 runs inside the
        # lax.scan with the rolling-window carry, so generate throughput
        # is fused tokens/s instead of per-step round-trips — per-token
        # logp_a/bver evidence still rides each record and episodes
        # still withhold/score/re-inject through the interceptor seam;
        # "remote" = thin clients against the serving plane
        # (serving.enabled on the training server) — sequence policies
        # serve through the per-session window table; keep
        # serving.max_sessions at or above the lane count.
        "generation_tier": "vector",
        # Fused-tier scan length: env steps (= tokens) per lane per
        # rollout dispatch when generation_tier is "anakin". One
        # dispatch emits `lanes x generation_unroll` tokens under ONE
        # behavior version, so this is the burst size the pacing loop
        # and the learner's queue see — a whole actor.unroll_length
        # window (32) at short TokenGen episodes is ~50-100 episodes
        # per burst, which blows straight through
        # max_episodes_per_version inside a single dispatch and trains
        # the learner on 100+-version-stale data. Keep it near
        # max_new_tokens (about one episode per lane per dispatch);
        # raise it only if dispatch overhead dominates generate time.
        "generation_unroll": 8,
        # Bounded-staleness pacing: once this many episodes have been
        # scored under ONE behavior version, generation pauses until a
        # newer model swap lands (or pace_timeout_s passes — a dead
        # learner must not wedge the scheduler; the episodes still ship
        # and V-trace corrects what lag remains). Unthrottled generation
        # on a fast actor host can outrun the learner by 10-30x, burning
        # episodes against a stale policy; the clipped-rho correction
        # tolerates lag, it does not make free throughput of it. 0
        # disables pacing.
        "max_episodes_per_version": 64,
        "pace_timeout_s": 5.0,
        # Score stage: completed generations per batched scorer dispatch
        # (padded to this size so the jitted vmap compiles once), and
        # the bound on episodes parked between generate and score
        # (backpressure: generation blocks rather than grow unbounded).
        "score_batch": 8,
        "score_queue": 256,
    },
    # -- observability (relayrl_tpu/telemetry/, docs/observability.md) --
    "telemetry": {
        # false = the process-global registry stays a NullRegistry: every
        # instrumentation site holds a no-op metric and the hot-path cost
        # is a single attribute call (benches/bench_telemetry.py).
        "enabled": False,
        # Exporter port for /metrics (Prometheus text) + /snapshot
        # (JSON), served by the training-server process; 0 binds an
        # ephemeral port (logged at startup).
        "port": 9100,
        "host": "127.0.0.1",
        # NDJSON run-event journal (model publish/swap, agent register/
        # unregister/reconnect, drop, checkpoint, drain). null disables.
        "events_path": None,
        # Size bound for the journal: past this many bytes the file
        # rotates once to `<events_path>.1` (torn-tail-tolerant across
        # the boundary; read_events stitches both generations), so
        # multi-hour soaks and the trace-span NDJSON export can't grow
        # it unbounded. 0 = no rotation.
        "events_max_bytes": 0,
        # Run identity stamped on every snapshot and journal line; null
        # derives one from pid + start time.
        "run_id": None,
        # Distributed tracing (telemetry/trace.py): the fraction of
        # trajectories/versions that draw a trace context (0 = the null
        # tracer, every span site a single attribute check; 1 = trace
        # everything — drills and tests). Sampled trajectory contexts
        # ride the envelope id beside the #s seq tag; model versions
        # sample by a deterministic hash so every process agrees.
        "trace_sample_rate": 0.0,
        # Flight-recorder capacity (spans, oldest evicted) behind the
        # /traces endpoint and the Chrome-trace dump.
        "trace_ring": 4096,
        # Fleet aggregation (telemetry/aggregate.py): every process's
        # registry ships a compact snapshot frame through its agent
        # transport (beside trajectories, no new socket) at this
        # cadence; relays merge their subtree's frames so root ingest
        # is O(relays); the root training server holds the fleet table
        # behind /fleet + /fleet/metrics and evaluates the SLO alert
        # rules each interval. 0 (the default) disables the plane —
        # the trace_sample_rate opt-in convention.
        "fleet_interval_s": 0.0,
        # A proc silent this long leaves the fleet table (its counters
        # leave the merged totals with it — eviction, not restart).
        "fleet_stale_s": 15.0,
        # SLO alert rules evaluated at the root over the MERGED fleet
        # snapshot: a list of {name, metric, agg, op, threshold, for_s,
        # labels} objects (docs/observability.md "Fleet aggregation"
        # has the syntax). null = just the default pack below.
        "alerts": None,
        # false drops the stock rule pack (drops / breaker open /
        # guardrail halt / non-finite publish blocked / ingest queue
        # depth / trace data-age p95) and runs only telemetry.alerts.
        "alerts_default_pack": True,
    },
    "model_paths": {
        "client_model": "client_model.rlx",
        "server_model": "server_model.rlx",
    },
    "server": {
        "training_server": {"prefix": "tcp://", "host": "127.0.0.1", "port": "50051"},
        "trajectory_server": {"prefix": "tcp://", "host": "127.0.0.1", "port": "7776"},
        "agent_listener": {"prefix": "tcp://", "host": "127.0.0.1", "port": "7777"},
        # Serving-plane action channel (zmq ROUTER/DEALER; also the
        # native fleets' passthrough plane — grpc fleets ride the
        # in-band GetActions RPC on training_server instead).
        "inference_server": {"prefix": "tcp://", "host": "127.0.0.1", "port": "7778"},
    },
    "training_tensorboard": {
        "launch_tb_on_startup": False,
        "scalar_tags": "AverageEpRet;LossPi",
        "global_step_tag": "Epoch",
    },
    "learner": {
        "bucket_lengths": [64, 256, 1000],
        # Frozen-layer optimizer mask (the RLHF fine-tune recipe,
        # algorithms/freeze.py): a regex — or list of regexes — matched
        # against "/"-joined param leaf paths (e.g.
        # "params/(obs_embed|pos_embed|block_[01])/"); matching leaves
        # go to optax.set_to_zero via multi_transform, so they never
        # move, stay bit-identical across updates, and cost zero bytes
        # on the wire-v2 delta plane (counted in publish_bytes_saved).
        # Validated at config load; recorded in every checkpoint's
        # extras and enforced equal on resume. null disables.
        "freeze": None,
        "mesh": {"dp": -1, "fsdp": 1, "ep": 1, "tp": 1, "sp": 1, "pp": 1},
        # compute dtype for policy trunks: float32 on CPU actors/tests;
        # set "bfloat16" on TPU learners to feed the MXU (bench configs do).
        "precision": "float32",
        "checkpoint_dir": "checkpoints",
        "checkpoint_every_epochs": 10,
        # Replay-buffer snapshot cadence (off-policy): the ring copy is a
        # synchronous host memcpy on the learner thread, ~buffer_size ×
        # transition_bytes per save — raise this for big buffers so only
        # every Nth periodic checkpoint carries experience.
        "checkpoint_aux_every": 1,
        # -- pipelined learner hot path (docs/architecture.md) --
        # Dispatched-but-unfenced updates the learner thread may run
        # ahead of the device; 0 restores the synchronous fence-every-
        # update behavior (and shrinks the staging-slab ring to 1).
        "max_inflight_updates": 2,
        # Model publish (params gather + serialize + socket + artifact
        # write) on a dedicated latest-wins thread; false publishes
        # synchronously on the learner thread.
        "async_publish": True,
        # jax.device_put assembled batches at dispatch time so the H2D
        # copy overlaps in-flight device compute.
        "device_prefetch": True,
        # Ingest decode workers feeding the learner thread (the native
        # decoder drops the GIL, so extra workers scale on real cores).
        "ingest_staging_threads": 1,
        # Idempotent-ingest dedup window (runtime/spool.SequenceLedger):
        # per-agent out-of-order tolerance for sequence-tagged
        # trajectories; replays beyond max_seq - window drop as
        # duplicates. 0 disables dedup (every tagged send trains).
        "ingest_dedup_window": 4096,
        # multi-host learner bring-up (jax.distributed); single-process when
        # coordinator is null. Env overrides: RELAYRL_COORDINATOR,
        # RELAYRL_NUM_PROCESSES. The per-host rank is deliberately NOT a
        # config key (configs are shared between hosts): set
        # RELAYRL_PROCESS_ID per host or pass process_id= explicitly.
        "distributed": {
            "coordinator": None,
            "num_processes": 1,
        },
    },
}

# Algorithm whitelist, matching the reference's registry
# (config_loader.rs:397-433 lists C51/DDPG/DQN/PPO/REINFORCE/SAC/TD3 even
# though only REINFORCE is implemented there).
SUPPORTED_ALGORITHMS = (
    "C51", "DDPG", "DQN", "IMPALA", "PPO", "REINFORCE", "SAC", "TD3",
)


def default_config() -> dict:
    return copy.deepcopy(DEFAULT_CONFIG)
