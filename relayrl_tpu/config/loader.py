"""JSON config loader.

Capability parity with the reference's ``ConfigLoader``
(reference: relayrl_framework/src/sys_utils/config_loader.rs:229-555 and the
auto-create macros at :30-58): loads `relayrl_config.json`, auto-creates it
from the embedded default when missing, exposes per-algorithm hyperparams,
three endpoint addresses, tensorboard params, model paths and
max_traj_length, with hardcoded fallbacks when keys are absent.

Departures (SURVEY.md §7.5):
* ``grpc_idle_timeout_s`` is seconds and used as seconds — the reference's
  config says 30 (seconds) but feeds it to a millisecond timeout
  (default_config.json:15 vs training_grpc.rs:757).
* client/server model-path fallbacks are not swapped
  (config_loader.rs:504-534 returns them crossed).
* auto-create is opt-out via ``create_if_missing=False`` for processes that
  must not write to cwd.
"""

from __future__ import annotations

import copy
import json
import os
from pathlib import Path
from typing import Any, Mapping

from relayrl_tpu.config.default_config import (
    DEFAULT_CONFIG,
    SUPPORTED_ALGORITHMS,
    default_config,
)

DEFAULT_CONFIG_FILENAME = "relayrl_config.json"

#: (config_path, dotted_key) pairs already warned about — unknown-key
#: warnings fire once per process per file, not once per ConfigLoader
#: (a server + N agents in one process would otherwise repeat them).
_warned_unknown_keys: set[tuple[str, str]] = set()


def _closest(key: str, candidates) -> str | None:
    """Nearest known key for the typo hint, or None when nothing close."""
    import difflib

    matches = difflib.get_close_matches(key, [str(c) for c in candidates],
                                        n=1, cutoff=0.6)
    return matches[0] if matches else None


class Endpoint:
    """One server address `{prefix, host, port}`
    (ref schema: config_loader.rs:161-179)."""

    def __init__(self, prefix: str = "tcp://", host: str = "127.0.0.1", port: str | int = "0"):
        self.prefix = prefix
        self.host = host
        self.port = str(port)

    @property
    def address(self) -> str:
        return f"{self.prefix}{self.host}:{self.port}"

    @property
    def host_port(self) -> str:
        return f"{self.host}:{self.port}"

    def __repr__(self) -> str:
        return f"Endpoint({self.address!r})"

    @classmethod
    def from_dict(cls, d: Mapping[str, Any], fallback: "Endpoint") -> "Endpoint":
        return cls(
            prefix=str(d.get("prefix", fallback.prefix)),
            host=str(d.get("host", fallback.host)),
            port=str(d.get("port", fallback.port)),
        )


_FALLBACK_ENDPOINTS = {
    "training_server": Endpoint(port="50051"),
    "trajectory_server": Endpoint(port="7776"),
    "agent_listener": Endpoint(port="7777"),
    "inference_server": Endpoint(port="7778"),
}


class ConfigLoader:
    """Load + query the framework config (ref: ConfigLoader::new + getters,
    config_loader.rs:241-297, 344-381)."""

    def __init__(
        self,
        algorithm_name: str | None = None,
        config_path: str | os.PathLike | None = None,
        create_if_missing: bool = True,
    ):
        self.config_path = resolve_config_path(config_path, create_if_missing)
        self.algorithm_name = algorithm_name
        if self.config_path is not None and Path(self.config_path).is_file():
            with open(self.config_path, "r") as f:
                loaded = json.load(f)
                # A non-object root (null / list / scalar — valid JSON,
                # malformed config) must degrade to defaults like every
                # other malformed section, not crash the first getter.
                if isinstance(loaded, dict):
                    self._raw = loaded
                else:
                    import warnings

                    warnings.warn(
                        f"config root is {type(loaded).__name__}, not an "
                        "object; using built-in defaults")
                    self._raw = default_config()
        else:
            self._raw = default_config()
        self._warn_unknown_keys()
        if algorithm_name is not None and algorithm_name.upper() not in SUPPORTED_ALGORITHMS:
            # The reference whitelists but ultimately tolerates unknown algos
            # (they resolve to empty params); keep that permissiveness for
            # user plugin algorithms, just warn.
            import warnings

            warnings.warn(
                f"algorithm {algorithm_name!r} is not in the built-in registry "
                f"{SUPPORTED_ALGORITHMS}; treating as a plugin"
            )

    def _warn_unknown_keys(self) -> None:
        """Warn ONCE per (config file, key) about keys the framework will
        never read: unknown top-level sections (the classic typo'd
        ``guardrials:`` block — silently ignored until this check) and
        unknown keys inside the known non-algorithm sections. Unknown
        ALGORITHM hyperparams are deliberately exempt (plugin algorithms
        take arbitrary overrides); ``_comment*`` keys are the config
        file's documented escape hatch."""
        import warnings

        def warn(key: str, hint: str) -> None:
            marker = (str(self.config_path), key)
            if marker in _warned_unknown_keys:
                return
            _warned_unknown_keys.add(marker)
            warnings.warn(f"config key {key!r} is not recognized and will "
                          f"be ignored{hint}", stacklevel=4)

        known_top = set(DEFAULT_CONFIG) | {"grpc_idle_timeout_s",
                                           "grpc_idle_timeout",
                                           "max_traj_length"}
        for key in self._raw:
            if str(key).startswith("_comment"):
                continue
            if key not in known_top:
                close = _closest(str(key), known_top)
                warn(str(key), f" (did you mean {close!r}?)" if close else "")
        # Sections whose key set IS the contract (algorithms excluded:
        # hyperparam overrides are open-ended by design).
        for section in ("actor", "transport", "learner", "telemetry",
                        "guardrails", "serving", "relay", "rlhf",
                        "model_paths", "server", "training_tensorboard"):
            defaults = DEFAULT_CONFIG.get(section)
            loaded = self._section(section)
            if not isinstance(defaults, Mapping) or not loaded:
                continue
            for key in loaded:
                if str(key).startswith("_comment") or key in defaults:
                    continue
                close = _closest(str(key), set(defaults))
                warn(f"{section}.{key}",
                     f" (did you mean {section}.{close!r}?)" if close
                     else "")

    # -- getters (ref: config_loader.rs:344-555) --
    def _section(self, key: str) -> Mapping:
        """A top-level config section, or {} when absent OR malformed
        (null / list / scalar): every getter must degrade to defaults, not
        crash the server on a hand-edited file (the reference's getters
        all fall back — config_loader.rs:344-381)."""
        value = self._raw.get(key)
        return value if isinstance(value, Mapping) else {}

    def get_algorithm_params(self, algorithm_name: str | None = None) -> dict[str, Any]:
        name = algorithm_name or self.algorithm_name
        if name is None:
            return {}
        algos = self._section("algorithms")
        # case-insensitive lookup, defaults merged under user overrides
        defaults = DEFAULT_CONFIG["algorithms"]
        base = {}
        for k, v in defaults.items():
            if k.upper() == name.upper():
                base = copy.deepcopy(v)  # nested lists must not alias defaults
        for k, v in algos.items():
            if str(k).upper() == name.upper() and isinstance(v, Mapping):
                base.update(v)
        return base

    def _endpoint(self, key: str) -> Endpoint:
        fallback = _FALLBACK_ENDPOINTS[key]
        entry = self._section("server").get(key)
        if not isinstance(entry, Mapping):
            return fallback
        return Endpoint.from_dict(entry, fallback)

    def get_train_server(self) -> Endpoint:
        return self._endpoint("training_server")

    def get_traj_server(self) -> Endpoint:
        return self._endpoint("trajectory_server")

    def get_agent_listener(self) -> Endpoint:
        return self._endpoint("agent_listener")

    def get_inference_server(self) -> Endpoint:
        """Serving-plane action channel (zmq ROUTER/DEALER — the thin
        clients' request/response endpoint; grpc fleets use the in-band
        GetActions RPC on training_server instead)."""
        return self._endpoint("inference_server")

    def get_tb_params(self) -> dict[str, Any]:
        params = dict(DEFAULT_CONFIG["training_tensorboard"])
        params.update(self._section("training_tensorboard"))
        params.pop("_comment1", None)
        params.pop("_comment2", None)
        return params

    def get_client_model_path(self) -> str:
        return str(
            self._section("model_paths").get("client_model", "client_model.rlx")
        )

    def get_server_model_path(self) -> str:
        return str(
            self._section("model_paths").get("server_model", "server_model.rlx")
        )

    def get_max_traj_length(self) -> int:
        try:
            value = int(self._raw.get("max_traj_length", 1000))
        except (TypeError, ValueError):
            return 1000
        return value if value >= 1 else 1000

    def get_grpc_idle_timeout_s(self) -> float:
        # jaxlint: disable=CFG01 - legacy spelling kept readable for old config files
        raw = self._raw.get("grpc_idle_timeout_s", self._raw.get("grpc_idle_timeout", 30.0))
        try:
            value = float(raw)
        except (TypeError, ValueError):
            return 30.0
        return value if value > 0 else 30.0

    def get_learner_params(self) -> dict[str, Any]:
        params = {k: (dict(v) if isinstance(v, dict) else v)
                  for k, v in DEFAULT_CONFIG["learner"].items()}
        params.update(self._section("learner"))
        # learner.freeze validates at LOAD time (the unknown-key warning
        # convention's validate-early cousin): a typo'd regex must fail
        # the config read with the offending pattern named, not the Nth
        # training step — and a malformed value degrades to no freezing
        # with a warning rather than crashing server construction.
        freeze = params.get("freeze")
        if freeze is not None:
            from relayrl_tpu.algorithms.freeze import normalize_freeze_spec

            try:
                params["freeze"] = list(normalize_freeze_spec(freeze)) or None
            except ValueError as e:
                import warnings

                warnings.warn(f"ignoring invalid learner.freeze: {e}")
                params["freeze"] = None
        return params

    def get_actor_params(self) -> dict[str, Any]:
        """Actor-plane knobs (``actor.num_envs`` / ``actor.host_mode`` /
        the anakin pair ``actor.unroll_length`` + ``actor.jax_env``),
        defaults merged under user overrides like every other section —
        malformed values degrade to the one-env-per-process default."""
        params = dict(DEFAULT_CONFIG["actor"])
        params.update(self._section("actor"))
        try:
            params["num_envs"] = max(1, int(params.get("num_envs", 1)))
        except (TypeError, ValueError):
            params["num_envs"] = 1
        if params.get("host_mode") not in ("process", "vector", "anakin",
                                           "remote"):
            params["host_mode"] = "process"
        try:
            params["unroll_length"] = max(1, int(
                params.get("unroll_length", 32)))
        except (TypeError, ValueError):
            params["unroll_length"] = 32
        jax_env = params.get("jax_env")
        params["jax_env"] = (str(jax_env) if jax_env
                             else DEFAULT_CONFIG["actor"]["jax_env"])
        # window_size: None defers to the model's serving context
        # (resolve_actor_context); an explicit value narrows the rolling
        # window and is clamped to >= 1. The hosts clamp it to the model
        # context again at build time — config cannot widen past it.
        ws = params.get("window_size")
        if ws is not None:
            try:
                ws = max(1, int(ws))
            except (TypeError, ValueError):
                ws = None
        params["window_size"] = ws
        params["async_emit"] = bool(params.get("async_emit", False))
        try:
            params["emit_coalesce_frames"] = max(1, int(
                params.get("emit_coalesce_frames", 1)))
        except (TypeError, ValueError):
            params["emit_coalesce_frames"] = 1
        # columnar_wire: "auto" resolves per tier (anakin -> columnar
        # frames, host-bound tiers -> per-record); booleans force it.
        cw = params.get("columnar_wire", "auto")
        if not isinstance(cw, bool):
            cw = "auto"
        params["columnar_wire"] = cw
        try:
            # 0 legitimately disables the spool; negatives clamp to 0.
            params["spool_entries"] = max(0, int(
                params.get("spool_entries", 512)))
        except (TypeError, ValueError):
            params["spool_entries"] = 512
        try:
            params["spool_bytes"] = max(1 << 16, int(
                params.get("spool_bytes", 64 << 20)))
        except (TypeError, ValueError):
            params["spool_bytes"] = 64 << 20
        spool_dir = params.get("spool_dir")
        params["spool_dir"] = str(spool_dir) if spool_dir else None
        return params

    def get_transport_params(self) -> dict[str, Any]:
        """Transport-plane knobs (``transport.heartbeat_s`` plus the
        model-wire v2 set ``wire_version`` / ``keyframe_interval`` /
        ``compress`` / ``chunk_bytes``), defaults merged under user
        overrides; malformed values degrade to the built-ins rather
        than crashing transport construction."""
        params = dict(DEFAULT_CONFIG["transport"])
        params.update(self._section("transport"))
        try:
            params["heartbeat_s"] = float(params.get("heartbeat_s", 5.0))
        except (TypeError, ValueError):
            params["heartbeat_s"] = 5.0
        try:
            params["wire_version"] = int(params.get("wire_version", 2))
        except (TypeError, ValueError):
            params["wire_version"] = 2
        if params["wire_version"] not in (1, 2):
            params["wire_version"] = 2
        try:
            # >= 1: an interval that never keyframed would make the
            # first dropped delta a permanent broadcast blackout.
            params["keyframe_interval"] = max(
                1, int(params.get("keyframe_interval", 10)))
        except (TypeError, ValueError):
            params["keyframe_interval"] = 10
        try:
            params["chunk_bytes"] = max(0, int(params.get("chunk_bytes", 0)))
        except (TypeError, ValueError):
            params["chunk_bytes"] = 0
        try:
            smb = params.get("small_model_bytes")
            params["small_model_bytes"] = (None if smb is None
                                           else max(0, int(smb)))
        except (TypeError, ValueError):
            params["small_model_bytes"] = None
        try:
            params["resync_min_interval_s"] = max(0.0, float(
                params.get("resync_min_interval_s", 0.25)))
        except (TypeError, ValueError):
            params["resync_min_interval_s"] = 0.25
        # retry: keep the raw (merged) dict — RetryPolicy.from_dict and
        # retry.breaker_from_config own per-knob validation, so a
        # malformed knob degrades at the consumer with the same
        # defaults everywhere.
        retry = params.get("retry")
        defaults = dict(DEFAULT_CONFIG["transport"]["retry"])
        if isinstance(retry, Mapping):
            defaults.update(retry)
        params["retry"] = defaults
        return params

    def get_guardrails_params(self) -> dict[str, Any]:
        """Training-health knobs (``guardrails.*`` — see
        docs/operations.md "Training-health guardrails"), defaults
        merged under user overrides; malformed values degrade to the
        built-ins (the guardrail plane must never crash the process it
        protects)."""
        params = dict(DEFAULT_CONFIG["guardrails"])
        params.update(self._section("guardrails"))
        params["enabled"] = bool(params.get("enabled", True))
        if params.get("ingest_validation") not in ("enforce", "warn", "off"):
            params["ingest_validation"] = "enforce"
        for key, default, lo in (
                ("strike_threshold", 3, 1),
                ("loss_window", 16, 4),
                ("reward_window", 32, 4),
                ("checkpoint_ring", 5, 1),
                ("max_rollbacks", 3, 0),
                ("ingest_soft_limit", 8192, 0)):
            try:
                params[key] = max(lo, int(params.get(key, default)))
            except (TypeError, ValueError):
                params[key] = default
        for key, default in (
                ("strike_window_s", 60.0), ("quarantine_cooldown_s", 300.0),
                ("rollback_window_s", 600.0), ("agent_share", 0.5),
                ("nack_retry_after_s", 1.0)):
            try:
                value = params.get(key, default)
                params[key] = max(0.0, float(default if value is None
                                             else value))
            except (TypeError, ValueError):
                params[key] = default
        for key, default in (
                ("max_param_norm", 1e6), ("max_update_norm", 0.0),
                ("loss_spike_factor", 0.0), ("reward_collapse_drop", 0.0)):
            # Trip thresholds honor the documented "0/null disables"
            # contract: an explicit null means the detector is OFF, not
            # back to a default that keeps it armed.
            try:
                value = params.get(key, default)
                params[key] = max(0.0, float(0.0 if value is None
                                             else value))
            except (TypeError, ValueError):
                params[key] = default
        try:
            max_steps = params.get("max_steps")
            params["max_steps"] = (None if max_steps is None
                                   else max(0, int(max_steps)))
        except (TypeError, ValueError):
            params["max_steps"] = None
        for key in ("watchdog", "probes", "update_norm_probe", "rollback"):
            params[key] = bool(params.get(key, True))
        if params.get("shed_policy") not in ("drop_oldest", "nack"):
            params["shed_policy"] = "drop_oldest"
        params["loss_key"] = str(params.get("loss_key") or "auto")
        return params

    def get_serving_params(self) -> dict[str, Any]:
        """Disaggregated batched-inference knobs (``serving.*`` — see
        docs/operations.md "Serving plane"), defaults merged under user
        overrides; malformed values degrade to the built-ins (the
        serving plane must not crash the training server hosting it)."""
        params = dict(DEFAULT_CONFIG["serving"])
        params.update(self._section("serving"))
        params["enabled"] = bool(params.get("enabled", False))
        for key, default, lo in (("max_batch", 16, 1),
                                 ("queue_limit", 1024, 1),
                                 ("max_sessions", 4096, 1),
                                 ("stream_window", 32, 1)):
            try:
                params[key] = max(lo, int(params.get(key, default)))
            except (TypeError, ValueError):
                params[key] = default
        for key, default in (("batch_timeout_ms", 5.0),
                             ("retry_after_s", 0.05),
                             ("stale_after_s", 5.0),
                             ("request_timeout_s", 2.0),
                             ("infer_deadline_s", 60.0),
                             ("session_ttl_s", 600.0)):
            try:
                value = params.get(key, default)
                params[key] = max(0.0, float(default if value is None
                                             else value))
            except (TypeError, ValueError):
                params[key] = default
        buckets = params.get("buckets")
        if isinstance(buckets, (list, tuple)) and buckets:
            try:
                clean = sorted({max(1, int(b)) for b in buckets})
                # The largest bucket must cover max_batch or full-size
                # closes could never dispatch without a clamp.
                if clean[-1] < params["max_batch"]:
                    clean.append(params["max_batch"])
                params["buckets"] = clean
            except (TypeError, ValueError):
                params["buckets"] = None
        else:
            params["buckets"] = None
        replicas = params.get("replicas")
        if isinstance(replicas, (list, tuple)) and replicas:
            params["replicas"] = [str(a) for a in replicas]
        else:
            params["replicas"] = None
        return params

    def get_relay_params(self) -> dict[str, Any]:
        """Relay-node knobs (``relay.*`` — see docs/architecture.md
        "relay tree" and docs/operations.md "Relay runbook"), defaults
        merged under user overrides; malformed values degrade to the
        built-ins (a relay must come up on a hand-edited config)."""
        params = dict(DEFAULT_CONFIG["relay"])
        params.update(self._section("relay"))
        params["enabled"] = bool(params.get("enabled", False))
        name = params.get("name")
        params["name"] = str(name) if name else None
        if params.get("upstream_type") not in ("zmq", "grpc", "native",
                                               "auto"):
            params["upstream_type"] = "zmq"
        if params.get("downstream_type") not in ("zmq", "grpc"):
            params["downstream_type"] = "zmq"
        for key in ("upstream", "downstream"):
            value = params.get(key)
            params[key] = dict(value) if isinstance(value, Mapping) else {}
        try:
            params["fanout_port"] = max(0, int(params.get("fanout_port", 0)))
        except (TypeError, ValueError):
            params["fanout_port"] = 0
        params["keyframe_cache"] = bool(params.get("keyframe_cache", True))
        try:
            params["batch_max"] = max(1, int(params.get("batch_max", 8)))
        except (TypeError, ValueError):
            params["batch_max"] = 8
        try:
            params["batch_linger_ms"] = max(0.0, float(
                params.get("batch_linger_ms", 5.0)))
        except (TypeError, ValueError):
            params["batch_linger_ms"] = 5.0
        try:
            params["spool_entries"] = max(0, int(
                params.get("spool_entries", 2048)))
        except (TypeError, ValueError):
            params["spool_entries"] = 2048
        try:
            params["spool_bytes"] = max(1 << 16, int(
                params.get("spool_bytes", 128 << 20)))
        except (TypeError, ValueError):
            params["spool_bytes"] = 128 << 20
        spool_dir = params.get("spool_dir")
        params["spool_dir"] = str(spool_dir) if spool_dir else None
        try:
            params["resync_min_interval_s"] = max(0.0, float(
                params.get("resync_min_interval_s", 0.25)))
        except (TypeError, ValueError):
            params["resync_min_interval_s"] = 0.25
        return params

    def get_rlhf_params(self) -> dict[str, Any]:
        """RLHF workload-plane knobs (``rlhf.*`` — see docs/operations.md
        "RLHF workload plane"), defaults merged under user overrides;
        malformed values degrade to the built-ins (the scheduler must
        come up on a hand-edited config)."""
        params = dict(DEFAULT_CONFIG["rlhf"])
        params.update(self._section("rlhf"))
        for key, default, lo in (("vocab_size", 8, 2),
                                 ("prompt_len", 3, 1),
                                 ("max_new_tokens", 8, 1),
                                 ("rm_d_model", 32, 4),
                                 ("rm_n_layers", 1, 1),
                                 ("rm_seed", 7, 0),
                                 ("lanes", 4, 1),
                                 ("generation_unroll", 8, 1),
                                 ("score_batch", 8, 1),
                                 ("score_queue", 256, 1),
                                 ("max_episodes_per_version", 64, 0)):
            try:
                params[key] = max(lo, int(params.get(key, default)))
            except (TypeError, ValueError):
                params[key] = default
        try:
            value = params.get("pace_timeout_s", 5.0)
            params["pace_timeout_s"] = max(0.1, float(
                5.0 if value is None else value))
        except (TypeError, ValueError):
            params["pace_timeout_s"] = 5.0
        if params.get("scorer") not in ("programmatic", "reward_model"):
            params["scorer"] = "programmatic"
        if params.get("generation_tier") not in ("vector", "remote",
                                                 "anakin"):
            params["generation_tier"] = "vector"
        return params

    def get_telemetry_params(self) -> dict[str, Any]:
        """Observability knobs (``telemetry.*`` — see
        docs/observability.md), defaults merged under user overrides.
        Malformed ``enabled``/``port`` degrade to disabled/default-port
        rather than crashing the process being observed."""
        params = dict(DEFAULT_CONFIG["telemetry"])
        params.update(self._section("telemetry"))
        params["enabled"] = bool(params.get("enabled", False))
        try:
            params["port"] = int(params.get("port", 9100))
        except (TypeError, ValueError):
            params["port"] = 9100
        params["host"] = str(params.get("host") or "127.0.0.1")
        try:
            params["events_max_bytes"] = max(
                0, int(params.get("events_max_bytes") or 0))
        except (TypeError, ValueError):
            params["events_max_bytes"] = 0
        try:
            params["trace_sample_rate"] = min(
                1.0, max(0.0, float(params.get("trace_sample_rate") or 0.0)))
        except (TypeError, ValueError):
            params["trace_sample_rate"] = 0.0
        try:
            params["trace_ring"] = max(16, int(params.get("trace_ring")
                                               or 4096))
        except (TypeError, ValueError):
            params["trace_ring"] = 4096
        try:
            params["fleet_interval_s"] = max(0.0, float(
                params.get("fleet_interval_s") or 0.0))
        except (TypeError, ValueError):
            params["fleet_interval_s"] = 0.0
        try:
            params["fleet_stale_s"] = max(1.0, float(
                params.get("fleet_stale_s") or 15.0))
        except (TypeError, ValueError):
            params["fleet_stale_s"] = 15.0
        if params["fleet_interval_s"] > 0:
            # The stale window must cover at least two emission
            # intervals, or the root evicts every proc between its own
            # frames and the table flaps (evict/rejoin per interval).
            floor = 2.0 * params["fleet_interval_s"]
            if params["fleet_stale_s"] < floor:
                import warnings

                warnings.warn(
                    f"telemetry.fleet_stale_s "
                    f"({params['fleet_stale_s']}) < 2x fleet_interval_s; "
                    f"raising to {floor} so procs don't flap out of the "
                    f"fleet table between their own frames")
                params["fleet_stale_s"] = floor
        alerts = params.get("alerts")
        if isinstance(alerts, Mapping):
            # A single rule object is a natural way to write one rule —
            # accept it as a one-element list instead of dropping it.
            alerts = [dict(alerts)]
        elif alerts is not None and not isinstance(alerts, (list, tuple)):
            import warnings

            warnings.warn(
                f"telemetry.alerts must be a list of rule objects; got "
                f"{type(alerts).__name__} — ignoring")
            alerts = None
        params["alerts"] = list(alerts) if alerts is not None else None
        params["alerts_default_pack"] = bool(
            params.get("alerts_default_pack", True))
        return params

    def raw(self) -> dict:
        return self._raw


def resolve_config_path(
    config_path: str | os.PathLike | None, create_if_missing: bool = True
) -> Path | None:
    """Resolve (and optionally auto-create) the config file
    (ref: resolve_config_json_path!/get_or_create_config_json_path!,
    config_loader.rs:12-113 — writes the embedded default to cwd if absent)."""
    path = Path(config_path) if config_path is not None else Path.cwd() / DEFAULT_CONFIG_FILENAME
    if path.is_file():
        return path
    if create_if_missing:
        try:
            path.parent.mkdir(parents=True, exist_ok=True)
            with open(path, "w") as f:
                json.dump(default_config(), f, indent=2)
            return path
        except OSError:
            return None
    return None
