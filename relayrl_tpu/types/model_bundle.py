"""Model distribution format: params + architecture config + version.

The reference ships whole TorchScript files as the model artifact
(reference: relayrl_framework/src/sys_utils/grpc_utils.rs:171-205 serializes
a tch CModule through a temp `.pt` file; agents re-load and validate it,
src/network/client/agent_wrapper.rs:88-168). A TorchScript blob carries both
code and weights; JAX params are data-only, so the TPU-native bundle ships

* ``arch``   — a JSON-able architecture config consumed by the model
               registry (relayrl_tpu.models) to rebuild the pure apply fn on
               any host (TPU learner or CPU actor),
* ``params`` — the parameter pytree, serialized with flax.serialization
               (msgpack of the state dict),
* ``version`` — a monotonically increasing int. The reference's proto has a
               version field that the server never increments
               (training_grpc.rs:722-725); here versioning is real and actors
               use it to skip stale updates.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Any, Mapping

import msgpack

WIRE_VERSION = 1


class _RawTreeSentinel:
    """Explicit opt-in for the no-template decode path (see
    :meth:`ModelBundle.from_bytes`)."""

    def __repr__(self) -> str:  # pragma: no cover - repr cosmetics
        return "ModelBundle.RAW_TREE"


@dataclasses.dataclass
class ModelBundle:
    version: int
    arch: dict[str, Any]
    params: Any  # parameter pytree

    # Pass as ``params_template`` to explicitly request the raw
    # nested-dict restore (no custom pytree node types) without the
    # fallback warning — the hot-path choice for pure apply fns, which
    # only ever index nested dicts. Deliberately NOT annotated: an
    # annotated class attribute would become a dataclass field.
    RAW_TREE = _RawTreeSentinel()

    def to_bytes(self) -> bytes:
        from flax import serialization

        wire = {
            "v": WIRE_VERSION,
            "ver": int(self.version),
            "arch": dict(self.arch),
            "params": serialization.to_bytes(self.params),
        }
        return msgpack.packb(wire, use_bin_type=True)

    @classmethod
    def from_bytes(cls, buf: bytes, params_template: Any | None = None) -> "ModelBundle":
        """Decode a bundle.

        ``params_template`` — when given, params are restored *into* this
        pytree structure (flax ``from_bytes``), preserving custom node
        types (FrozenDict, dataclass nodes, ...).

        Without a template the restore is structural only: params come
        back as plain nested dicts of numpy arrays. That is exactly what
        a pure apply fn needs, but it silently DROPS any custom pytree
        node types the serialized tree had — so the fallback is explicit
        here: passing ``params_template=None`` warns once per call site,
        and callers that want the raw-dict restore on purpose pass
        ``params_template=ModelBundle.RAW_TREE``.
        """
        from flax import serialization

        wire = msgpack.unpackb(buf, raw=False, strict_map_key=False)
        if wire.get("v") != WIRE_VERSION:
            raise ValueError(f"unsupported model bundle version: {wire.get('v')}")
        raw = wire["params"]
        if params_template is None:
            warnings.warn(
                "ModelBundle.from_bytes without params_template restores "
                "params as plain nested dicts — custom pytree node types "
                "are not reconstructed. Pass the live params tree as "
                "params_template to preserve them, or "
                "params_template=ModelBundle.RAW_TREE to opt into the "
                "raw-dict restore explicitly.",
                stacklevel=2)
            params = serialization.msgpack_restore(raw)
        elif params_template is cls.RAW_TREE:
            params = serialization.msgpack_restore(raw)
        else:
            params = serialization.from_bytes(params_template, raw)
        return cls(version=int(wire["ver"]), arch=dict(wire["arch"]), params=params)

    # -- file helpers (the reference's server reads model bytes off disk to
    #    serve agents, training_zmq.rs:905-919; we keep a file path too so
    #    checkpoint/resume and debugging can inspect the artifact) --
    def save(self, path) -> None:
        import os

        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(self.to_bytes())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path, params_template: Any | None = None) -> "ModelBundle":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read(), params_template)


# Arch keys the learner may legitimately change between publishes without
# changing the parameter ABI — exploration schedules ride the arch config
# (e.g. DQN anneals `epsilon`, DDPG/TD3 tune `act_noise`). Everything else
# is structural: a mismatch means the params won't fit the network.
EXPLORATION_ARCH_KEYS = frozenset({"epsilon", "act_noise"})


def exploration_kwargs(arch: Mapping[str, Any]) -> dict[str, Any]:
    """Exploration knobs present in ``arch`` as device scalars, to pass as
    traced ``step`` kwargs — the single construction both in-process actors
    and the networked PolicyActor use, so annealing a knob never retraces."""
    import jax.numpy as jnp

    return {k: jnp.float32(arch[k]) for k in EXPLORATION_ARCH_KEYS
            if k in arch}


# -- leaf manifest + template-driven assembly (model-wire v2) ---------------
# The wire format ships params as a flat sequence of leaf payloads; the
# manifest [(path, dtype, shape), ...] is the schema both ends agree on
# (hashed into every delta frame). Flatten order is jax's deterministic
# tree_flatten order, so publisher and subscriber derive identical
# manifests from isomorphic trees.

def _path_key(entry) -> str:
    """One jax KeyEntry -> a STRING key. Always a string, matching the
    flax state-dict convention (``to_state_dict`` renders sequence nodes
    as ``{'0': ...}`` dicts): a publisher flattening the live tree
    (SequenceKey idx 0) and a subscriber seeded from a restored v1
    bundle (DictKey '0') must derive the SAME manifest hash, or every
    delta resyncs forever on trees containing list/tuple nodes."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def leaf_manifest(params: Any) -> tuple[list[list], list]:
    """Flatten a params pytree into ``(manifest, leaves)``:
    ``manifest[i] = [path_keys, dtype_str, shape]`` and ``leaves[i]`` the
    matching C-contiguous host array."""
    import jax
    import numpy as np

    paths_leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    manifest, leaves = [], []
    for path, leaf in paths_leaves:
        arr = np.ascontiguousarray(np.asarray(leaf))
        manifest.append([[_path_key(k) for k in path],
                         str(arr.dtype), list(arr.shape)])
        leaves.append(arr)
    return manifest, leaves


def tree_from_leaves(manifest: list, leaves: list,
                     params_template: Any | None = None) -> Any:
    """Assemble ``leaves`` back into a params pytree.

    With ``params_template`` the assembly is template-driven: leaves are
    matched to the template's own flatten paths and unflattened with its
    treedef, preserving custom node types. Without one the result is
    plain nested dicts keyed by the manifest paths — the same structural
    restore ``ModelBundle.from_bytes`` does without a template (apply
    fns only ever index nested dicts, so this is the actor default).
    """
    if params_template is not None:
        import jax

        tpl_paths, treedef = jax.tree_util.tree_flatten_with_path(
            params_template)
        by_path = {tuple(entry[0]): leaf
                   for entry, leaf in zip(manifest, leaves)}
        ordered = []
        for path, _tpl_leaf in tpl_paths:
            key = tuple(_path_key(k) for k in path)
            if key not in by_path:
                raise ValueError(
                    f"params_template has leaf {key} absent from the wire "
                    f"manifest — template and published tree diverge")
            ordered.append(by_path[key])
        return jax.tree_util.tree_unflatten(treedef, ordered)
    root: dict = {}
    for (path, _dtype, _shape), leaf in zip(manifest, leaves):
        if not path:
            return leaf  # single-leaf tree (bare array params)
        node = root
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = leaf
    return root


def arch_equal(a: Mapping[str, Any], b: Mapping[str, Any]) -> bool:
    """Structural arch-config equality — the actor refuses a hot-swap whose
    arch differs from the one it validated at handshake (param-ABI guard,
    SURVEY.md §7.4 item 2). Exploration-only keys are exempt."""
    sa = {k: v for k, v in a.items() if k not in EXPLORATION_ARCH_KEYS}
    sb = {k: v for k, v in b.items() if k not in EXPLORATION_ARCH_KEYS}
    return sa == sb
