"""Tensor ⇄ bytes wire codec.

Capability parity with the reference's safetensors codec
(reference: relayrl_framework/src/types/action.rs:287-354, 368-418 —
tch::Tensor → contiguous buffer → safetensors bytes and back). The reference
round-trips every tensor through the safetensors container per action; here
the framing is a fixed little-endian header followed by the raw buffer, so
decode is a single `np.frombuffer` view (zero-copy on the receive path) and
the C++ native codec (native/wire.cc) can parse it without a JSON header.

Wire layout (all little-endian):

    u16 magic 0x5254 ("RT") | u8 version | u8 dtype tag | u8 ndim
    | ndim × u32 dims | payload bytes (C-contiguous)
"""

from __future__ import annotations

import dataclasses
import math
import struct

import numpy as np

from relayrl_tpu.types.dtypes import DType, from_numpy_dtype, to_numpy_dtype

_MAGIC = 0x5254
_VERSION = 1
_HEADER = struct.Struct("<HBBB")  # magic, version, dtype, ndim
_MAX_NDIM = 16
# Decode is the server ingest hot path (~2 tensors per ActionRecord at
# fleet rate) — resolve dtype tags through a flat dict instead of the
# enum constructor + mapping lookup, and count elements with math.prod
# (np.prod on a small tuple costs a ufunc reduction per tensor). Tags
# that cannot resolve on this interpreter (bfloat16 without ml_dtypes —
# dtypes.py degrades gracefully there) are simply absent and fail at
# decode time like before, not at import time.


def _np_by_tag() -> dict:
    out = {}
    for tag in DType:
        try:
            out[int(tag)] = to_numpy_dtype(tag)
        except ValueError:
            continue
    return out


_NP_BY_TAG = _np_by_tag()
_PREPACKED_DIMS = [struct.Struct(f"<{n}I") for n in range(_MAX_NDIM + 1)]


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Shape + dtype of a wire tensor (ref: TensorData sans payload,
    relayrl_framework/src/types/action.rs:196-201)."""

    shape: tuple[int, ...]
    dtype: DType

    @property
    def np_dtype(self) -> np.dtype:
        return to_numpy_dtype(self.dtype)

    @property
    def nbytes(self) -> int:
        n = self.np_dtype.itemsize
        for d in self.shape:
            n *= d
        return n


def encode_tensor(array) -> bytes:
    """ndarray/jax.Array/scalar → wire bytes."""
    arr = np.asarray(array)
    if not arr.flags.c_contiguous:
        # ascontiguousarray would also promote 0-d scalars to 1-d; only copy
        # when the layout actually requires it.
        arr = np.ascontiguousarray(arr)
    tag = from_numpy_dtype(arr.dtype)
    if arr.ndim > _MAX_NDIM:
        raise ValueError(f"tensor rank {arr.ndim} exceeds wire max {_MAX_NDIM}")
    header = _HEADER.pack(_MAGIC, _VERSION, int(tag), arr.ndim)
    dims = _PREPACKED_DIMS[arr.ndim].pack(*arr.shape)
    return header + dims + arr.tobytes()


def decode_tensor(buf: bytes | memoryview) -> np.ndarray:
    """Wire bytes → ndarray (zero-copy view over the input buffer)."""
    view = memoryview(buf)
    if len(view) < _HEADER.size:
        raise ValueError("truncated tensor frame: missing header")
    magic, version, tag, ndim = _HEADER.unpack_from(view, 0)
    if magic != _MAGIC:
        raise ValueError(f"bad tensor frame magic: {magic:#06x}")
    if version != _VERSION:
        raise ValueError(f"unsupported tensor frame version: {version}")
    if ndim > _MAX_NDIM:
        raise ValueError(f"tensor rank {ndim} exceeds wire max {_MAX_NDIM}")
    dims_end = _HEADER.size + 4 * ndim
    if len(view) < dims_end:
        raise ValueError("truncated tensor frame: missing dims")
    shape = _PREPACKED_DIMS[ndim].unpack_from(view, _HEADER.size)
    np_dtype = _NP_BY_TAG.get(tag)
    if np_dtype is None:
        raise ValueError(f"unsupported wire dtype tag: {tag!r}")
    expected = math.prod(shape) * np_dtype.itemsize
    payload = view[dims_end:]
    if len(payload) != expected:
        raise ValueError(
            f"tensor frame payload size {len(payload)} != expected {expected} "
            f"for shape {shape} dtype {np_dtype}"
        )
    return np.frombuffer(payload, dtype=np_dtype).reshape(shape)


def spec_of(buf: bytes | memoryview) -> TensorSpec:
    """Parse just the header — used by ingest staging to pre-size batches."""
    view = memoryview(buf)
    if len(view) < _HEADER.size:
        raise ValueError("truncated tensor frame: missing header")
    magic, version, tag, ndim = _HEADER.unpack_from(view, 0)
    if magic != _MAGIC or version != _VERSION:
        raise ValueError("bad tensor frame header")
    if ndim > _MAX_NDIM:
        raise ValueError(f"tensor rank {ndim} exceeds wire max {_MAX_NDIM}")
    if len(view) < _HEADER.size + 4 * ndim:
        raise ValueError("truncated tensor frame: missing dims")
    shape = _PREPACKED_DIMS[ndim].unpack_from(view, _HEADER.size)
    return TensorSpec(shape=tuple(shape), dtype=DType(tag))
