"""Columnar decoded trajectories (the native ingest fast path).

The reference's server decodes every trajectory inside its native loop
(reference: relayrl_framework/src/network/server/training_zmq.rs:994-1011
pickle-decodes Vec<RelayRLAction> in Rust). This framework's equivalent is
``native/codec.cc``: it parses the msgpack wire trajectory off-GIL and
emits one contiguous ``[T, ...]`` buffer per field ("RLD1" blobs). This
module is the Python half — blob parsing into :class:`DecodedTrajectory`
(a handful of ``np.frombuffer`` views, no per-step objects) plus the
ctypes wrapper around ``rl_decode`` so the ZMQ/gRPC ingest path reuses the
native decoder even though their sockets live in Python.

Terminal markers are already folded by the native decoder (same semantics
as :func:`relayrl_tpu.data.batching.fold_trailing_markers`; parity is
enforced by tests/test_native_codec.py), so ``n_steps`` counts real steps
and ``final_obs``/``final_mask``/``marker_truncated`` carry what the
markers contributed.
"""

from __future__ import annotations

import ctypes
import dataclasses
import struct
import threading
import zlib

import numpy as np

from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.dtypes import DType, from_numpy_dtype, to_numpy_dtype
from relayrl_tpu.types.tensor import decode_tensor, encode_tensor

_BLOB_MAGIC = 0x31444C52  # "RLD1"
MAGIC_BYTES = b"RLD1"  # little-endian prefix of every blob/frame
KIND_COLUMNAR = 0
KIND_RAW = 1
KIND_REGISTER = 2
KIND_RAW_ENVELOPE = 3
KIND_UNREGISTER = 4

# -- columnar WIRE frames (the trajectory fast path, ISSUE 9) --
#
# A columnar frame is an RLD1 kind-0 blob shipped AS the trajectory
# payload (inside the usual transport envelope, so attribution and the
# spool's ``#s<seq>`` tag ride the envelope id unchanged), extended with
# a footer the wire needs but the in-process drain does not:
#
#     flags bit 3 (8): u8 frame_version | u32 crc32
#
# The CRC covers every preceding byte of the blob (header through the
# final-tensor sections), so a corrupt frame is detected at decode time
# instead of poisoning the staging slabs. The native C++ codec never
# emits the footer bit, so its drain blobs parse exactly as before; a
# frame arriving over the native transport rides the C++ envelope
# decoder's raw-fallback path verbatim (codec.cc carries unknown
# payloads through untouched) and is parsed HERE, so one Python parser
# serves all three transports.
FRAME_VERSION = 1
FLAG_MARKER_TRUNCATED = 1
FLAG_FINAL_OBS = 2
FLAG_FINAL_MASK = 4
FLAG_FOOTER = 8
_FOOTER = struct.Struct("<BI")  # frame_version, crc32


def is_columnar_frame(payload) -> bool:
    """Cheap wire sniff: does this trajectory payload carry an RLD1
    columnar frame (vs a msgpack per-record trajectory, which always
    starts with a msgpack map byte)?"""
    return len(payload) >= _HDR.size and bytes(payload[:4]) == MAGIC_BYTES


@dataclasses.dataclass
class DecodedTrajectory:
    """One wire trajectory as columns (markers folded)."""

    agent_id: str
    n_steps: int
    n_records: int  # pre-fold record count — bucketing parity with the
    #                 ActionRecord path (pick_bucket sees raw record count)
    marker_truncated: bool
    columns: dict[str, np.ndarray]  # "o","a","m","r","t","u","x" (present ones)
    aux: dict[str, np.ndarray]      # per-step aux columns ("v","logp_a",...)
    final_obs: np.ndarray | None = None
    final_mask: np.ndarray | None = None

    def __len__(self) -> int:
        return self.n_records

    @property
    def total_reward(self) -> float:
        r = self.columns.get("r")
        return float(r.sum()) if r is not None else 0.0

    def to_action_records(self) -> list[ActionRecord]:
        """Reconstruct per-step records (compat path for consumers without
        a columnar fast path). Marker contributions that survive folding
        (bootstrap obs/mask, truncation flag) are re-attached as one
        synthetic trailing marker so downstream re-folding reproduces the
        same result."""
        cols, aux = self.columns, self.aux
        records = []
        for t in range(self.n_steps):
            data = {k: v[t] for k, v in aux.items()} or None
            records.append(ActionRecord(
                obs=cols["o"][t] if "o" in cols else None,
                act=cols["a"][t] if "a" in cols else None,
                mask=cols["m"][t] if "m" in cols else None,
                rew=float(cols["r"][t]),
                data=data,
                done=bool(cols["t"][t]),
                reward_updated=bool(cols["u"][t]),
                truncated=bool(cols["x"][t]),
            ))
        if (self.final_obs is not None or self.final_mask is not None
                or self.marker_truncated):
            records.append(ActionRecord(
                obs=self.final_obs, act=None, mask=self.final_mask,
                rew=0.0, done=False, truncated=self.marker_truncated))
        return records


def _all_finite(value) -> bool:
    """False iff the value holds NaN/inf. Delegates to action.py's
    _has_nonfinite, whose kind check covers 'V' — bfloat16/float8 arrive
    via ml_dtypes with dtype.kind 'V', and a kind-'f'-only check would
    wave their NaNs straight through the guard."""
    from relayrl_tpu.types.action import _has_nonfinite

    try:
        return not _has_nonfinite(np.asarray(value))
    except Exception:
        # Unconvertible aux values can't reach a batch column either
        # (np.asarray fails identically there, isolated by the server's
        # per-trajectory exception handling) — treat as inert here.
        return True


def trajectory_is_finite(item) -> bool:
    """True iff every training-relevant float in the trajectory is finite.

    The ingest trust boundary's semantic guard: a NaN/inf smuggled into
    obs, act, reward, or a float aux column (v, logp_a feed REINFORCE/
    IMPALA losses directly) would not crash anything — it would silently
    poison the learner state and, through the next publish, the whole
    fleet. Both algorithm families call this in ``accumulate`` and drop
    the trajectory (counted, logged) when it fails. Action masks are
    deliberately NOT checked: models consume them as ``mask > 0``, so a
    -inf fill is semantically harmless.

    Accepts either wire representation: a :class:`DecodedTrajectory`
    (columnar fast path) or a list of :class:`ActionRecord`.
    """
    if isinstance(item, DecodedTrajectory):
        for key in ("o", "a", "r"):
            col = item.columns.get(key)
            if col is not None and not _all_finite(col):
                return False
        for col in item.aux.values():
            if not _all_finite(col):
                return False
        if item.final_obs is not None and not _all_finite(item.final_obs):
            return False
        return True
    for a in item:
        if not np.isfinite(a.rew):
            return False
        for value in (a.obs, a.act):
            if value is not None and not _all_finite(value):
                return False
        for v in (a.data or {}).values():
            # Skip only known-inert types: a NaN can arrive as a plain
            # msgpack list (foreign encoder) or an ml_dtypes scalar, and
            # both feed batch columns via np.asarray downstream.
            if isinstance(v, (str, bytes, bool)):
                continue
            if not _all_finite(v):
                return False
    return True


@dataclasses.dataclass
class RawTrajectory:
    """Fallback: the native decoder couldn't columnarize this payload;
    carry the original bytes for the Python decoder. ``is_envelope`` marks
    payloads that are still wrapped in the transport envelope (the
    envelope itself failed to parse natively, or the decoder threw) —
    consumers must ``unpack_trajectory_envelope`` first."""

    agent_id: str
    payload: bytes
    is_envelope: bool = False


@dataclasses.dataclass
class Registration:
    agent_id: str


@dataclasses.dataclass
class Unregistration:
    """A registered agent's control connection died (crash / kill -9 /
    idle-reap): elastic-fleet registry maintenance."""

    agent_id: str


_HDR = struct.Struct("<IBI")          # magic, kind, id_len
_COL_FIXED = struct.Struct("<BB")     # dtype, ndim (after name)
_META = struct.Struct("<IIBH")        # n_steps, n_records, flags, n_cols


def parse_blob(view: memoryview, off: int = 0, verify_crc: bool = True):
    """Parse one RLD1 blob at ``off``; returns ``(item, next_off)``.

    Blobs carrying the wire footer (``flags & FLAG_FOOTER``, produced by
    :func:`encode_columnar_frame`) are CRC-verified here — a mismatch
    raises ``ValueError`` so the ingest path counts the frame as
    malformed instead of staging corrupt columns. ``verify_crc=False``
    skips the recompute for callers that already checked the footer
    (:func:`parse_frame` verifies integrity BEFORE parsing)."""
    start = off
    magic, kind, id_len = _HDR.unpack_from(view, off)
    if magic != _BLOB_MAGIC:
        raise ValueError(f"bad RLD1 magic {magic:#x}")
    off += _HDR.size
    agent_id = bytes(view[off:off + id_len]).decode(errors="replace")
    off += id_len
    if kind == KIND_REGISTER:
        return Registration(agent_id), off
    if kind == KIND_UNREGISTER:
        return Unregistration(agent_id), off
    if kind in (KIND_RAW, KIND_RAW_ENVELOPE):
        (n,) = struct.unpack_from("<Q", view, off)
        off += 8
        payload = bytes(view[off:off + n])
        return RawTrajectory(agent_id, payload,
                             is_envelope=(kind == KIND_RAW_ENVELOPE)), off + n
    n_steps, n_records, flags, n_cols = _META.unpack_from(view, off)
    off += _META.size
    descs = []
    for _ in range(n_cols):
        name_len = view[off]
        off += 1
        name = bytes(view[off:off + name_len]).decode()
        off += name_len
        dtype_tag, ndim = _COL_FIXED.unpack_from(view, off)
        off += _COL_FIXED.size
        dims = struct.unpack_from(f"<{ndim}I", view, off)
        off += 4 * ndim
        col_off, nbytes = struct.unpack_from("<QQ", view, off)
        off += 16
        descs.append((name, dtype_tag, dims, col_off, nbytes))
    (data_len,) = struct.unpack_from("<Q", view, off)
    off += 8
    data = view[off:off + data_len]
    off += data_len
    columns: dict[str, np.ndarray] = {}
    aux: dict[str, np.ndarray] = {}
    for name, dtype_tag, dims, col_off, nbytes in descs:
        np_dtype = to_numpy_dtype(DType(dtype_tag))
        arr = np.frombuffer(data[col_off:col_off + nbytes],
                            dtype=np_dtype).reshape(dims)
        if name.startswith("d:"):
            aux[name[2:]] = arr
        else:
            columns[name] = arr
    final_obs = final_mask = None
    if flags & 2:
        (n,) = struct.unpack_from("<I", view, off)
        off += 4
        final_obs = decode_tensor(view[off:off + n])
        off += n
    if flags & 4:
        (n,) = struct.unpack_from("<I", view, off)
        off += 4
        final_mask = decode_tensor(view[off:off + n])
        off += n
    if flags & FLAG_FOOTER:
        version, crc = _FOOTER.unpack_from(view, off)
        if version != FRAME_VERSION:
            raise ValueError(
                f"unsupported columnar frame version: {version}")
        if (verify_crc
                and zlib.crc32(view[start:off]) & 0xFFFFFFFF != crc):
            raise ValueError("columnar frame CRC mismatch")
        off += _FOOTER.size
    return DecodedTrajectory(
        agent_id=agent_id, n_steps=n_steps, n_records=n_records,
        marker_truncated=bool(flags & 1), columns=columns, aux=aux,
        final_obs=final_obs, final_mask=final_mask), off


def parse_drain(buf: memoryview | bytes) -> list:
    """Parse a batch-drain buffer: u64-length-prefixed RLD1 blobs."""
    view = memoryview(buf)
    items = []
    off = 0
    while off < len(view):
        (blob_len,) = struct.unpack_from("<Q", view, off)
        off += 8
        item, end = parse_blob(view, off)
        if end - off != blob_len:
            raise ValueError(
                f"blob framing mismatch: prefix {blob_len}, parsed {end - off}")
        items.append(item)
        off = end
    return items


# -- columnar frame encode/decode (the trajectory wire fast path) --

_CANONICAL_COLS = ("o", "a", "m", "r", "t", "u", "x")


# dtype-tag memo keyed by the dtype object: the emitter encodes tens of
# thousands of small frames per second, and from_numpy_dtype's
# np.dtype() + dict hop per column was measurable at that rate.
_TAG_BY_DTYPE: dict = {}


def _dtype_tag(dtype) -> int:
    tag = _TAG_BY_DTYPE.get(dtype)
    if tag is None:
        tag = int(from_numpy_dtype(dtype))
        _TAG_BY_DTYPE[dtype] = tag
    return tag


def encode_columnar_frame(dt: DecodedTrajectory,
                          agent_id: str | None = None) -> bytes:
    """One :class:`DecodedTrajectory` → wire frame bytes.

    The layout is the RLD1 kind-0 blob the native drain already emits
    (so :func:`parse_blob` is the one parser for both), plus the CRC
    footer (``FLAG_FOOTER``). Attribution normally rides the transport
    envelope — ``agent_id`` defaults to the trajectory's own id and may
    be empty to save wire bytes when the envelope carries it."""
    ident = (dt.agent_id if agent_id is None else agent_id).encode()
    flags = FLAG_FOOTER
    if dt.marker_truncated:
        flags |= FLAG_MARKER_TRUNCATED
    if dt.final_obs is not None:
        flags |= FLAG_FINAL_OBS
    if dt.final_mask is not None:
        flags |= FLAG_FINAL_MASK
    names = [n for n in _CANONICAL_COLS if n in dt.columns]
    names += [n for n in dt.columns if n not in _CANONICAL_COLS]
    cols = [(name.encode(), dt.columns[name]) for name in names]
    cols += [(b"d:" + name.encode(), arr) for name, arr in dt.aux.items()]
    out = bytearray(_HDR.pack(_BLOB_MAGIC, KIND_COLUMNAR, len(ident)))
    out += ident
    out += _META.pack(dt.n_steps, dt.n_records, flags, len(cols))
    pack = struct.pack
    off = 0
    payloads = []
    for name, arr in cols:
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        nbytes = arr.nbytes
        # one pack per column: name_len|name|dtype|ndim|dims|off|nbytes
        out += pack(f"<B{len(name)}sBB{arr.ndim}IQQ", len(name), name,
                    _dtype_tag(arr.dtype), arr.ndim, *arr.shape,
                    off, nbytes)
        padded = (nbytes + 7) & ~7  # 8-align each column
        payloads.append((arr, padded - nbytes))
        off += padded
    out += pack("<Q", off)
    for arr, pad in payloads:
        out += arr.tobytes()
        if pad:
            out += b"\x00" * pad
    for final in (dt.final_obs, dt.final_mask):
        if final is not None:
            frame = encode_tensor(final)
            out += pack("<I", len(frame))
            out += frame
    out += _FOOTER.pack(FRAME_VERSION, zlib.crc32(out) & 0xFFFFFFFF)
    return bytes(out)


def parse_frame(payload, agent_id: str | None = None) -> DecodedTrajectory:
    """Wire frame bytes → :class:`DecodedTrajectory` (CRC verified).

    The strict wire-side entry point: exactly one CRC-footed columnar
    blob, nothing trailing. ``agent_id`` (the transport envelope's
    attribution, seq tag already stripped by the caller) overrides the
    frame-embedded id when given — the envelope owns attribution on
    every transport, mirroring the msgpack decode path."""
    view = memoryview(payload)
    try:
        _, kind, id_len = _HDR.unpack_from(view, 0)
        if kind != KIND_COLUMNAR:
            raise ValueError(
                f"payload is an RLD1 blob but not a columnar frame "
                f"(kind {kind})")
        if not view[_HDR.size + id_len + 8] & FLAG_FOOTER:
            # Wire frames are always CRC-footed (encode_columnar_frame);
            # an unfooted kind-0 blob on the wire is foreign/corrupt.
            raise ValueError("columnar wire frame missing CRC footer")
        # Integrity FIRST: the footer sits in the last 5 bytes, so the
        # whole frame is checksummed before any column is trusted — a
        # corrupt frame fails here with the CRC verdict, never as a
        # numpy shape error halfway through a poisoned parse.
        version, crc = _FOOTER.unpack_from(view, len(view) - _FOOTER.size)
        if version != FRAME_VERSION:
            raise ValueError(
                f"unsupported columnar frame version: {version}")
        if zlib.crc32(view[:len(view) - _FOOTER.size]) & 0xFFFFFFFF != crc:
            raise ValueError("columnar frame CRC mismatch")
        # verify_crc=False: the full-frame checksum above already covered
        # every byte parse_blob will walk — no second pass on the ingest
        # hot path.
        item, end = parse_blob(view, verify_crc=False)
    except (struct.error, IndexError) as e:
        # Truncated/hostile frames surface as data-shaped errors, the
        # class transport receive loops classify as droppable.
        raise ValueError(f"malformed columnar frame: {e}") from e
    if end != len(view):
        raise ValueError(
            f"columnar frame framing mismatch: {len(view) - end} "
            f"trailing bytes")
    if agent_id is not None:
        item.agent_id = agent_id
    return item


# -- ctypes wrapper over rl_decode (shared with the zmq/grpc ingest path) --

_codec_lock = threading.Lock()
_codec_lib = None
_codec_checked = False


def _load_codec():
    global _codec_lib, _codec_checked
    with _codec_lock:
        if _codec_checked:
            return _codec_lib
        _codec_checked = True
        from relayrl_tpu.transport.native_backend import _find_library

        path = _find_library()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.rl_decode.restype = ctypes.c_long
            lib.rl_decode.argtypes = [
                ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
                ctypes.c_int, ctypes.c_char_p, ctypes.c_size_t]
        except (OSError, AttributeError):
            return None
        _codec_lib = lib
        return _codec_lib


def native_codec_available() -> bool:
    return _load_codec() is not None


class NativeDecoder:
    """Per-thread reusable decode buffer around ``rl_decode``.

    The ctypes call releases the GIL for the whole msgpack parse + column
    build, so a staging thread decodes while the learner thread runs the
    device step (SURVEY.md §7.4 item 1's ingest ∥ compute overlap).
    """

    def __init__(self, initial_cap: int = 1 << 20):
        self._lib = _load_codec()
        if self._lib is None:
            raise RuntimeError("native codec library unavailable")
        self._cap = initial_cap
        self._buf = ctypes.create_string_buffer(self._cap)

    def decode(self, payload: bytes, agent_id: str = "?",
               has_envelope: bool = False):
        """Payload (or envelope) bytes -> DecodedTrajectory | RawTrajectory."""
        while True:
            n = self._lib.rl_decode(payload, len(payload),
                                    agent_id.encode(), int(has_envelope),
                                    self._buf, self._cap)
            if n < 0:
                return RawTrajectory(agent_id, payload)
            if n <= self._cap:
                # Slice-copy out of the reusable buffer: the parsed columns
                # are zero-copy views and must not alias the next decode.
                item, _ = parse_blob(memoryview(self._buf[:n]))
                return item
            self._cap = int(n) * 2
            self._buf = ctypes.create_string_buffer(self._cap)
