"""The per-step record type.

Capability parity with the reference's ``RelayRLAction``
(reference: relayrl_framework/src/types/action.rs:428-525 — `{obs?, act?,
mask?, rew: f32, data?: map<String, RelayRLData>, done, reward_updated}` with
getters and `update_reward`). The aux-data union RelayRLData
(action.rs:206-218) maps onto msgpack-native scalars plus an ExtType for
tensors, so the whole record packs as one msgpack map instead of the
reference's pickle (zmq path, types/trajectory.rs:50-55) or
JSON-bytes-in-proto (grpc path, sys_utils/grpc_utils.rs:31-66).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import msgpack
import numpy as np

from relayrl_tpu.types.tensor import decode_tensor, encode_tensor

# msgpack ExtType code for a wire tensor frame. Part of the wire ABI.
EXT_TENSOR = 1

AuxValue = Any  # np.ndarray | int | float | str | bool


@dataclasses.dataclass
class ActionRecord:
    """One environment step: observation, action, mask, reward, aux data.

    ``data`` carries algorithm side-channel values — the reference's REINFORCE
    stores ``logp_a`` and ``v`` there (algorithms/REINFORCE/REINFORCE.py usage
    of ``data['v']``/``data['logp_a']``) and this framework's policies do the
    same, so trajectories are self-contained for the learner.
    """

    obs: np.ndarray | None = None
    act: np.ndarray | None = None
    mask: np.ndarray | None = None
    rew: float = 0.0
    data: dict[str, AuxValue] | None = None
    done: bool = False
    reward_updated: bool = False
    # Terminated-vs-truncated distinction the reference lacks: ``done`` says
    # the episode ended; ``truncated`` says it ended by time limit, not by
    # reaching a terminal state — value targets must still bootstrap through
    # a truncation (Gymnasium step() semantics).
    truncated: bool = False

    # -- reference getter parity (action.rs:454-525) --
    def get_obs(self) -> np.ndarray | None:
        return self.obs

    def get_act(self) -> np.ndarray | None:
        return self.act

    def get_mask(self) -> np.ndarray | None:
        return self.mask

    def get_rew(self) -> float:
        return self.rew

    def get_data(self) -> dict[str, AuxValue] | None:
        return self.data

    def get_done(self) -> bool:
        return self.done

    def get_truncated(self) -> bool:
        return self.truncated

    def update_reward(self, reward: float) -> None:
        self.rew = float(reward)
        self.reward_updated = True

    # -- wire codec --
    def to_wire(self) -> dict:
        return {
            "o": _pack_opt_tensor(self.obs),
            "a": _pack_opt_tensor(self.act),
            "m": _pack_opt_tensor(self.mask),
            "r": float(self.rew),
            "d": _pack_aux(self.data),
            "t": bool(self.done),
            "u": bool(self.reward_updated),
            "x": bool(self.truncated),
        }

    @classmethod
    def from_wire(cls, wire: Mapping) -> "ActionRecord":
        return cls(
            obs=_unpack_opt_tensor(wire.get("o")),
            act=_unpack_opt_tensor(wire.get("a")),
            mask=_unpack_opt_tensor(wire.get("m")),
            rew=float(wire.get("r", 0.0)),
            data=_unpack_aux(wire.get("d")),
            done=bool(wire.get("t", False)),
            reward_updated=bool(wire.get("u", False)),
            truncated=bool(wire.get("x", False)),
        )

    def to_bytes(self) -> bytes:
        return msgpack.packb(self.to_wire(), use_bin_type=True)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "ActionRecord":
        return cls.from_wire(
            msgpack.unpackb(buf, raw=False, ext_hook=_ext_hook, strict_map_key=False)
        )

    # -- JSON codec. Method-name parity with the reference's surface
    #    (PyRelayRLAction.to_json / action_from_json,
    #    bindings/python/o3_action.rs:29-235), NOT format parity — a
    #    deliberate departure, like the msgpack-for-pickle swap documented
    #    in trajectory.py: the reference feeds an already-parsed dict with
    #    tensors as {"inner": {shape, dtype: "Float", data}} to its learner
    #    IPC; here from_json takes the JSON *string* to_json produced, and
    #    tensors are tagged {"__tensor__": {dtype, shape, data|b64}} so
    #    numpy dtype + shape survive exactly. Human-readable debug/interop
    #    surface — the hot path stays msgpack (to_bytes). Output is strict
    #    RFC 8259 (allow_nan=False; non-finite floats are tagged), so
    #    serde_json/JSON.parse-class decoders accept it. --
    def to_jsonable(self) -> dict:
        """Plain-dict form of :meth:`to_json` (no string encode) — used by
        :meth:`Trajectory.to_json` to avoid per-action re-parsing."""
        return {
            "obs": _tensor_to_jsonable(self.obs),
            "act": _tensor_to_jsonable(self.act),
            "mask": _tensor_to_jsonable(self.mask),
            "rew": _float_to_jsonable(float(self.rew)),
            "data": (
                None
                if self.data is None
                else {k: _aux_to_jsonable(v) for k, v in self.data.items()}
            ),
            "done": bool(self.done),
            "reward_updated": bool(self.reward_updated),
            "truncated": bool(self.truncated),
        }

    @classmethod
    def from_jsonable(cls, obj: Mapping) -> "ActionRecord":
        data = obj.get("data")
        return cls(
            obs=_tensor_field_from_jsonable(obj.get("obs"), "obs"),
            act=_tensor_field_from_jsonable(obj.get("act"), "act"),
            mask=_tensor_field_from_jsonable(obj.get("mask"), "mask"),
            rew=_float_from_jsonable(obj.get("rew", 0.0)),
            data=(
                None
                if data is None
                else {k: _aux_from_jsonable(v) for k, v in data.items()}
            ),
            done=bool(obj.get("done", False)),
            reward_updated=bool(obj.get("reward_updated", False)),
            truncated=bool(obj.get("truncated", False)),
        )

    def to_json(self) -> str:
        import json

        return json.dumps(self.to_jsonable(), allow_nan=False)

    @classmethod
    def from_json(cls, text: str) -> "ActionRecord":
        import json

        return cls.from_jsonable(json.loads(text))

    # reference static-method name (o3_action.rs `action_from_json`)
    action_from_json = from_json


def _pack_opt_tensor(value) -> msgpack.ExtType | None:
    if value is None:
        return None
    return msgpack.ExtType(EXT_TENSOR, encode_tensor(value))


def _unpack_opt_tensor(value):
    if value is None:
        return None
    if isinstance(value, np.ndarray):  # already decoded by ext_hook
        return value
    if isinstance(value, msgpack.ExtType):
        return decode_tensor(value.data)
    raise TypeError(f"expected tensor ext frame, got {type(value)!r}")


def _pack_aux(data: Mapping[str, AuxValue] | None):
    if data is None:
        return None
    out = {}
    for key, value in data.items():
        if isinstance(value, (np.ndarray, np.generic)) and getattr(value, "shape", None) != ():
            out[key] = msgpack.ExtType(EXT_TENSOR, encode_tensor(value))
        elif isinstance(value, np.generic):
            out[key] = value.item()
        elif isinstance(value, (bool, int, float, str, bytes)):
            out[key] = value
        elif hasattr(value, "dtype") and hasattr(value, "shape"):  # jax.Array
            out[key] = msgpack.ExtType(EXT_TENSOR, encode_tensor(np.asarray(value)))
        else:
            raise TypeError(f"aux data {key!r} has unsupported type {type(value)!r}")
    return out


def _unpack_aux(data):
    if data is None:
        return None
    out = {}
    for key, value in data.items():
        if isinstance(value, msgpack.ExtType):
            out[key] = decode_tensor(value.data)
        else:
            out[key] = value
    return out


def _ext_hook(code: int, payload: bytes):
    if code == EXT_TENSOR:
        return decode_tensor(payload)
    return msgpack.ExtType(code, payload)


def _tensor_to_jsonable(value):
    """Tagged JSON form `{"__tensor__": {dtype, shape, data|b64}}` — keeps
    dtype + shape exact through a round trip (a bare nested list would
    collapse float32 -> float64 and lose empty-dim shapes). Float arrays
    holding non-finite values (e.g. -inf action-mask fills) switch the
    payload to base64 raw bytes: RFC 8259 has no NaN/Infinity literal, so
    a tolist() form would either crash allow_nan=False or emit JSON that
    serde_json/JSON.parse-class decoders reject."""
    if value is None:
        return None
    arr = np.asarray(value)
    t = {"dtype": arr.dtype.name, "shape": list(arr.shape)}
    if _has_nonfinite(arr):
        import base64

        # Fixed little-endian payload (same convention as tensor.py's
        # binary wire): dtype.name carries no endianness mark, so bytes
        # must be order-normalized on the writer, not trusted to match
        # the reader's native order.
        t["b64"] = base64.b64encode(_to_le_bytes(arr)).decode("ascii")
    else:
        t["data"] = arr.tolist()
    return {"__tensor__": t}


def _has_nonfinite(arr: np.ndarray) -> bool:
    """True when a float-like array (incl. bfloat16/float8, numpy kind
    'V') holds values JSON has no literal for (NaN/Infinity)."""
    if arr.dtype.kind not in "fV":
        return False
    try:
        return not bool(np.isfinite(arr).all())
    except TypeError:  # structured void dtypes — not float-like
        return False


def _to_le_bytes(arr: np.ndarray) -> bytes:
    if arr.dtype.kind == "f":
        le = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
        return np.ascontiguousarray(le).tobytes()
    # Custom float-likes (bfloat16/float8) have no numpy byte-order
    # variant; normalize through a little-endian unsigned view of the
    # same width.
    width = arr.dtype.itemsize
    uview = np.ascontiguousarray(arr).view(f"u{width}")
    return uview.astype(f"<u{width}", copy=False).tobytes()


def _from_le_bytes(raw: bytes, dtype: np.dtype, shape) -> np.ndarray:
    if dtype.kind == "f":
        le = np.frombuffer(raw, dtype=dtype.newbyteorder("<"))
        return le.astype(dtype, copy=True).reshape(shape)
    width = dtype.itemsize
    units = np.frombuffer(raw, dtype=f"<u{width}").astype(f"=u{width}")
    return units.view(dtype).reshape(shape).copy()


def _tensor_from_jsonable(value):
    if value is None:
        return None
    if isinstance(value, dict) and "__tensor__" in value:
        t = value["__tensor__"]
        dtype = np.dtype(t["dtype"])
        if "b64" in t:
            import base64

            return _from_le_bytes(
                base64.b64decode(t["b64"]), dtype, t["shape"])
        return np.asarray(t["data"], dtype=dtype).reshape(t["shape"])
    return value  # plain aux scalar (int/float/str/bool)


def _tensor_field_from_jsonable(value, field: str):
    """Strict decode for obs/act/mask: tensor-tagged or null only — the
    JSON twin of :func:`_unpack_opt_tensor`'s TypeError on non-tensor
    frames, so a malformed/foreign-format field fails at decode time
    instead of smuggling a plain dict into the record."""
    if value is None:
        return None
    if isinstance(value, dict) and "__tensor__" in value:
        return _tensor_from_jsonable(value)
    raise TypeError(
        f"{field!r} must be a tagged tensor object or null, "
        f"got {type(value).__name__}")


def _float_to_jsonable(x: float):
    """Non-finite floats as tagged strings (RFC 8259 has no literal)."""
    return x if np.isfinite(x) else {"__float__": repr(x)}


def _float_from_jsonable(x) -> float:
    if isinstance(x, dict) and "__float__" in x:
        return float(x["__float__"])
    return float(x)


def _aux_to_jsonable(value):
    """Mirror of :func:`_pack_aux` semantics for the JSON surface: 0-d
    numpy scalars unwrap to native Python (so both codecs decode a record
    identically), arrays/jax values become tagged tensors, bytes become
    tagged base64, non-finite plain floats are tagged, and anything
    outside that union raises — exactly the set :func:`_pack_aux`
    accepts, so a record is JSON-encodable iff it is msgpack-encodable
    (rejecting dicts here also closes tag injection: no user value can
    collide with the ``__tensor__``/``__bytes__``/``__float__`` tags)."""
    if isinstance(value, np.generic) and getattr(value, "shape", None) == ():
        value = value.item()
    if isinstance(value, (np.ndarray, np.generic)) or (
        hasattr(value, "dtype") and hasattr(value, "shape")
    ):
        return _tensor_to_jsonable(np.asarray(value))
    if isinstance(value, bytes):
        import base64

        return {"__bytes__": base64.b64encode(value).decode("ascii")}
    if isinstance(value, float):
        return _float_to_jsonable(value)
    if isinstance(value, (bool, int, str)):
        return value
    raise TypeError(
        f"aux data has unsupported type {type(value)!r} for JSON encoding")


def _aux_from_jsonable(value):
    if isinstance(value, dict):
        if "__tensor__" in value:
            return _tensor_from_jsonable(value)
        if "__bytes__" in value:
            import base64

            return base64.b64decode(value["__bytes__"])
        if "__float__" in value:
            return _float_from_jsonable(value)
    return value
