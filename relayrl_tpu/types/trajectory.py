"""Trajectory type + wire codec.

Capability parity with the reference's ``RelayRLTrajectory``
(reference: relayrl_framework/src/types/trajectory.rs:95-203 — Vec of actions
+ max_length + `add_action(action, send_if_done)` which serializes and PUSHes
to the trajectory server when a done action arrives).

Deliberate departures from the reference (documented per SURVEY.md §7.5):

* **msgpack, not pickle.** The reference pickles `Vec<RelayRLAction>`
  (trajectory.rs:50-55); unpickling network input is code execution on the
  training server. The wire format here is msgpack + tensor ext frames.
* **Transport-agnostic send hook.** The reference hardcodes a fresh ZMQ PUSH
  socket per send (trajectory.rs:69-90); here the owner injects an
  ``on_send(bytes)`` callable so the same type serves ZMQ, gRPC, the native
  C++ transport, and in-process tests.
* **Buffer always clears after send.** The reference clears only when
  ``len >= max_length`` so earlier episodes are re-sent cumulatively
  (trajectory.rs:196-202) — a bug we do not replicate.
"""

from __future__ import annotations

from typing import Callable, Iterable

import msgpack

from relayrl_tpu.types.action import ActionRecord, _ext_hook

WIRE_VERSION = 1


class Trajectory:
    """Ordered actions for one (or part of one) episode."""

    def __init__(
        self,
        max_length: int = 1000,
        on_send: Callable[[bytes], None] | None = None,
    ):
        if max_length <= 0:
            raise ValueError("max_length must be positive")
        self.max_length = int(max_length)
        self._on_send = on_send
        self._actions: list[ActionRecord] = []
        # Tracing stamps (telemetry/trace.py): born_ns marks the first
        # step of the chunk currently buffering, encode_t0/t1_ns bracket
        # the last flush's serialize. Read by the owning agent's send
        # hook when it mints a trajectory trace context; one clock read
        # per chunk/flush, never per step beyond the emptiness check.
        self.born_ns = 0
        self.encode_t0_ns = 0
        self.encode_t1_ns = 0

    # -- reference API parity (trajectory.rs:95-203) --
    @property
    def actions(self) -> list[ActionRecord]:
        return self._actions

    def get_actions(self) -> list[ActionRecord]:
        return self._actions

    def __len__(self) -> int:
        return len(self._actions)

    def add_action(self, action: ActionRecord, send_if_done: bool = True) -> bool:
        """Append; on a done action (or overflow) ship and clear.

        Returns True only when the trajectory was actually handed to a
        transport. Without an ``on_send`` hook the actions are retained for
        the caller to read (local/offline collection), bounded by eviction of
        the oldest entries at capacity.

        Capacity is enforced *before* appending a real step, so chunks
        never exceed ``max_length`` steps — but a terminal marker (act-less
        record from ``flag_last_action``) always joins the chunk it ends:
        markers fold into the preceding step learner-side, so the chunk
        still pads into its ``max_length`` bucket, and flushing before the
        marker instead would strand it in a marker-only send that loses
        the final reward and bootstrap obs.
        """
        is_marker = action.act is None
        if not is_marker and len(self._actions) >= self.max_length:
            self._flush_or_evict_at_capacity(send_if_done)
        if not self._actions:
            import time

            self.born_ns = time.monotonic_ns()
        self._actions.append(action)
        if action.done and send_if_done and self._on_send is not None:
            self.flush()
            return True
        return False

    def _flush_or_evict_at_capacity(self, send_if_done: bool) -> bool:
        """The ONE copy of the capacity rule (a real step arriving at
        ``max_length``): flush to the transport when one is attached,
        else evict the oldest half rather than grow unbounded. Shared by
        :meth:`add_action` and :meth:`add_actions` so the per-step and
        bulk wire chunking can never diverge. Returns True iff a
        transport flush happened."""
        if send_if_done and self._on_send is not None:
            self.flush()
            return True
        del self._actions[: max(1, self.max_length // 2)]
        return False

    def add_actions(self, records: list[ActionRecord],
                    send_if_done: bool = True) -> int:
        """Bulk append: wire-identical to calling :meth:`add_action` per
        record, but runs of non-terminal steps extend the buffer in one
        slice, so the Python overhead is O(flushes), not O(steps) — the
        anakin fallback unstacker's path (runtime/anakin.py). Returns
        the number of transport flushes performed."""
        acts = self._actions
        if not acts and records:
            import time

            self.born_ns = time.monotonic_ns()
        flushes = 0
        i, n = 0, len(records)
        while i < n:
            rec = records[i]
            is_marker = rec.act is None
            if not is_marker and len(acts) >= self.max_length:
                flushes += self._flush_or_evict_at_capacity(send_if_done)
            if rec.done or is_marker:
                acts.append(rec)
                i += 1
                if rec.done and send_if_done and self._on_send is not None:
                    self.flush()
                    flushes += 1
                continue
            # run of plain steps: extend up to capacity / the next record
            # that needs per-record handling (done or marker)
            j = i
            stop = min(n, i + self.max_length - len(acts))
            while (j < stop and not records[j].done
                   and records[j].act is not None):
                j += 1
            acts.extend(records[i:j])
            i = j
        return flushes

    def flush(self) -> None:
        """Serialize + hand off to the transport, then clear.

        No-op without a transport — data is never silently discarded; use
        :meth:`clear` to drop it explicitly.
        """
        if not self._actions or self._on_send is None:
            return
        import time

        self.encode_t0_ns = time.monotonic_ns()
        buf = self.to_bytes()
        self.encode_t1_ns = time.monotonic_ns()
        self._on_send(buf)
        self._actions.clear()

    def clear(self) -> None:
        self._actions.clear()

    # -- wire codec --
    def to_bytes(self) -> bytes:
        return serialize_actions(self._actions)

    @classmethod
    def from_bytes(cls, buf: bytes, max_length: int | None = None) -> "Trajectory":
        actions = deserialize_actions(buf)
        traj = cls(max_length=max_length or max(len(actions), 1))
        traj._actions = actions
        return traj

    # -- JSON codec. Method-name parity with the reference's surface
    #    (PyRelayRLTrajectory.to_json / traj_from_json,
    #    bindings/python/o3_trajectory.rs:113-166), NOT format parity —
    #    a deliberate departure (see the action.py JSON codec note and
    #    this module's docstring): from_json takes the JSON string
    #    to_json produced, carries a version field, and uses the tagged
    #    tensor form. Debug/interop surface; the hot path stays msgpack
    #    (to_bytes). --
    def to_json(self) -> str:
        import json

        return json.dumps(
            {
                "version": WIRE_VERSION,
                "max_length": self.max_length,
                "actions": [a.to_jsonable() for a in self._actions],
            },
            allow_nan=False,
        )

    @classmethod
    def from_json(cls, text: str) -> "Trajectory":
        import json

        obj = json.loads(text)
        version = obj.get("version")
        if version != WIRE_VERSION:
            raise ValueError(
                f"unsupported trajectory json version: {version}")
        actions = [
            ActionRecord.from_jsonable(a) for a in obj.get("actions", [])
        ]
        traj = cls(max_length=obj.get("max_length") or max(len(actions), 1))
        traj._actions = actions
        return traj

    # reference static-method name (o3_trajectory.rs `traj_from_json`)
    traj_from_json = from_json


def serialize_actions(actions: Iterable[ActionRecord]) -> bytes:
    """Actions → one msgpack frame (ref codec: trajectory.rs:50-55)."""
    wire = {"v": WIRE_VERSION, "acts": [a.to_wire() for a in actions]}
    return msgpack.packb(wire, use_bin_type=True)


def deserialize_actions(buf: bytes | memoryview) -> list[ActionRecord]:
    wire = msgpack.unpackb(buf, raw=False, ext_hook=_ext_hook, strict_map_key=False)
    version = wire.get("v")
    if version != WIRE_VERSION:
        raise ValueError(f"unsupported trajectory wire version: {version}")
    return [ActionRecord.from_wire(w) for w in wire["acts"]]
