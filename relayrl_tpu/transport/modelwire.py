"""Model wire-format v2: per-leaf delta frames with keyframes and resync.

PRs 2-3 left model distribution as the untouched hot path: every publish
re-serializes the whole policy (``ModelBundle.to_bytes``) and ships it to
every subscriber, so the distribution plane costs
O(actors x model_size x publish_rate) bytes even though consecutive RL
updates move each parameter by a tiny amount. This module is the wire
format that exploits that structure, losslessly:

* **Keyframes** carry the full per-leaf payload plus the *leaf manifest*
  (paths, dtypes, shapes — :func:`relayrl_tpu.types.model_bundle.
  leaf_manifest`); they are the resync anchor and are emitted every
  ``keyframe_interval`` publishes and whenever the manifest changes.
* **Delta frames** carry, for each leaf that changed since the last
  published snapshot, the bitwise integer difference of the raw storage
  words, zigzag-mapped and split into byte planes. A small update shares
  its sign/exponent/high-mantissa bits with the base value, so the high
  byte planes are almost entirely zero and the per-frame codec folds
  them away; unchanged leaves (frozen trunks, untrained positional rows)
  are skipped outright. Integer subtraction is exact, so decode
  reconstructs the published params **bit-identically** — float
  arithmetic is never used on the wire.
* **Per-frame compression** with a codec ladder (zstd if importable,
  else lz4, else stdlib zlib; ``Z_RLE`` strategy for delta planes, where
  it beats default deflate on both ratio and speed) and an
  incompressible-skip heuristic; the codec id rides the frame header,
  and every frame carries a CRC32 of the shipped payload.
* **Chunking** (:func:`split_frame` / :class:`ChunkReassembler`) splits
  frames larger than ``transport.chunk_bytes`` into ordered chunk frames
  for broadcast planes that prefer bounded message sizes (ZMQ HWM
  accounting); the native backend passes them through as opaque bytes
  and the Python listeners reassemble before decode.

Decode is zero-copy: leaf payloads are ``np.frombuffer`` views into the
(decompressed) received frame, applied into preallocated per-leaf host
buffers (:class:`ModelWireDecoder`); the actor then does ONE
``jax.device_put`` of the assembled pytree inside the existing
``apply_bundle_swap`` gate — no flax ``from_bytes`` deep restore on the
hot path. v1 frames (plain ``ModelBundle`` msgpack) still decode for
rolling compatibility: :func:`is_wire_frame` sniffs the magic, and a v1
delivery reseeds the decoder so a mixed rollout converges.

Resync: a delta whose ``base`` version or manifest hash does not match
the held state raises :class:`WireBaseMismatch` once (the caller may
re-poll with ``ver=-1`` on pull transports); the decoder then waits for
the next keyframe, silently dropping deltas, which bounds the blackout
to ``keyframe_interval`` publishes on broadcast transports.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Any

import msgpack
import numpy as np

MAGIC = b"RLW2"
_HDR_FIXED = len(MAGIC) + 1 + 4  # magic | kind u8 | header_len u32le

KIND_KEYFRAME = 1
KIND_DELTA = 2
KIND_CHUNK = 3

# payload codec ids (frame header "codec")
CODEC_RAW = 0
CODEC_ZSTD = 1
CODEC_LZ4 = 2
CODEC_ZLIB = 3

# per-leaf delta encodings (delta header "leaves" entries)
ENC_RAW = 0     # raw replacement bytes (dtypes the integer path can't carry)
ENC_IDELTA = 1  # zigzag(int(new) - int(base)) split into byte planes


class WireFrameError(ValueError):
    """Malformed/corrupt v2 frame (bad magic, header, CRC, or length)."""


class WireBaseMismatch(WireFrameError):
    """Delta frame whose base version / manifest does not match the held
    state — the caller should trigger a resync (re-poll with ``ver=-1``
    on pull transports; broadcast decoders wait for the next keyframe)."""

    def __init__(self, msg: str, base: int, held: int):
        super().__init__(msg)
        self.base = base
        self.held = held


def is_wire_frame(buf) -> bool:
    """True when ``buf`` is a v2 wire frame (v1 ``ModelBundle`` msgpack
    blobs start with a fixmap byte, never this magic)."""
    return bytes(buf[:4]) == MAGIC


def is_chunk_frame(buf) -> bool:
    return (len(buf) > _HDR_FIXED and bytes(buf[:4]) == MAGIC
            and buf[4] == KIND_CHUNK)


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------

def _zlib_compress_delta(data: bytes) -> bytes:
    # Z_RLE: run-length matches + Huffman literals. Delta payloads are
    # byte-plane transposed, so the high planes are long zero runs (RLE
    # folds them at memcpy speed) and the low planes are skewed literals
    # (Huffman entropy-codes them) — measured both faster AND tighter
    # than default deflate on real update deltas (benches/results/
    # model_wire.json).
    co = zlib.compressobj(6, zlib.DEFLATED, zlib.MAX_WBITS, 9, zlib.Z_RLE)
    return co.compress(data) + co.flush()


def _zlib_compress_key(data: bytes) -> bytes:
    # Keyframes are raw float payloads — mostly incompressible except
    # zero-initialized regions; spend little CPU on them.
    co = zlib.compressobj(1)
    return co.compress(data) + co.flush()


def _codec_table() -> dict[int, tuple]:
    """``{codec_id: (name, compress(data, hint), decompress)}`` for every
    codec importable in this process. Decompression support is what
    matters cross-process: a frame names its codec in the header, so a
    decoder missing that library fails loudly instead of guessing."""
    table: dict[int, tuple] = {}
    try:  # zstd: best ratio/speed when present
        import zstandard

        _c = zstandard.ZstdCompressor(level=3)
        _d = zstandard.ZstdDecompressor()
        table[CODEC_ZSTD] = ("zstd", lambda b, hint: _c.compress(b),
                             _d.decompress)
    except ImportError:
        pass
    try:
        import lz4.frame as _lz4f

        table[CODEC_LZ4] = ("lz4", lambda b, hint: _lz4f.compress(b),
                            _lz4f.decompress)
    except ImportError:
        pass
    table[CODEC_ZLIB] = (
        "zlib",
        lambda b, hint: (_zlib_compress_delta(b) if hint == "delta"
                         else _zlib_compress_key(b)),
        zlib.decompress)
    return table


_CODECS: dict[int, tuple] | None = None


def _codecs() -> dict[int, tuple]:
    global _CODECS
    if _CODECS is None:
        _CODECS = _codec_table()
    return _CODECS


def resolve_codec(compress: Any) -> int:
    """``transport.compress`` knob -> codec id. ``"auto"``/``True`` walks
    the ladder (zstd > lz4 > zlib); a codec name pins it (falling back to
    the ladder with a note if that library is absent); ``False``/
    ``"none"``/``"raw"`` disables compression."""
    if compress in (False, None, "none", "raw", "off", 0):
        return CODEC_RAW
    table = _codecs()
    if isinstance(compress, str) and compress not in ("auto", "true", "on"):
        for cid, (name, _c, _d) in table.items():
            if name == compress:
                return cid
        print(f"[modelwire] codec {compress!r} not importable here; "
              f"falling back to the auto ladder", flush=True)
    for cid in (CODEC_ZSTD, CODEC_LZ4, CODEC_ZLIB):
        if cid in table:
            return cid
    return CODEC_RAW


_MIN_COMPRESS_BYTES = 1024
_SAMPLE_BYTES = 65536


def _maybe_compress(payload: bytes, codec: int, hint: str) -> tuple[int, bytes]:
    """Compress ``payload`` with ``codec`` unless it is tiny or the
    incompressible-skip heuristic fires (a sample that barely shrinks
    predicts the whole payload won't pay for its CPU)."""
    if codec == CODEC_RAW or len(payload) < _MIN_COMPRESS_BYTES:
        return CODEC_RAW, payload
    _name, comp, _dec = _codecs()[codec]
    if len(payload) > 4 * _SAMPLE_BYTES:
        sample = payload[:_SAMPLE_BYTES]
        if len(comp(sample, hint)) > 0.92 * len(sample):
            return CODEC_RAW, payload
    out = comp(payload, hint)
    if len(out) >= len(payload):
        return CODEC_RAW, payload
    return codec, out


def _decompress(payload, codec: int, rawlen: int) -> bytes:
    if codec == CODEC_RAW:
        return payload
    entry = _codecs().get(codec)
    if entry is None:
        raise WireFrameError(
            f"frame compressed with codec id {codec} but no matching "
            f"library is importable in this process")
    out = entry[2](bytes(payload))
    if len(out) != rawlen:
        raise WireFrameError(
            f"decompressed payload is {len(out)} bytes, header says {rawlen}")
    return out


# ---------------------------------------------------------------------------
# per-leaf integer delta codec
# ---------------------------------------------------------------------------

_UI = {2: np.uint16, 4: np.uint32, 8: np.uint64}
_SI = {2: np.int16, 4: np.int32, 8: np.int64}


def _encode_leaf_delta(base: np.ndarray, new: np.ndarray) -> bytes:
    """zigzag(int(new) - int(base)) as byte planes. Exact for every dtype
    whose storage words fit the integer view (2/4/8-byte floats and
    ints): subtraction wraps mod 2**bits, so decode's wrapping add
    reconstructs the new words bit-for-bit."""
    itemsize = new.dtype.itemsize
    ui, si = _UI[itemsize], _SI[itemsize]
    au = np.ascontiguousarray(base).view(ui).ravel()
    bu = np.ascontiguousarray(new).view(ui).ravel()
    s = (bu - au).view(si)
    zz = ((s << 1) ^ (s >> (itemsize * 8 - 1))).view(ui)
    # byte-plane transpose: plane b holds byte b of every word, so the
    # near-constant high planes become long runs for the codec.
    return np.ascontiguousarray(zz.view(np.uint8).reshape(-1, itemsize).T).tobytes()


def _apply_leaf_delta(buf: np.ndarray, seg) -> None:
    """In-place ``buf += delta`` in the integer domain. ``seg`` is a
    zero-copy view into the received payload."""
    itemsize = buf.dtype.itemsize
    ui = _UI[itemsize]
    n = buf.size
    planes = np.frombuffer(seg, np.uint8, count=itemsize * n).reshape(itemsize, n)
    zz = np.ascontiguousarray(planes.T).view(ui).ravel()
    one = ui(1)
    s = (zz >> one) ^ (ui(0) - (zz & one))  # un-zigzag, still unsigned bits
    bu = buf.view(ui).ravel()
    bu += s  # wrapping add == adding the signed delta


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def _pack_frame(kind: int, header: dict, payload: bytes) -> bytes:
    h = msgpack.packb(header, use_bin_type=True)
    return b"".join((MAGIC, bytes((kind,)),
                     len(h).to_bytes(4, "little"), h, payload))


def parse_frame(buf) -> tuple[int, dict, memoryview]:
    """``frame -> (kind, header, payload_view)`` — the payload is a
    zero-copy view into ``buf``."""
    mv = memoryview(buf)
    if len(mv) < _HDR_FIXED or bytes(mv[:4]) != MAGIC:
        raise WireFrameError("not a model-wire v2 frame")
    kind = mv[4]
    hlen = int.from_bytes(mv[5:9], "little")
    if _HDR_FIXED + hlen > len(mv):
        raise WireFrameError("truncated frame header")
    try:
        header = msgpack.unpackb(mv[_HDR_FIXED:_HDR_FIXED + hlen], raw=False)
    except Exception as e:
        raise WireFrameError(f"undecodable frame header: {e!r}") from e
    return kind, header, mv[_HDR_FIXED + hlen:]


def verify_frame(buf) -> tuple[int, int, int | None]:
    """Per-hop integrity check for frame forwarders (the relay plane):
    parse the header, re-verify the payload CRC, and return ``(kind,
    version, base_version)`` — ``base_version`` is None for keyframes
    and chunk frames. Raises :class:`WireFrameError` on a corrupt frame
    so a relay drops it at THIS hop instead of re-broadcasting rot to
    its whole subtree. The frame bytes are never modified: a verified
    frame re-broadcasts verbatim."""
    kind, hdr, payload = parse_frame(buf)
    try:
        if zlib.crc32(payload) != hdr["crc"]:
            raise WireFrameError(
                f"frame CRC mismatch at forward hop (ver {hdr.get('ver')})")
        version = int(hdr["ver"])
        base = int(hdr["base"]) if kind == KIND_DELTA else None
    except WireFrameError:
        raise
    except (KeyError, ValueError, TypeError, OverflowError) as e:
        # A mangled msgpack HEADER can decode into missing keys or wrong
        # value types while the payload CRC still matches — every such
        # shape must surface as the one exception forwarders catch, or
        # a hostile frame kills the listener thread that carried it.
        raise WireFrameError(f"mangled frame header: {e!r}") from e
    return kind, version, base


def manifest_hash(manifest: list) -> int:
    """Stable 32-bit hash of a leaf manifest (paths + dtypes + shapes) —
    deltas carry it so a decoder can detect that its buffer layout no
    longer matches the publisher's tree."""
    return zlib.crc32(msgpack.packb(manifest, use_bin_type=True))


# ---------------------------------------------------------------------------
# chunking
# ---------------------------------------------------------------------------

def split_frame(frame: bytes, chunk_bytes: int, version: int) -> list[bytes]:
    """Split ``frame`` into ordered chunk frames of at most ~chunk_bytes
    payload each; a frame that already fits is returned unwrapped. The
    receiving listener feeds everything through a
    :class:`ChunkReassembler`, which passes non-chunk frames straight
    through."""
    if chunk_bytes <= 0 or len(frame) <= chunk_bytes:
        return [frame]
    n = (len(frame) + chunk_bytes - 1) // chunk_bytes
    out = []
    for i in range(n):
        part = frame[i * chunk_bytes:(i + 1) * chunk_bytes]
        out.append(_pack_frame(
            KIND_CHUNK,
            {"ver": int(version), "idx": i, "n": n,
             "crc": zlib.crc32(part)},
            part))
    return out


class ChunkReassembler:
    """Orders chunk frames back into the original frame. Keyed by the
    publisher version: a chunk from a newer version discards any
    incomplete older state (broadcast planes may drop messages under
    backpressure — the lost frame surfaces as a delta-base mismatch and
    resyncs at the next keyframe, so partial frames are never
    delivered)."""

    def __init__(self):
        self._ver: int | None = None
        self._total = 0
        self._parts: list[bytes] = []
        self.dropped_partials = 0

    @property
    def pending(self) -> bool:
        return self._ver is not None

    def feed(self, buf) -> bytes | None:
        """Returns a complete frame (chunked or pass-through), or None
        while a chunked frame is still accumulating / on a corrupt
        chunk."""
        if not is_chunk_frame(buf):
            if self._ver is not None:
                self._reset(dropped=True)
            return bytes(buf) if not isinstance(buf, bytes) else buf
        try:
            _kind, hdr, payload = parse_frame(buf)
            ver, idx, total = int(hdr["ver"]), int(hdr["idx"]), int(hdr["n"])
            if zlib.crc32(payload) != hdr["crc"]:
                raise WireFrameError("chunk CRC mismatch")
        except WireFrameError:
            self._reset(dropped=self._ver is not None)
            return None
        if idx == 0:
            if self._ver is not None:
                self._reset(dropped=True)
            self._ver, self._total, self._parts = ver, total, []
        elif ver != self._ver or idx != len(self._parts):
            # missed/reordered chunk: drop the partial frame entirely
            self._reset(dropped=self._ver is not None)
            return None
        self._parts.append(bytes(payload))
        if len(self._parts) < self._total:
            return None
        frame = b"".join(self._parts)
        self._reset(dropped=False)
        return frame

    def _reset(self, dropped: bool) -> None:
        if dropped:
            self.dropped_partials += 1
        self._ver, self._total, self._parts = None, 0, []


# ---------------------------------------------------------------------------
# publisher-side encoder
# ---------------------------------------------------------------------------

class ModelWireEncoder:
    """Keeps the last-published host snapshot and turns each publish into
    a keyframe or a delta frame. Runs off the learner thread (the
    publisher thread in the pipelined server); ``frame_for`` is the
    thread-safe read surface pull transports (gRPC long-polls) use to
    pick delta-vs-full per subscriber."""

    #: Models smaller than this publish as plain v1 bundles (the actor's
    #: sniffing decode handles both formats): at ~100 KB the whole
    #: broadcast is two packets, dense-update deltas barely compress,
    #: and the zigzag/deflate work would COST publish→swap latency where
    #: there are no meaningful bytes to win (benches/results/
    #: model_wire.json latency rows). Deltas start paying around the
    #: quarter-megabyte mark and dominate from transformer sizes up.
    SMALL_MODEL_BYTES = 256 * 1024

    def __init__(self, keyframe_interval: int = 10, compress: Any = "auto",
                 small_model_bytes: int | None = None):
        from relayrl_tpu import telemetry

        # interval N: every Nth publish is a keyframe (N <= 1 makes every
        # frame a keyframe; the resync blackout on broadcast planes is
        # bounded by this many publishes). Clamped to >= 1 — an interval
        # that never keyframed would turn the first dropped delta into a
        # permanent blackout on broadcast transports.
        self.keyframe_interval = max(1, int(keyframe_interval))
        self.codec = resolve_codec(compress)
        self.small_model_bytes = (self.SMALL_MODEL_BYTES
                                  if small_model_bytes is None
                                  else int(small_model_bytes))
        self._base: list[np.ndarray] | None = None
        self._manifest: list | None = None
        self._mh = 0
        self._since_key = 0
        self._force_key = False
        self._passthrough = False  # latched by the first size check
        self._lock = threading.Lock()
        self.version = -1
        self.last_frame: bytes | None = None
        self.last_frame_base: int | None = None  # None == keyframe
        reg = telemetry.get_registry()
        self._m_key = reg.counter(
            "relayrl_wire_keyframes_total",
            "full keyframes published on the model wire")
        self._m_delta = reg.counter(
            "relayrl_wire_delta_frames_total",
            "delta frames published on the model wire")
        self._m_bytes = reg.counter(
            "relayrl_wire_publish_bytes_total",
            "model-wire frame bytes handed to the transport")
        self._m_saved = reg.counter(
            "relayrl_wire_publish_bytes_saved_total",
            "raw param bytes minus shipped frame bytes, accumulated")
        self._m_encode = reg.histogram(
            "relayrl_wire_encode_seconds",
            "one keyframe/delta encode on the publisher thread")

    def force_keyframe(self) -> None:
        """Make the next publish a keyframe regardless of the interval."""
        self._force_key = True

    def encode(self, version: int, arch: dict, host_params) -> tuple[bytes, dict]:
        """``(frame_bytes, info)`` for one publish. ``host_params`` must
        be a host (numpy) pytree; the encoder keeps its leaves as the
        next publish's delta base, so callers must not mutate them."""
        from relayrl_tpu.types.model_bundle import leaf_manifest

        t0 = time.monotonic()
        if self._passthrough:
            # Latched on the first publish: model size is fixed for the
            # life of a training run (actors hard-reject arch changes),
            # so later publishes skip the flatten entirely — passthrough
            # latency is to_bytes + header, byte-for-byte the v1 path.
            return self._encode_passthrough(version, arch, host_params,
                                            None, t0)
        manifest, leaves = leaf_manifest(host_params)
        mh = manifest_hash(manifest)
        raw_total = sum(leaf.nbytes for leaf in leaves)
        if raw_total < self.small_model_bytes:
            self._passthrough = True
            return self._encode_passthrough(version, arch, host_params,
                                            raw_total, t0)
        keyframe = (self._base is None or mh != self._mh or self._force_key
                    or self._since_key >= self.keyframe_interval)
        if keyframe:
            frame = self._encode_keyframe(version, arch, manifest, mh, leaves)
            base: int | None = None
            self._since_key = 1
            self._force_key = False
            self._m_key.inc()
        else:
            frame = self._encode_delta(version, arch, mh, leaves)
            base = self.version
            self._since_key += 1
            self._m_delta.inc()
        self._base, self._manifest, self._mh = leaves, manifest, mh
        with self._lock:
            self.version = int(version)
            self.last_frame = frame
            self.last_frame_base = base
        dt = time.monotonic() - t0
        self._m_encode.observe(dt)
        self._m_bytes.inc(len(frame))
        self._m_saved.inc(max(0, raw_total - len(frame)))
        return frame, {
            "kind": "keyframe" if keyframe else "delta",
            "base_version": base,
            "frame_bytes": len(frame),
            "raw_bytes": raw_total,
            "encode_s": dt,
        }

    def _encode_passthrough(self, version, arch, host_params, raw_total,
                            t0) -> tuple[bytes, dict]:
        """Small-model publish: a plain v1 bundle (every subscriber's
        sniffing decode handles it; a v1 delivery also reseeds live v2
        decoders). Counted like a keyframe — it IS a full model."""
        from relayrl_tpu.types.model_bundle import ModelBundle

        frame = ModelBundle(version=int(version), arch=dict(arch),
                            params=host_params).to_bytes()
        self._base = None  # passthrough keeps no delta base
        self._since_key = 0
        self._force_key = False
        with self._lock:
            self.version = int(version)
            self.last_frame = frame
            self.last_frame_base = None  # decodable by anyone, keyframe-like
        dt = time.monotonic() - t0
        self._m_key.inc()
        self._m_encode.observe(dt)
        self._m_bytes.inc(len(frame))
        return frame, {
            "kind": "v1_passthrough", "base_version": None,
            "frame_bytes": len(frame),
            "raw_bytes": len(frame) if raw_total is None else raw_total,
            "encode_s": dt,
        }

    def frame_for(self, known_version: int) -> tuple[int, bytes] | None:
        """Pull-transport surface: the latest frame IF the subscriber at
        ``known_version`` can decode it (its base matches, or it is a
        keyframe) — else None, and the caller serves a full bundle."""
        with self._lock:
            if self.last_frame is None or self.version <= known_version:
                return None
            if self.last_frame_base is None \
                    or self.last_frame_base == known_version:
                return self.version, self.last_frame
        return None

    def _encode_keyframe(self, version, arch, manifest, mh, leaves) -> bytes:
        payload = b"".join(
            np.ascontiguousarray(leaf).tobytes() for leaf in leaves)
        codec, shipped = _maybe_compress(payload, self.codec, "key")
        header = {
            "ver": int(version), "arch": dict(arch), "man": manifest,
            "mh": mh, "codec": codec, "crc": zlib.crc32(shipped),
            "rawlen": len(payload),
        }
        return _pack_frame(KIND_KEYFRAME, header, shipped)

    def _encode_delta(self, version, arch, mh, leaves) -> bytes:
        entries: list[list[int]] = []
        segs: list[bytes] = []
        for i, (a, b) in enumerate(zip(self._base, leaves)):
            # Byte-view compare (no copies, and bit-exact: +0.0 vs -0.0
            # or differing NaN payloads must NOT count as unchanged).
            if np.array_equal(a.view(np.uint8), b.view(np.uint8)):
                continue  # unchanged leaf: skipped outright
            if b.dtype.itemsize in _UI and a.dtype == b.dtype:
                seg = _encode_leaf_delta(a, b)
                enc = ENC_IDELTA
            else:
                seg = np.ascontiguousarray(b).tobytes()
                enc = ENC_RAW
            entries.append([i, enc, len(seg)])
            segs.append(seg)
        payload = b"".join(segs)
        codec, shipped = _maybe_compress(payload, self.codec, "delta")
        header = {
            "ver": int(version), "base": int(self.version),
            "arch": dict(arch), "mh": mh, "codec": codec,
            "crc": zlib.crc32(shipped), "rawlen": len(payload),
            "leaves": entries,
        }
        return _pack_frame(KIND_DELTA, header, shipped)


# ---------------------------------------------------------------------------
# actor-side decoder
# ---------------------------------------------------------------------------

class ModelWireDecoder:
    """Holds the preallocated per-leaf host buffers a subscription's
    frames apply into, plus the version/manifest state that gates them.

    One decoder per model subscription (PolicyActor / VectorActorHost —
    both lazily create one on the first wire delivery). NOT thread-safe:
    drive it from the single transport listener thread that owns the
    subscription, which is how every backend already delivers."""

    def __init__(self):
        from relayrl_tpu import telemetry

        self.version = -1
        self.arch: dict = {}
        self.manifest: list | None = None
        self._mh = 0
        self._buffers: list[np.ndarray] = []
        self.awaiting_keyframe = False
        self.deltas_applied = 0
        self.keyframes_applied = 0
        self.resyncs = 0
        self.dropped_frames = 0
        reg = telemetry.get_registry()
        self._m_delta = reg.counter(
            "relayrl_wire_deltas_applied_total",
            "delta frames applied into the actor's host buffers")
        self._m_key = reg.counter(
            "relayrl_wire_keyframes_applied_total",
            "keyframes applied into the actor's host buffers")
        self._m_resync = reg.counter(
            "relayrl_wire_resyncs_total",
            "base/manifest mismatches that forced a resync")
        self._m_dropped = reg.counter(
            "relayrl_wire_frames_dropped_total",
            "frames dropped (corrupt, stale, or awaiting a keyframe)")
        self._m_decode = reg.histogram(
            "relayrl_wire_decode_seconds",
            "one frame parse+decompress+apply into host buffers")

    def seed(self, version: int, arch: dict, host_params) -> None:
        """(Re)initialize from a full model — the handshake bundle, or
        any v1 full-bundle delivery on a mixed-version fleet. Copies the
        leaves: the buffers must outlive the source tree."""
        from relayrl_tpu.types.model_bundle import leaf_manifest

        manifest, leaves = leaf_manifest(host_params)
        self._install_manifest(manifest)
        for buf, leaf in zip(self._buffers, leaves):
            buf[...] = leaf
        self.version = int(version)
        self.arch = dict(arch)
        self.awaiting_keyframe = False

    def decode(self, blob) -> tuple[int, dict, Any] | None:
        """One frame -> ``(version, arch, host_tree)`` where the tree's
        leaves ARE the live preallocated buffers (device_put before the
        next frame arrives — the listener thread's natural order), or
        None when the frame was stale/dropped/awaiting resync.

        Raises :class:`WireBaseMismatch` exactly once per divergence so
        the owner can trigger a transport-level resync; subsequent
        deltas are dropped silently until a keyframe lands."""
        t0 = time.monotonic()
        try:
            kind, hdr, payload = parse_frame(blob)
        except WireFrameError:
            self.dropped_frames += 1
            self._m_dropped.inc()
            raise
        if kind == KIND_CHUNK:
            raise WireFrameError(
                "chunk frame reached the decoder — the transport listener "
                "must reassemble (ChunkReassembler) before decode")
        version = int(hdr["ver"])
        if version <= self.version:
            self.dropped_frames += 1
            self._m_dropped.inc()
            return None  # duplicate/stale delivery
        shipped = payload
        if zlib.crc32(shipped) != hdr["crc"]:
            self.dropped_frames += 1
            self._m_dropped.inc()
            raise WireFrameError(f"frame CRC mismatch (ver {version})")
        if kind == KIND_KEYFRAME:
            out = self._decode_keyframe(version, hdr, shipped)
        elif kind == KIND_DELTA:
            out = self._decode_delta(version, hdr, shipped)
        else:
            self.dropped_frames += 1
            self._m_dropped.inc()
            raise WireFrameError(f"unknown frame kind {kind}")
        if out is not None:
            self._m_decode.observe(time.monotonic() - t0)
        return out

    def tree(self, params_template: Any | None = None):
        """The current buffers assembled back into a params pytree
        (template-driven when given, nested dicts otherwise)."""
        from relayrl_tpu.types.model_bundle import tree_from_leaves

        return tree_from_leaves(self.manifest, self._buffers,
                                params_template)

    # -- internals --
    def _install_manifest(self, manifest: list) -> None:
        mh = manifest_hash(manifest)
        if self.manifest is not None and mh == self._mh:
            return  # layout unchanged: keep the buffers (and their bytes)
        self.manifest = manifest
        self._mh = mh
        self._buffers = [
            np.empty(tuple(shape), dtype=np.dtype(dtype))
            for (_path, dtype, shape) in manifest
        ]

    def _decode_keyframe(self, version, hdr, shipped):
        payload = _decompress(shipped, int(hdr["codec"]), int(hdr["rawlen"]))
        self._install_manifest(hdr["man"])
        if sum(b.nbytes for b in self._buffers) != len(payload):
            # Before any buffer is touched: a short/long payload would
            # otherwise leave a half-written snapshot behind.
            self.awaiting_keyframe = True
            raise WireFrameError(
                f"keyframe payload is {len(payload)} bytes, manifest "
                f"needs {sum(b.nbytes for b in self._buffers)}")
        off = 0
        for buf in self._buffers:
            view = np.frombuffer(payload, buf.dtype, count=buf.size,
                                 offset=off).reshape(buf.shape)
            buf[...] = view
            off += buf.nbytes
        self.version = version
        self.arch = dict(hdr["arch"])
        self.awaiting_keyframe = False
        self.keyframes_applied += 1
        self._m_key.inc()
        return version, self.arch, self.tree()

    def _decode_delta(self, version, hdr, shipped):
        base = int(hdr["base"])
        if self.awaiting_keyframe:
            self.dropped_frames += 1
            self._m_dropped.inc()
            return None  # blackout until the next keyframe
        if base != self.version or int(hdr["mh"]) != self._mh:
            self.awaiting_keyframe = True
            self.resyncs += 1
            self._m_resync.inc()
            raise WireBaseMismatch(
                f"delta base {base} (manifest {hdr['mh']:#x}) does not "
                f"match held version {self.version} (manifest "
                f"{self._mh:#x}) — resync required",
                base=base, held=self.version)
        payload = _decompress(shipped, int(hdr["codec"]), int(hdr["rawlen"]))
        try:
            off = 0
            for idx, enc, seglen in hdr["leaves"]:
                buf = self._buffers[idx]
                seg = memoryview(payload)[off:off + seglen]
                if enc == ENC_IDELTA:
                    _apply_leaf_delta(buf, seg)
                elif enc == ENC_RAW:
                    buf[...] = np.frombuffer(
                        seg, buf.dtype, count=buf.size).reshape(buf.shape)
                else:
                    raise WireFrameError(f"unknown leaf encoding {enc}")
                off += seglen
        except Exception:
            # The CRC passed but the entries didn't apply cleanly
            # (publisher/decoder disagreement): the buffers may be
            # half-mutated, so nothing short of a keyframe is trustworthy.
            self.awaiting_keyframe = True
            self.resyncs += 1
            self._m_resync.inc()
            raise
        self.version = version
        self.arch = dict(hdr["arch"])
        self.deltas_applied += 1
        self._m_delta.inc()
        return version, self.arch, self.tree()


__all__ = [
    "MAGIC", "KIND_KEYFRAME", "KIND_DELTA", "KIND_CHUNK",
    "CODEC_RAW", "CODEC_ZSTD", "CODEC_LZ4", "CODEC_ZLIB",
    "WireFrameError", "WireBaseMismatch",
    "is_wire_frame", "is_chunk_frame", "parse_frame", "verify_frame",
    "manifest_hash",
    "split_frame", "ChunkReassembler",
    "ModelWireEncoder", "ModelWireDecoder", "resolve_codec",
]
