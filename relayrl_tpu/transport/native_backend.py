"""Native C++ transport backend (ctypes bindings over native/librelayrl_native.so).

The reference's transport core is native Rust (tokio + zmq + tonic); the
TPU-native equivalent is the C++ core under ``native/`` — a framed-TCP
epoll event loop speaking the same envelopes as the Python backends.
This module is the thin ctypes binding; build the library with
``make -C native`` first.
"""

from __future__ import annotations

import os

_LIB_NAMES = ("librelayrl_native.so",)


def _find_library() -> str | None:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for name in _LIB_NAMES:
        for cand in (os.path.join(here, "native", name),
                     os.path.join(here, name)):
            if os.path.isfile(cand):
                return cand
    return None


def native_available() -> bool:
    return _find_library() is not None


def _require_lib() -> str:
    path = _find_library()
    if path is None:
        raise RuntimeError(
            "native transport library not built; run `make -C native` "
            "(falls back: use server_type='zmq' or 'grpc')")
    return path


# Real implementations are bound in native_bindings once the .so exists;
# import them lazily so zmq/grpc users never touch ctypes.
def NativeServerTransport(*args, **kwargs):
    from relayrl_tpu.transport.native_bindings import NativeServerTransportImpl

    return NativeServerTransportImpl(_require_lib(), *args, **kwargs)


def NativeAgentTransport(*args, **kwargs):
    from relayrl_tpu.transport.native_bindings import NativeAgentTransportImpl

    return NativeAgentTransportImpl(_require_lib(), *args, **kwargs)
