"""Native C++ transport backend (ctypes bindings over native/librelayrl_native.so).

The reference's transport core is native Rust (tokio + zmq + tonic); the
TPU-native equivalent is the C++ core under ``native/`` — a framed-TCP
epoll event loop speaking the same envelopes as the Python backends.
This module is the thin ctypes binding; build the library with
``make -C native`` first.
"""

from __future__ import annotations

import os

_LIB_NAMES = ("librelayrl_native.so",)


def _find_library() -> str | None:
    # Wheel install: the .so ships inside the package (setup.py builds
    # it into relayrl_tpu/_native/ — reference parity with its
    # maturin-bundled native artifact). Checked first so an installed
    # user never silently downgrades; source checkouts fall through to
    # the make -C native output.
    try:
        from relayrl_tpu._native import bundled_library_path

        bundled = bundled_library_path()
        if bundled is not None:
            return bundled
    except ImportError:
        pass
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for name in _LIB_NAMES:
        for cand in (os.path.join(here, "native", name),
                     os.path.join(here, name)):
            if os.path.isfile(cand):
                return cand
    return None


def _try_build() -> None:
    """Best-effort `make -C native` when the toolchain is present."""
    import shutil
    import subprocess

    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    native_dir = os.path.join(here, "native")
    if not os.path.isfile(os.path.join(native_dir, "Makefile")):
        return
    if shutil.which("make") is None:
        return
    try:
        subprocess.run(["make", "-C", native_dir], check=True,
                       capture_output=True, timeout=120)
    except (subprocess.SubprocessError, OSError):
        pass


def native_available(build: bool = True) -> bool:
    if _find_library() is not None:
        return True
    if build:
        _try_build()
    return _find_library() is not None


def _require_lib() -> str:
    path = _find_library()
    if path is None:
        _try_build()
        path = _find_library()
    if path is None:
        raise RuntimeError(
            "native transport library not built and auto-build failed; run "
            "`make -C native` (falls back: use server_type='zmq' or 'grpc')")
    return path


# Real implementations are bound in native_bindings once the .so exists;
# import them lazily so zmq/grpc users never touch ctypes.
def NativeServerTransport(*args, **kwargs):
    from relayrl_tpu.transport.native_bindings import NativeServerTransportImpl

    return NativeServerTransportImpl(_require_lib(), *args, **kwargs)


def NativeGrpcServerTransport(*args, **kwargs):
    from relayrl_tpu.transport.native_bindings import (
        NativeGrpcServerTransportImpl,
    )

    return NativeGrpcServerTransportImpl(_require_lib(), *args, **kwargs)


def NativeAgentTransport(*args, **kwargs):
    from relayrl_tpu.transport.native_bindings import NativeAgentTransportImpl

    return NativeAgentTransportImpl(_require_lib(), *args, **kwargs)
