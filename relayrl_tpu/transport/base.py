"""Transport abstractions shared by ZMQ / gRPC / native backends.

The reference hard-wires its two transports into the server/agent classes
(reference: relayrl_framework/src/network/server/training_server_wrapper.rs:
329-379 picks TrainingServerZmq vs TrainingServerGrpc; the agent wrapper
likewise, src/network/client/agent_wrapper.rs:231-270). Here the runtime
composes against these two small interfaces, so ZMQ, gRPC, the C++ native
core, and the in-process test transport are interchangeable.

Wire protocol (same message surface as the reference, SURVEY.md §2.3):

* handshake:   agent → ``GET_MODEL``            → server replies model bundle
               agent → ``MODEL_SET <agent_id>`` → server replies ``ID_LOGGED``
* trajectory:  agent → envelope{agent_id, trajectory bytes} (fire-and-forget)
* model push:  server → broadcast {version, bundle bytes} to all agents

Logical-agent multiplexing (vector actor hosts): one connection may carry
N *logical* agents — ``register`` is callable N times with distinct ids,
each producing its own server-side registry entry, and ``send_trajectory``
takes an optional ``agent_id`` that stamps the envelope so per-agent
trajectory attribution survives the shared socket. The model subscription
stays per-connection (one receipt fans into every logical lane host-side).
"""

from __future__ import annotations

import abc
import threading
from collections import deque
from typing import Callable

import msgpack

# -- command frames (ref: GET_MODEL/MODEL_SET/ID_LOGGED strings,
#    training_zmq.rs:747-829) --
CMD_GET_MODEL = b"GET_MODEL"
CMD_MODEL_SET = b"MODEL_SET"
# Broadcast-plane resync request (relay plane, ISSUE 11): a subscriber
# whose delta base diverged asks the publisher for a keyframe instead of
# passively waiting out ``keyframe_interval`` publishes. Fire-and-forget
# (no reply frame): the heal IS the next broadcast. The root server
# answers with a coalesced, rate-limited ``force_keyframe``; a relay
# answers from its keyframe cache without touching the root.
CMD_RESYNC = b"RESYNC"
REPLY_MODEL = b"MODEL"
REPLY_ID_LOGGED = b"ID_LOGGED"
REPLY_ERROR = b"ERROR"
MODEL_TOPIC = b"model"


def pack_trajectory_envelope(agent_id: str, payload: bytes) -> bytes:
    """``payload`` is opaque to the transport plane: per-record msgpack
    (``types/trajectory.serialize_actions``), a columnar trajectory
    frame (``types/columnar.encode_columnar_frame`` — the anakin tier's
    wire form, sniffed server-side by the RLD1 magic), or a fleet
    telemetry snapshot frame (``telemetry/aggregate.py`` — ``RLS1``
    magic, id ``@fleet/<proc>``, sniffed at every ingest funnel and at
    relays; rides beside trajectories so the metrics plane needs no
    socket of its own). Envelopes carry attribution + the spool's
    ``#s<seq>`` tag identically for all three, so the whole delivery
    plane is wire-form-agnostic."""
    return msgpack.packb({"id": agent_id, "traj": payload}, use_bin_type=True)


def unpack_trajectory_envelope(buf: bytes) -> tuple[str, bytes]:
    env = msgpack.unpackb(buf, raw=False)
    return str(env.get("id", "?")), env["traj"]


# -- batch containers (shared framing helper, ISSUE 11) --
#
# One length-prefixed container serves BOTH coalescing paths:
#
# * ``BATCH_KIND_ENVELOPES`` — a relay's upstream forward: N whole
#   trajectory envelopes (each still carrying its own agent id + ``#s``
#   seq tag verbatim) ship as ONE wire send; the server's ingest funnel
#   splits the container and runs every inner envelope through the
#   normal per-agent dedup/guardrail path, so relay batching is
#   invisible to the exactly-once accounting.
# * ``BATCH_KIND_FRAMES`` — an anakin host's emit coalesce
#   (``actor.emit_coalesce_frames``): N completed columnar segments of
#   ONE logical lane ship as a single spooled send (one seq, one
#   envelope); a staging worker splits the container and decodes each
#   contained RLD1 frame.
#
# Layout: ``RLB1 | kind u8 | count u32le | (len u32le | part)*`` —
# self-delimiting, transport-opaque (every backend's envelope treats the
# payload as bytes; the native C++ core's raw fallback carries it to the
# Python funnel untouched).
BATCH_MAGIC = b"RLB1"
BATCH_KIND_ENVELOPES = 1
BATCH_KIND_FRAMES = 2
_BATCH_HDR = 4 + 1 + 4


def pack_batch(kind: int, parts: list[bytes]) -> bytes:
    out = bytearray(BATCH_MAGIC)
    out.append(kind)
    out += len(parts).to_bytes(4, "little")
    for part in parts:
        out += len(part).to_bytes(4, "little")
        out += part
    return bytes(out)


def batch_kind(buf) -> int | None:
    """The container kind, or None when ``buf`` is not a batch frame."""
    if len(buf) < _BATCH_HDR or bytes(buf[:4]) != BATCH_MAGIC:
        return None
    return buf[4]


def split_batch(buf) -> list[bytes]:
    """Container -> parts. Raises ``ValueError`` on a truncated or
    miscounted container (a data-shaped error the receive loops'
    decode-error narrowing already classifies as droppable)."""
    if batch_kind(buf) is None:
        raise ValueError("not a batch container")
    mv = memoryview(buf)
    count = int.from_bytes(mv[5:9], "little")
    off = _BATCH_HDR
    parts: list[bytes] = []
    for _ in range(count):
        if off + 4 > len(mv):
            raise ValueError("truncated batch container")
        n = int.from_bytes(mv[off:off + 4], "little")
        off += 4
        if off + n > len(mv):
            raise ValueError("truncated batch part")
        parts.append(bytes(mv[off:off + n]))
        off += n
    if off != len(mv):
        raise ValueError("batch container carries trailing bytes")
    return parts


# -- delivery sequence tags (crash-recovery plane, runtime/spool.py) --
#
# Per-agent monotonic sequence numbers ride as a SUFFIX on the envelope
# agent id ("<agent_id>#s<seq>") rather than a new envelope key: the id
# is an opaque attribution string through every backend INCLUDING the
# native C++ columnar fast path (codec.cc decode_envelope_to_blob carries
# the id verbatim but would drop an unknown envelope key on the decoded
# path), so one tagging scheme survives all three transports unchanged.
# The server's ingest funnel strips the tag before attribution and feeds
# the seq to its dedup ledger; ids without a tag (raw transport users,
# pre-spool fleets) pass through untouched.
_SEQ_TAG = "#s"


def tag_agent_seq(agent_id: str, seq: int) -> str:
    return f"{agent_id}{_SEQ_TAG}{int(seq)}"


def split_agent_seq(agent_id: str) -> tuple[str, int | None]:
    """``"a#s42" -> ("a", 42)``; untagged ids -> ``(agent_id, None)``."""
    base, sep, tail = agent_id.rpartition(_SEQ_TAG)
    if sep and tail.isdigit():
        return base, int(tail)
    return agent_id, None


# -- trace-context tags (distributed tracing, telemetry/trace.py) --
#
# A sampled trajectory's trace context rides the SAME envelope-id channel
# as the seq tag, immediately before it: ``<agent>#t<ctx>#s<seq>``. The
# ctx payload is three dot-separated lowercase-hex fields (trace id,
# born_ns, born_version — telemetry.trace.TrajCtx), validated strictly
# on split so an agent id that happens to contain ``#t`` cannot be
# misparsed. Coalescing with the id (instead of a new envelope key)
# is what makes the context survive the native C++ columnar raw-fallback
# path verbatim — codec.cc drops unknown envelope KEYS but carries the
# id untouched, the seq-tag lesson from PR 6 (locked by an explicit
# passthrough test in tests/test_trace.py).
_TRACE_TAG = "#t"
_CTX_HEX = set("0123456789abcdef-")


def tag_agent_trace(agent_id: str, ctx_text: str) -> str:
    return f"{agent_id}{_TRACE_TAG}{ctx_text}"


def split_agent_trace(agent_id: str) -> tuple[str, str | None]:
    """``"a#tdead.beef.2" -> ("a", "dead.beef.2")``; ids without a
    valid trace tag -> ``(agent_id, None)``. Call AFTER
    :func:`split_agent_seq` (the seq tag is outermost on the wire)."""
    base, sep, tail = agent_id.rpartition(_TRACE_TAG)
    if not sep:
        return agent_id, None
    parts = tail.split(".")
    if len(parts) != 3 or not all(
            p and all(c in _CTX_HEX for c in p) for p in parts):
        return agent_id, None
    return base, tail


def pack_model_frame(version: int, bundle_bytes: bytes,
                     pub_ns: int | None = None) -> bytes:
    """``pub_ns`` is the publisher's CLOCK_MONOTONIC stamp (same-host
    comparable — the soak bench's fan-out methodology): when present, a
    receiving SUB thread can compute its own publish→receipt latency
    without any cross-process glue. Omitted by default so handshake
    replies stay byte-stable; absent keys are simply not decoded."""
    frame = {"ver": int(version), "model": bundle_bytes}
    if pub_ns is not None:
        frame["pub_ns"] = int(pub_ns)
    return msgpack.packb(frame, use_bin_type=True)


def unpack_model_frame_ex(buf: bytes) -> tuple[int, bytes, int | None]:
    """Decode a model frame: ``(version, bundle_bytes, pub_ns|None)``
    (``pub_ns`` absent in frames packed without a publisher stamp).
    The ONE decode path — :func:`unpack_model_frame` delegates here so
    a schema change can never drift between two decoders."""
    frame = msgpack.unpackb(buf, raw=False)
    pub_ns = frame.get("pub_ns")
    return (int(frame["ver"]), frame["model"],
            None if pub_ns is None else int(pub_ns))


def unpack_model_frame(buf: bytes) -> tuple[int, bytes]:
    version, model, _ = unpack_model_frame_ex(buf)
    return version, model


# -- typed ingest nacks (guardrail plane) --
#
# Ack-capable transports (gRPC request/response; any future proto with a
# reply) carry the server's admission verdict back to the sender as a
# typed nack instead of a silent drop: code 2 = the sending agent is
# QUARANTINED (stop sending — the spool discards the entry; retrying is
# pointless until parole), code 3 = ingest OVERLOADED (keep the entry
# spooled and retry after ``retry_after_s``). Broadcast planes (zmq PUSH,
# native) have no per-send back-channel; there the same verdicts are
# enforced server-side and surface through telemetry/events only.
NACK_OK = 1
NACK_MALFORMED = 0
NACK_QUARANTINED = 2
NACK_OVERLOADED = 3
# Serving plane only: the endpoint exists but no InferenceService is
# installed (serving.enabled false / misconfigured fleet). PERMANENT —
# thin clients fail fast with the reply's error text instead of
# retrying a misconfiguration into a deadline exhaustion.
NACK_UNAVAILABLE = 4
# Serving plane only: the request named a session id the service no
# longer holds (LRU-evicted under serving.max_sessions, expired past
# serving.session_ttl_s, or a fresh replica after re-route/restart).
# RESYNC, not failure: the client answers by resending the same request
# with its episode window attached — session state is always
# reconstructible-from-client (the replica-death contract).
NACK_SESSION_EVICTED = 5


class IngestNack(RuntimeError):
    """A send the server REFUSED with a typed verdict (not a transport
    failure: the server is alive and answered — callers must not count
    it against circuit breakers or retry budgets)."""

    def __init__(self, code: int, reason: str = "",
                 retry_after_s: float = 0.0):
        super().__init__(f"ingest nack code={code}"
                         f"{f' ({reason})' if reason else ''}")
        self.code = int(code)
        self.reason = reason
        self.retry_after_s = float(retry_after_s)

    @property
    def quarantined(self) -> bool:
        return self.code == NACK_QUARANTINED


# -- receive-loop decode-error narrowing (ISSUE 6 satellite) --
#
# The receive loops used to eat EVERY exception from a frame decode
# ("malformed frame: drop, never crash ingest"), which also swallowed
# genuine bugs. Decode sites now classify: data-shaped errors (anything a
# hostile/corrupt frame can provoke from msgpack/struct/np slicing) are
# dropped with a counter + one log line per site/type; everything else —
# AttributeError, NameError, OSError, MemoryError: states a corrupt frame
# cannot reach — re-raises and takes the loop down loudly.
TRANSIENT_DECODE_ERRORS = (
    ValueError,            # msgpack FormatError subclasses this; int() etc.
    KeyError,              # missing envelope keys
    TypeError,             # wrong msgpack container shapes
    IndexError,            # truncated frames
    OverflowError,
    UnicodeDecodeError,
    msgpack.exceptions.UnpackException,
    msgpack.exceptions.StackError,
)

_swallow_logged: set[tuple[str, str, str]] = set()
_swallow_lock = threading.Lock()


def swallow_decode_error(backend: str, site: str, exc: Exception) -> None:
    """Account for (or refuse to swallow) one receive-loop decode error.

    Transient, data-shaped errors increment
    ``relayrl_transport_swallowed_errors_total{backend,site}`` and log
    once per (backend, site, type); anything else re-raises — a
    programming error must not be laundered as a malformed frame.
    """
    if not isinstance(exc, TRANSIENT_DECODE_ERRORS):
        raise exc
    from relayrl_tpu import telemetry

    telemetry.get_registry().counter(
        "relayrl_transport_swallowed_errors_total",
        "malformed frames dropped by receive loops",
        {"backend": backend, "site": site}).inc()
    key = (backend, site, type(exc).__name__)
    with _swallow_lock:
        first = key not in _swallow_logged
        if first:
            _swallow_logged.add(key)
    if first:
        print(f"[{backend}] {site}: dropped malformed frame "
              f"({type(exc).__name__}: {exc}) — counted in "
              f"relayrl_transport_swallowed_errors_total; further "
              f"occurrences logged only to the counter", flush=True)


class ReceiptLedger:
    """Pre-decode model-receipt ledger: ``(version, rx_mono_ns)`` pairs
    stamped the moment a frame leaves the socket, drained destructively.
    The Python mirror of the native C++ reader's ledger
    (``rl_sub_receipts``), shared by the zmq and grpc agent transports
    so the stamping semantics and bounds can never drift between
    backends (the zmq 64-actor 0.433 lesson, benches/README.md)."""

    def __init__(self, maxlen: int = 65536):
        self._receipts: deque[tuple[int, int]] = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def append(self, version: int, rx_ns: int) -> None:
        with self._lock:
            self._receipts.append((version, rx_ns))

    def drain(self, max_n: int = 65536) -> list[tuple[int, int]]:
        with self._lock:
            out: list[tuple[int, int]] = []
            while self._receipts and len(out) < max_n:
                out.append(self._receipts.popleft())
            return out


def register_subscriber_gauge(backend: str, fn, bind: str = "") -> None:
    """Install the ``relayrl_transport_subscribers`` pull-gauge for one
    server transport (ISSUE 11 satellite: the fan-out observability
    gap). ``fn`` reads the backend's live registry/connection table at
    snapshot time — zmq counts PUB-socket peers via its socket monitor,
    grpc counts fresh long-poll connections, native counts its
    registered-connection table. A relay tree is then verifiable live:
    the root publisher's gauge equals the RELAY count, not the actor
    count. ``bind`` (the publisher's bind address) distinguishes
    instances — a process hosting two same-backend server transports
    (an in-process relay next to a root) must not clobber one gauge
    with the other's table."""
    from relayrl_tpu import telemetry

    labels = {"backend": backend}
    if bind:
        labels["bind"] = bind
    telemetry.get_registry().gauge_fn(
        "relayrl_transport_subscribers", fn,
        "current model-plane subscribers (streams) on this publisher",
        labels)


def server_wire_metrics(backend: str,
                        include_publish_bytes: bool = True) -> dict:
    """The server-side transport instrument set (one per backend,
    process-aggregated; null objects when telemetry is disabled):
    ``recv_total``/``recv_bytes`` for trajectory ingest and
    ``publish_total``(/``publish_bytes``) for model broadcasts.
    ``include_publish_bytes=False`` for pull-based planes (grpc long
    polls) where no broadcast bytes exist to count."""
    from relayrl_tpu import telemetry

    reg = telemetry.get_registry()
    labels = {"backend": backend}
    metrics = {
        "recv_total": reg.counter(
            "relayrl_transport_recv_total",
            "trajectory envelopes received at ingest", labels),
        "recv_bytes": reg.counter(
            "relayrl_transport_recv_bytes_total",
            "trajectory wire bytes received", labels),
        "publish_total": reg.counter(
            "relayrl_transport_publish_total",
            "model publishes", labels),
    }
    if include_publish_bytes:
        metrics["publish_bytes"] = reg.counter(
            "relayrl_transport_publish_bytes_total",
            "model broadcast bytes sent", labels)
    return metrics


def _wide_buckets():
    from relayrl_tpu.telemetry.core import LATENCY_BUCKETS_WIDE

    return LATENCY_BUCKETS_WIDE


def agent_wire_metrics(backend: str) -> dict:
    """The shared agent-side transport instrument set, one registry
    lookup per connection (all metrics are process-aggregated across
    connections of the same backend; null objects when telemetry is
    disabled). Keys:

    * ``send_total`` / ``send_bytes``  — trajectory sends + wire bytes
    * ``send_seconds``                 — per-send latency histogram
    * ``model_recv_total`` / ``model_recv_bytes`` — model frames received
    * ``model_deliver_seconds``        — SUB/poll thread time from the
      pre-decode receipt stamp to ``on_model`` returning (decode + swap
      + persist): the per-receipt cost that starves Python SUB threads
      at fleet fan-out rates (benches/README.md, zmq 64-actor row)
    * ``receipt_latency_seconds``      — publish→receipt when the frame
      carries the publisher's monotonic stamp (same-host pairs only)
    * ``reconnects``                   — transport heals/redials
    """
    from relayrl_tpu import telemetry

    reg = telemetry.get_registry()
    labels = {"backend": backend}
    return {
        "send_total": reg.counter(
            "relayrl_transport_send_total",
            "trajectory payloads sent", labels),
        "send_bytes": reg.counter(
            "relayrl_transport_send_bytes_total",
            "trajectory wire bytes sent (envelope included)", labels),
        # Wide log-spaced grids (telemetry.core.LATENCY_BUCKETS_WIDE)
        # for the two per-op latencies that saturate the default 10 s
        # grid at relay/pod scale: a send riding out an open-breaker
        # stall and a model delivery behind a backed-up SUB thread both
        # legitimately reach tens of seconds, and a grid that pins them
        # in +Inf cannot localize the tail (ISSUE 14 bucket audit).
        "send_seconds": reg.histogram(
            "relayrl_transport_send_seconds",
            "one trajectory send on the caller thread", labels,
            buckets=_wide_buckets()),
        "model_recv_total": reg.counter(
            "relayrl_transport_model_recv_total",
            "model frames received on the subscription", labels),
        "model_recv_bytes": reg.counter(
            "relayrl_transport_model_recv_bytes_total",
            "model frame bytes received", labels),
        "model_deliver_seconds": reg.histogram(
            "relayrl_transport_model_deliver_seconds",
            "receipt stamp to on_model return (decode+swap+persist)",
            labels, buckets=_wide_buckets()),
        "receipt_latency_seconds": reg.histogram(
            "relayrl_transport_receipt_latency_seconds",
            "publish stamp to receipt stamp, same-host monotonic pairs",
            labels),
        "reconnects": reg.counter(
            "relayrl_transport_reconnects_total",
            "connection heals/redials observed", labels),
    }


class ServerTransport(abc.ABC):
    """Server-side: accept handshakes, ingest trajectories, publish models.

    ``on_trajectory(agent_id, payload)`` is invoked from transport threads —
    implementations must be thread-safe; the training server funnels into a
    queue.
    ``get_model()`` returns the current ``(version, bundle_bytes)`` for
    handshakes.
    ``on_register(agent_id)`` records an agent (multi-actor registry,
    ref: training_server_wrapper.rs:159-163).
    ``get_model_update(known_version)`` is the model-wire v2 pull
    surface: the freshest frame a subscriber holding ``known_version``
    can decode (a delta when its base matches, else a full bundle).
    Backends with per-subscriber delivery (gRPC long-polls) prefer it
    when set; broadcast backends never call it. None means "no encoder
    — serve get_model()".
    """

    #: True when this backend's native core answers handshakes itself
    #: from bytes pushed at publish time (set_model) — the embedding
    #: server must then pass ``handshake_bytes`` (a full v1 bundle)
    #: alongside any v2 ``publish_model`` frame.
    needs_handshake_bytes = False

    #: True when this backend carries the serving plane in-band (a
    #: request/response action RPC routed through ``on_infer``) — the
    #: pure-grpcio backend's ``GetActions``. Broadcast backends and the
    #: native C++ cores leave it False; their fleets serve inference on
    #: the dedicated zmq ROUTER plane instead.
    supports_inband_infer = False

    def __init__(self):
        self.on_trajectory: Callable[[str, bytes], None] = lambda *_: None
        self.get_model: Callable[[], tuple[int, bytes]] = lambda: (0, b"")
        self.get_model_update = None
        # Guardrail admission pre-check for ack-capable backends:
        # ``check_ingest(agent_id) -> None | (nack_code, reason,
        # retry_after_s)``. A non-None verdict is returned to the sender
        # as a typed nack INSTEAD of invoking on_trajectory. None (the
        # default) admits everything; broadcast backends never call it.
        self.check_ingest = None
        # Cheap current-version probe (no bundle serialize): long-poll
        # wakeup checks want the version alone — under wire v2 the full
        # v1 bytes serialize lazily, and probing through get_model()
        # would serialize a bundle nobody ships. None -> get_model()[0].
        self.get_model_version = None
        self.on_register: Callable[[str], None] = lambda *_: None
        # Broadcast-plane resync requests (CMD_RESYNC, relay plane): a
        # subscriber's delta base diverged and it wants a keyframe
        # sooner than the interval. Called as ``on_resync(held_version)``
        # — the requester's held model version, or -1 when unknown. The
        # training server binds a coalesced rate-limited force_keyframe
        # (version-blind); a relay compares against its keyframe cache:
        # a late joiner below the cache is served locally, a mid-stream
        # divergence ABOVE it escalates upstream (the cache cannot heal
        # a subscriber newer than itself — decoders drop stale
        # versions). Default no-op — pull transports never need it.
        self.on_resync: Callable[..., None] = lambda *_: None
        # Elastic fleets: fired when a registered agent's connection dies
        # (native transport's crash/idle detection; other backends may
        # never call it).
        self.on_unregister: Callable[[str], None] = lambda *_: None
        # Optional fast path: transports whose native core decodes
        # trajectories into columnar form (native batch drain) deliver
        # DecodedTrajectory objects here when the embedder sets it; raw
        # payload bytes always fall back to ``on_trajectory``.
        self.on_trajectory_decoded = None
        # Serving plane (disaggregated batched inference,
        # transport/serving.py): backends with an in-band
        # request/response action RPC (pure-grpcio ``GetActions``) call
        # ``on_infer(request_bytes) -> reply_bytes`` when the embedder
        # set it — the InferenceService's blocking adapter. None (the
        # default, and on every broadcast-only backend) answers clients
        # with a pointed "serving disabled" error instead of hanging.
        self.on_infer = None
        # Streamed serving plane (pipelined bidi inference,
        # ``StreamActions``): backends with a bidi action stream call
        # ``on_infer_submit(request_bytes, reply) -> bool`` per inbound
        # frame — the InferenceService's non-blocking enqueue, which
        # ALWAYS eventually invokes ``reply(reply_bytes)`` (served,
        # nacked, or shed at stop). None disables the stream RPC with a
        # typed unavailable nack, exactly like ``on_infer``.
        self.on_infer_submit = None

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def stop(self) -> None: ...

    @abc.abstractmethod
    def publish_model(self, version: int, bundle_bytes: bytes) -> None:
        """Broadcast a fresh model to every connected agent."""


class AgentTransport(abc.ABC):
    """Agent-side: handshake, trajectory send, model-update subscription.

    Backends that stamp model receipts pre-decode additionally expose
    ``drain_receipts() -> [(version, rx_mono_ns), ...]`` — the native
    C++ ledger's surface, mirrored in Python by the zmq/grpc listeners
    so fan-out accounting (benches/bench_soak.py) is backend-uniform.
    """

    def __init__(self):
        self.on_model: Callable[[int, bytes], None] = lambda *_: None
        # Reconnect notification (crash-recovery plane): fired from a
        # transport thread when this connection demonstrably healed after
        # a break — zmq via a socket-monitor CONNECTED-after-DISCONNECTED
        # pair, grpc on the first successful poll after a broken channel,
        # native on a ping-heal redial. The agent hooks it to replay its
        # trajectory spool (runtime/spool.py); the server's idempotent
        # ingest makes that replay safe.
        self.on_reconnect: Callable[[], None] = lambda: None

    def _notify_reconnect(self) -> None:
        """Count + forward one observed heal (shared by the backends so
        the reconnect metric and the callback can never drift apart);
        callback errors are isolated — a replay bug must not kill the
        transport thread that noticed the heal."""
        m = getattr(self, "_m", None)
        if m is not None:
            m["reconnects"].inc()
        try:
            self.on_reconnect()
        except Exception as e:
            print(f"[transport] on_reconnect handler failed: {e!r}",
                  flush=True)

    @abc.abstractmethod
    def fetch_model(self, timeout_s: float = 60.0) -> tuple[int, bytes]:
        """Blocking initial handshake: returns (version, bundle bytes)
        (ref: initial_model_handshake, agent_zmq.rs:316-442)."""

    @abc.abstractmethod
    def register(self, agent_id: str, timeout_s: float = 10.0) -> bool:
        """MODEL_SET/ID_LOGGED registration. May be called multiple times
        with distinct ids: each registers one logical agent on this
        connection (vector actor hosts multiplex N lanes over one socket).
        """

    @abc.abstractmethod
    def send_trajectory(self, payload: bytes,
                        agent_id: str | None = None) -> None:
        """Ship one serialized trajectory (per-record msgpack or a
        columnar frame — opaque bytes either way, see
        :func:`pack_trajectory_envelope`). ``agent_id`` stamps the wire
        envelope (defaults to the connection identity) — vector hosts pass
        the owning logical lane's id so server-side attribution is
        per-logical-agent, not per-socket."""

    @abc.abstractmethod
    def start_model_listener(self) -> None:
        """Begin delivering model updates to ``on_model`` asynchronously."""

    def request_resync(self, held_version: int = -1) -> None:
        """Model-wire v2 resync hook: ask the server for a full model on
        the next delivery. ``held_version`` is the caller's decoder
        version when known (WireBaseMismatch carries it) — it rides the
        zmq CMD_RESYNC so a RELAY can decide cache-serve vs escalate;
        the root publisher ignores it. Pull transports (gRPC) re-poll
        with ``ver=-1``; transports without a back-channel rely on the
        publisher's periodic keyframes — the default no-op."""

    @abc.abstractmethod
    def close(self) -> None: ...
