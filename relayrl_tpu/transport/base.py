"""Transport abstractions shared by ZMQ / gRPC / native backends.

The reference hard-wires its two transports into the server/agent classes
(reference: relayrl_framework/src/network/server/training_server_wrapper.rs:
329-379 picks TrainingServerZmq vs TrainingServerGrpc; the agent wrapper
likewise, src/network/client/agent_wrapper.rs:231-270). Here the runtime
composes against these two small interfaces, so ZMQ, gRPC, the C++ native
core, and the in-process test transport are interchangeable.

Wire protocol (same message surface as the reference, SURVEY.md §2.3):

* handshake:   agent → ``GET_MODEL``            → server replies model bundle
               agent → ``MODEL_SET <agent_id>`` → server replies ``ID_LOGGED``
* trajectory:  agent → envelope{agent_id, trajectory bytes} (fire-and-forget)
* model push:  server → broadcast {version, bundle bytes} to all agents

Logical-agent multiplexing (vector actor hosts): one connection may carry
N *logical* agents — ``register`` is callable N times with distinct ids,
each producing its own server-side registry entry, and ``send_trajectory``
takes an optional ``agent_id`` that stamps the envelope so per-agent
trajectory attribution survives the shared socket. The model subscription
stays per-connection (one receipt fans into every logical lane host-side).
"""

from __future__ import annotations

import abc
from typing import Callable

import msgpack

# -- command frames (ref: GET_MODEL/MODEL_SET/ID_LOGGED strings,
#    training_zmq.rs:747-829) --
CMD_GET_MODEL = b"GET_MODEL"
CMD_MODEL_SET = b"MODEL_SET"
REPLY_MODEL = b"MODEL"
REPLY_ID_LOGGED = b"ID_LOGGED"
REPLY_ERROR = b"ERROR"
MODEL_TOPIC = b"model"


def pack_trajectory_envelope(agent_id: str, payload: bytes) -> bytes:
    return msgpack.packb({"id": agent_id, "traj": payload}, use_bin_type=True)


def unpack_trajectory_envelope(buf: bytes) -> tuple[str, bytes]:
    env = msgpack.unpackb(buf, raw=False)
    return str(env.get("id", "?")), env["traj"]


def pack_model_frame(version: int, bundle_bytes: bytes) -> bytes:
    return msgpack.packb({"ver": int(version), "model": bundle_bytes}, use_bin_type=True)


def unpack_model_frame(buf: bytes) -> tuple[int, bytes]:
    frame = msgpack.unpackb(buf, raw=False)
    return int(frame["ver"]), frame["model"]


class ServerTransport(abc.ABC):
    """Server-side: accept handshakes, ingest trajectories, publish models.

    ``on_trajectory(agent_id, payload)`` is invoked from transport threads —
    implementations must be thread-safe; the training server funnels into a
    queue.
    ``get_model()`` returns the current ``(version, bundle_bytes)`` for
    handshakes.
    ``on_register(agent_id)`` records an agent (multi-actor registry,
    ref: training_server_wrapper.rs:159-163).
    """

    def __init__(self):
        self.on_trajectory: Callable[[str, bytes], None] = lambda *_: None
        self.get_model: Callable[[], tuple[int, bytes]] = lambda: (0, b"")
        self.on_register: Callable[[str], None] = lambda *_: None
        # Elastic fleets: fired when a registered agent's connection dies
        # (native transport's crash/idle detection; other backends may
        # never call it).
        self.on_unregister: Callable[[str], None] = lambda *_: None
        # Optional fast path: transports whose native core decodes
        # trajectories into columnar form (native batch drain) deliver
        # DecodedTrajectory objects here when the embedder sets it; raw
        # payload bytes always fall back to ``on_trajectory``.
        self.on_trajectory_decoded = None

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def stop(self) -> None: ...

    @abc.abstractmethod
    def publish_model(self, version: int, bundle_bytes: bytes) -> None:
        """Broadcast a fresh model to every connected agent."""


class AgentTransport(abc.ABC):
    """Agent-side: handshake, trajectory send, model-update subscription."""

    def __init__(self):
        self.on_model: Callable[[int, bytes], None] = lambda *_: None

    @abc.abstractmethod
    def fetch_model(self, timeout_s: float = 60.0) -> tuple[int, bytes]:
        """Blocking initial handshake: returns (version, bundle bytes)
        (ref: initial_model_handshake, agent_zmq.rs:316-442)."""

    @abc.abstractmethod
    def register(self, agent_id: str, timeout_s: float = 10.0) -> bool:
        """MODEL_SET/ID_LOGGED registration. May be called multiple times
        with distinct ids: each registers one logical agent on this
        connection (vector actor hosts multiplex N lanes over one socket).
        """

    @abc.abstractmethod
    def send_trajectory(self, payload: bytes,
                        agent_id: str | None = None) -> None:
        """Ship one serialized trajectory. ``agent_id`` stamps the wire
        envelope (defaults to the connection identity) — vector hosts pass
        the owning logical lane's id so server-side attribution is
        per-logical-agent, not per-socket."""

    @abc.abstractmethod
    def start_model_listener(self) -> None:
        """Begin delivering model updates to ``on_model`` asynchronously."""

    @abc.abstractmethod
    def close(self) -> None: ...
