"""Wire-protocol probing: identify what a live server endpoint speaks.

The three transports are mutually unintelligible on the wire — ZMTP
framing (zmq), HTTP/2 (grpc), and the native length-prefixed frames — so
a fleet whose two ends resolve different ``server_type`` values used to
fail only as a remote handshake timeout with no breadcrumb (the round-2
``auto`` footgun: it resolved PER PROCESS from local .so availability).

``probe_endpoint`` classifies a TCP endpoint by what the protocols
volunteer or answer:

* **zmq** — libzmq sends its 10-byte ZMTP greeting (``FF …signature… 7F``)
  immediately on accept, before the client says anything. The probe
  listens PASSIVELY first: sending non-ZMTP bytes to a libzmq socket is
  a protocol error that makes it throttle greetings to subsequent raw
  connections (observed empirically), which would poison later probes.
* **native** — the C++ core answers a Ping frame with a Pong frame
  (native/transport.cc kFramePing/kFramePong); it never speaks first, so
  the Ping goes out only after the passive window stays silent.
* **grpc** — an HTTP/2 server answers the client connection preface +
  empty SETTINGS with its own SETTINGS frame (RFC 7540 §3.5); it drops
  the ping bytes silently, so this takes a second connection.

A ZMTP greeting or native Pong is honored at ANY stage (slow servers may
answer late, even into the gRPC pass). ``make_agent_transport`` uses
this to negotiate ``auto`` against the live server and to fail fast on
explicit mismatches instead of timing out (VERDICT round-2 weak #3).
"""

from __future__ import annotations

import socket
import struct
import time

# native frame layout (native/transport.cc): u32 len | u8 type
_NATIVE_PING = struct.pack("<IB", 0, 8)
_NATIVE_PONG = struct.pack("<IB", 0, 9)
# RFC 7540 §3.5 client preface, followed by an empty SETTINGS frame.
_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" + b"\x00\x00\x00\x04\x00\x00\x00\x00\x00"
_H2_SETTINGS_TYPE = 0x04


class ProtocolMismatchError(RuntimeError):
    """Raised when a probed server speaks a different transport protocol
    than the one this process was configured with."""


def _connect(host: str, port: int, timeout_s: float) -> socket.socket | None:
    try:
        return socket.create_connection((host, port), timeout=timeout_s)
    except OSError:
        return None


def _classify_frame(buf: bytes) -> str | None:
    if len(buf) >= 10 and buf[0] == 0xFF and buf[9] == 0x7F:
        return "zmq"
    if buf.startswith(_NATIVE_PONG):
        return "native"
    if len(buf) >= 9 and buf[3:4] == bytes([_H2_SETTINGS_TYPE]):
        return "grpc"
    return None


def probe_endpoint(host: str, port: int, timeout_s: float = 1.0) -> str:
    """Classify the protocol spoken at ``host:port``.

    Returns one of ``"zmq" | "native" | "grpc" | "unknown" | "unreachable"``.
    ``unknown`` (something answered, but not one of ours) and
    ``unreachable`` (nothing listening) are deliberately non-committal —
    callers must not hard-fail on them, since a server may simply not be
    up yet.
    """
    deadline = time.monotonic() + timeout_s
    # Pass 1: passive listen (zmq speaks first), then a native Ping on the
    # same connection if the server stayed silent.
    sock = _connect(host, port, timeout_s)
    if sock is None:
        return "unreachable"
    try:
        buf = b""
        pinged = False
        # Scale the passive window with the caller's budget: pinging a
        # loaded zmq server that just hasn't greeted yet makes libzmq
        # throttle greetings to later raw connections (see module header),
        # so spend up to 60% of the timeout (capped 0.5s) listening first.
        passive_until = time.monotonic() + min(0.5, timeout_s * 0.6)
        while time.monotonic() < deadline:
            verdict = _classify_frame(buf)
            if verdict:
                return verdict
            if not pinged and not buf and time.monotonic() >= passive_until:
                # Silent server: not zmq. Ask the native core for a Pong.
                try:
                    sock.sendall(_NATIVE_PING)
                except OSError:
                    break
                pinged = True
            sock.settimeout(0.05)
            try:
                chunk = sock.recv(4096)
            except socket.timeout:
                continue
            except (ConnectionError, OSError):
                break
            if not chunk:
                break  # peer closed on us (h2 rejecting ping bytes, etc.)
            buf += chunk
        verdict = _classify_frame(buf)
        if verdict:
            return verdict
        if not pinged:
            return "unknown"  # endpoint spoke, but nothing we recognize
    finally:
        sock.close()
    # Pass 2: fresh connection for the HTTP/2 preface (an h2 server drops
    # the ping-bytes connection above without answering).
    sock = _connect(host, port, max(0.1, deadline - time.monotonic()))
    if sock is None:
        return "unreachable"
    try:
        try:
            sock.sendall(_H2_PREFACE)
        except OSError:
            return "unknown"
        buf = b""
        h2_deadline = max(time.monotonic() + 0.2, deadline)
        while time.monotonic() < h2_deadline:
            verdict = _classify_frame(buf)
            if verdict:
                return verdict
            sock.settimeout(max(0.05, h2_deadline - time.monotonic()))
            try:
                chunk = sock.recv(4096)
            except (socket.timeout, ConnectionError, OSError):
                break
            if not chunk:
                break
            buf += chunk
        return _classify_frame(buf) or "unknown"
    finally:
        sock.close()


def parse_host_port(addr: str) -> tuple[str, int]:
    """``tcp://h:p`` / ``h:p`` -> (h, p)."""
    addr = addr.split("//")[-1]
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)
