"""ZeroMQ transport backend.

Capability parity with the reference's ZMQ plane
(reference: relayrl_framework/src/network/server/training_zmq.rs — ROUTER
agent-listener at :669-864, PULL trajectory ingest at :948-1058, model push
at :876-934; client side src/network/client/agent_zmq.rs — DEALER handshake
at :316-442, PUSH trajectory via types/trajectory.rs:69-90, model listener
thread at :625-698).

Deliberate redesigns (documented, SURVEY.md §7.5):

* **PUB/SUB model broadcast.** The reference has the *agent* bind a PULL
  socket and the server connect per update (agent_zmq.rs:632-638 /
  training_zmq.rs:921-927) — one bind address means >1 agent cannot receive
  models. Server-side PUB with agent-side SUB is the topology that actually
  broadcasts; it's why the north-star "64 ZMQ actors" config is reachable.
* **Blocking polls, not 50 ms sleep loops.** All reference loops poll
  non-blocking sockets every 50 ms (training_zmq.rs:860,1053), a latency
  floor and a busy-wait; here every loop blocks in ``zmq.Poller`` with a
  shutdown-check timeout.
* **Persistent PUSH socket.** The reference opens a fresh PUSH connection per
  trajectory send (trajectory.rs:69-90); here one connected socket per agent.
"""

from __future__ import annotations

import threading
import time

import zmq

from relayrl_tpu.transport.base import (
    AgentTransport,
    CMD_GET_MODEL,
    CMD_MODEL_SET,
    CMD_RESYNC,
    MODEL_TOPIC,
    REPLY_ERROR,
    REPLY_ID_LOGGED,
    REPLY_MODEL,
    ReceiptLedger,
    ServerTransport,
    agent_wire_metrics,
    pack_model_frame,
    register_subscriber_gauge,
    server_wire_metrics,
    swallow_decode_error,
    unpack_model_frame,
    unpack_model_frame_ex,
    unpack_trajectory_envelope,
)
from relayrl_tpu.transport.retry import RetryPolicy

_POLL_MS = 100  # shutdown-check cadence for otherwise-blocking polls


def _bind_with_retry(sock: zmq.Socket, addr: str, timeout_s: float = 3.0) -> None:
    """Bind, tolerating the brief window where a just-closed socket's port is
    still being released (restart_server re-binds the same addresses)."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            sock.bind(addr)
            return
        except zmq.ZMQError as e:
            if e.errno != zmq.EADDRINUSE or time.monotonic() >= deadline:
                raise
            time.sleep(0.05)


class ZmqServerTransport(ServerTransport):
    """ROUTER handshake + PULL trajectory ingest + PUB model broadcast."""

    def __init__(self, agent_listener_addr: str, trajectory_addr: str,
                 model_pub_addr: str, chunk_bytes: int = 0):
        super().__init__()
        self._addrs = (agent_listener_addr, trajectory_addr, model_pub_addr)
        self._ctx: zmq.Context | None = None
        self._pub: zmq.Socket | None = None
        self._pub_lock = threading.Lock()
        # transport.chunk_bytes: broadcast frames above this size are
        # split into ordered chunk frames (modelwire.split_frame) so the
        # PUB socket's HWM accounting sees bounded messages; 0 = off.
        self._chunk_bytes = max(0, int(chunk_bytes))
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._m = server_wire_metrics("zmq")
        # Live subscriber (stream) count for the PUB plane, maintained
        # from the socket monitor's ACCEPTED/DISCONNECTED events and
        # read lazily by the relayrl_transport_subscribers pull-gauge —
        # libzmq has no direct peer-count API, but the bind-side monitor
        # sees every SUB connect/drop.
        self._pub_monitor: zmq.Socket | None = None
        self._sub_count = 0
        self._sub_count_lock = threading.Lock()

    def start(self) -> None:
        self._stop.clear()
        self._ctx = zmq.Context.instance()
        listener_addr, traj_addr, pub_addr = self._addrs
        self._pub = self._ctx.socket(zmq.PUB)
        try:
            self._pub_monitor = self._pub.get_monitor_socket(
                zmq.EVENT_ACCEPTED | zmq.EVENT_DISCONNECTED)
        except (zmq.ZMQError, AttributeError):
            self._pub_monitor = None  # monitor unsupported: gauge stays 0
        _bind_with_retry(self._pub, pub_addr)
        register_subscriber_gauge("zmq", self._subscriber_count,
                                  bind=pub_addr)
        self._threads = [
            threading.Thread(target=self._listener_loop, args=(listener_addr,),
                             name="zmq-agent-listener", daemon=True),
            threading.Thread(target=self._trajectory_loop, args=(traj_addr,),
                             name="zmq-trajectory-ingest", daemon=True),
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        self._threads.clear()
        with self._sub_count_lock:  # vs a concurrent gauge read
            if self._pub_monitor is not None:
                try:
                    self._pub_monitor.close(linger=0)
                except zmq.ZMQError:
                    pass
                self._pub_monitor = None
            # The socket (and every peer) dies with this stop; without
            # the reset a restart_server cycle would stack the old count
            # under the reconnecting peers' fresh ACCEPTED events.
            self._sub_count = 0
        if self._pub is not None:
            self._pub.close(linger=0)
            self._pub = None

    def _subscriber_count(self) -> int:
        """Pull-gauge read: drain queued PUB monitor events, return the
        live peer count. Runs on the snapshot/export thread only; the
        lock covers a concurrent stop() closing the monitor."""
        with self._sub_count_lock:
            mon = self._pub_monitor
            if mon is None:
                return self._sub_count
            try:
                from zmq.utils.monitor import recv_monitor_message

                while mon.poll(0):
                    evt = recv_monitor_message(mon)["event"]
                    if evt == zmq.EVENT_ACCEPTED:
                        self._sub_count += 1
                    elif evt == zmq.EVENT_DISCONNECTED:
                        self._sub_count = max(0, self._sub_count - 1)
            except (zmq.ZMQError, KeyError, OSError):
                pass  # monitor died mid-read: report the last known count
            return self._sub_count

    def publish_model(self, version: int, bundle_bytes: bytes) -> None:
        if self._pub is None:
            raise RuntimeError("transport not started")
        from relayrl_tpu.transport.modelwire import split_frame

        # The publisher's monotonic stamp rides the frame so every SUB
        # thread on this host can compute publish→receipt latency
        # locally (the telemetry answer to the soak bench's fan-out
        # methodology; cross-host stamps don't pair and are ignored).
        # A model blob over chunk_bytes ships as ordered chunk frames
        # under ONE lock hold, so no other publish can interleave; the
        # agent-side ChunkReassembler restores the original frame.
        parts = split_frame(bundle_bytes, self._chunk_bytes, version)
        sent = 0
        with self._pub_lock:
            for part in parts:
                frame = pack_model_frame(version, part,
                                         pub_ns=time.monotonic_ns())
                self._pub.send_multipart([MODEL_TOPIC, frame])
                sent += len(frame)
        self._m["publish_total"].inc()
        self._m["publish_bytes"].inc(sent)

    # -- loops --
    def _listener_loop(self, addr: str) -> None:
        """ROUTER: GET_MODEL → model reply; MODEL_SET → register + ID_LOGGED
        (ref: _listen_for_agents, training_zmq.rs:669-864 — minus the
        break-after-first-registration single-actor quirk at :826-829)."""
        sock = self._ctx.socket(zmq.ROUTER)
        _bind_with_retry(sock, addr)
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        try:
            while not self._stop.is_set():
                if not dict(poller.poll(_POLL_MS)):
                    continue
                frames = sock.recv_multipart()
                # ROUTER framing: [identity, (empty,) cmd, args...]
                identity, rest = frames[0], frames[1:]
                if rest and rest[0] == b"":
                    rest = rest[1:]
                if not rest:
                    continue
                cmd = rest[0]
                if cmd == CMD_GET_MODEL:
                    version, bundle = self.get_model()
                    sock.send_multipart(
                        [identity, REPLY_MODEL, pack_model_frame(version, bundle)])
                elif cmd == CMD_MODEL_SET:
                    agent_id = rest[1].decode() if len(rest) > 1 else identity.decode(
                        errors="replace")
                    self.on_register(agent_id)
                    sock.send_multipart([identity, REPLY_ID_LOGGED])
                elif cmd == CMD_RESYNC:
                    # Fire-and-forget keyframe request (no reply — the
                    # heal is the next broadcast). The optional second
                    # frame carries the requester's held version so a
                    # relay can pick cache-serve vs escalate; the
                    # training server coalesces into one rate-limited
                    # force_keyframe regardless.
                    held = -1
                    if len(rest) > 1:
                        try:
                            held = int(rest[1])
                        except ValueError:
                            pass
                    try:
                        self.on_resync(held)
                    except Exception as e:
                        print(f"[zmq] on_resync handler failed: {e!r}",
                              flush=True)
                else:
                    sock.send_multipart([identity, REPLY_ERROR, b"unknown command"])
        finally:
            sock.close(linger=0)

    def _trajectory_loop(self, addr: str) -> None:
        """PULL ingest (ref: _start_training_loop recv half,
        training_zmq.rs:948-1011)."""
        sock = self._ctx.socket(zmq.PULL)
        _bind_with_retry(sock, addr)
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        try:
            while not self._stop.is_set():
                if not dict(poller.poll(_POLL_MS)):
                    continue
                buf = sock.recv()
                self._m["recv_total"].inc()
                self._m["recv_bytes"].inc(len(buf))
                try:
                    agent_id, payload = unpack_trajectory_envelope(buf)
                except Exception as e:
                    # Malformed frame: drop WITH a trace (counter + one
                    # log line); non-data errors re-raise — see
                    # base.swallow_decode_error.
                    swallow_decode_error("zmq", "trajectory_ingest", e)
                    continue
                self.on_trajectory(agent_id, payload)
        finally:
            sock.close(linger=0)


class ZmqAgentTransport(AgentTransport):
    """DEALER handshake + PUSH trajectories + SUB model updates."""

    def __init__(self, agent_listener_addr: str, trajectory_addr: str,
                 model_sub_addr: str, identity: str | None = None,
                 retry: dict | None = None):
        super().__init__()
        import os
        import secrets

        from relayrl_tpu import faults

        self._identity = (identity or
                          f"AGENT_ID-{os.getpid()}{secrets.token_hex(4)}").encode()
        self._ctx = zmq.Context.instance()
        self._addrs = (agent_listener_addr, trajectory_addr, model_sub_addr)
        self._dealer = self._ctx.socket(zmq.DEALER)
        self._dealer.setsockopt(zmq.IDENTITY, self._identity)
        self._dealer.connect(agent_listener_addr)
        self._push = self._ctx.socket(zmq.PUSH)
        self._push.connect(trajectory_addr)
        # Reconnect detection for a broadcast-plane transport with no
        # request/response back-channel: a zmq socket monitor on the PUSH
        # pipe reports DISCONNECTED/CONNECTED transitions from libzmq's
        # own reconnect machinery — a CONNECTED after a DISCONNECTED is
        # the server-restart signal that fires on_reconnect (spool
        # replay). Polled from the model-listener thread.
        self._push_monitor: zmq.Socket | None = None
        try:
            self._push_monitor = self._push.get_monitor_socket(
                zmq.EVENT_CONNECTED | zmq.EVENT_DISCONNECTED)
        except (zmq.ZMQError, AttributeError):
            pass  # monitor unsupported: replay falls back to explicit paths
        self._push_broken = False
        self._push_lock = threading.Lock()
        self._dealer_lock = threading.Lock()
        self._sub: zmq.Socket | None = None
        self._listener: threading.Thread | None = None
        self._stop = threading.Event()
        self._m = agent_wire_metrics("zmq")
        # Unified retry policy (transport.retry config) drives the
        # handshake re-poll cadence; fault sites are None without a plan.
        self._retry = RetryPolicy.from_dict(retry)
        self._fault_send = faults.site("agent.send")
        self._fault_model = faults.site("agent.model")
        # Pre-decode receipt ledger (base.ReceiptLedger — the native C++
        # ledger's Python mirror): (version, rx_mono_ns) stamped the
        # moment recv returns, BEFORE the frame is decoded or the swap
        # runs — so fan-out accounting measures the wire, not the Python
        # decode backlog behind it (benches/README.md zmq 64-actor note).
        self._ledger = ReceiptLedger()
        # Chunked model frames (server transport.chunk_bytes) reassemble
        # here before the ledger stamp / on_model, so one publish is one
        # receipt no matter how many wire messages carried it.
        from relayrl_tpu.transport.modelwire import ChunkReassembler

        self._reasm = ChunkReassembler()

    @property
    def identity(self) -> str:
        return self._identity.decode()

    def _dealer_request(self, frames: list[bytes], timeout_s: float,
                        want: bytes):
        """Send a request and wait for a reply whose first frame is ``want``.

        Replies of other types are discarded: the handshake may re-send
        GET_MODEL on a slow server, leaving stale MODEL replies queued ahead
        of a later ID_LOGGED — request/response pairing on a DEALER is by
        reply type, not ordering.
        """
        # _dealer_lock: zmq sockets are not thread-safe, and reconnect-
        # time re-registration (Agent._on_reconnect, fired from a
        # listener thread) may race a handshake on the caller thread.
        with self._dealer_lock:
            deadline = time.monotonic() + timeout_s
            poller = zmq.Poller()
            poller.register(self._dealer, zmq.POLLIN)
            self._dealer.send_multipart(frames)
            while time.monotonic() < deadline:
                if dict(poller.poll(_POLL_MS)):
                    # deliberate blocking-under-lock: the lock EXISTS to
                    # serialize whole request/reply exchanges on the
                    # non-thread-safe DEALER; poll() above guarantees
                    # recv returns immediately, and the hold is bounded
                    # by the caller's timeout_s.
                    reply = self._dealer.recv_multipart()  # jaxlint: disable=CONC01
                    if reply and reply[0] == want:
                        return reply
            return None

    def fetch_model(self, timeout_s: float = 60.0) -> tuple[int, bytes]:
        """Retrying GET_MODEL handshake under the unified RetryPolicy
        (ref: agent_zmq.rs:316-442 retries every 1 s forever; previously
        a hand-rolled fixed-2s re-poll dialect here — now the one
        jittered-backoff policy all three backends share)."""
        deadline = time.monotonic() + timeout_s

        def attempt():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            reply = self._dealer_request([CMD_GET_MODEL],
                                         min(remaining, 2.0),
                                         want=REPLY_MODEL)
            if reply and len(reply) > 1:
                return unpack_model_frame(reply[1])
            return None

        try:
            return self._retry.call(attempt, op="zmq.handshake",
                                    deadline_s=timeout_s)
        except TimeoutError:
            raise TimeoutError(
                f"model handshake timed out after {timeout_s}s "
                f"(server at {self._addrs[0]} unreachable?)") from None

    def register(self, agent_id: str | None = None, timeout_s: float = 10.0) -> bool:
        reply = self._dealer_request(
            [CMD_MODEL_SET, (agent_id or self.identity).encode()], timeout_s,
            want=REPLY_ID_LOGGED)
        return reply is not None

    def send_trajectory(self, payload: bytes,
                        agent_id: str | None = None) -> None:
        from relayrl_tpu.transport.base import pack_trajectory_envelope

        env = pack_trajectory_envelope(agent_id or self.identity, payload)
        if self._fault_send is not None:
            if self._fault_send.take_kill_connection():
                self._kill_push()
            parts = self._fault_send.inject(env)
        else:
            parts = ((0.0, env),)
        t0 = time.monotonic()
        for delay_s, part in parts:
            if delay_s > 0:
                time.sleep(delay_s)  # before the lock: a chaos delay
                #                      must not serialize sibling senders
            with self._push_lock:
                self._push.send(part)
            self._m["send_total"].inc()
            self._m["send_bytes"].inc(len(part))
        self._m["send_seconds"].observe(time.monotonic() - t0)

    def _kill_push(self) -> None:
        """Fault-plane connection kill: tear down the PUSH socket the way
        a TCP RST would (queued frames lost) and reconnect fresh — the
        recovery the spool's replay-on-reconnect covers."""
        with self._push_lock:
            if self._push_monitor is not None:
                try:
                    self._push_monitor.close(linger=0)
                except zmq.ZMQError:
                    pass
            self._push_monitor = None
            self._push.close(linger=0)
            self._push = self._ctx.socket(zmq.PUSH)
            # zmq connect is asynchronous (returns before any TCP
            # handshake) — not a blocking call, and the swap must be
            # atomic against concurrent senders holding this lock.
            self._push.connect(self._addrs[1])  # jaxlint: disable=CONC01
            try:
                self._push_monitor = self._push.get_monitor_socket(
                    zmq.EVENT_CONNECTED | zmq.EVENT_DISCONNECTED)
            except (zmq.ZMQError, AttributeError):
                pass

    def start_model_listener(self) -> None:
        if self._listener is not None:
            return
        self._sub = self._ctx.socket(zmq.SUB)
        self._sub.connect(self._addrs[2])
        self._sub.setsockopt(zmq.SUBSCRIBE, MODEL_TOPIC)
        self._stop.clear()
        self._listener = threading.Thread(
            target=self._model_loop, name="zmq-model-listener", daemon=True)
        self._listener.start()

    def _model_loop(self) -> None:
        """SUB loop → on_model (ref: OS-thread PULL listener,
        agent_zmq.rs:625-698).

        The receipt stamp is taken the moment ``recv`` returns — before
        decode, before the (lock-contended) swap in ``on_model`` — and
        appended to the ledger right after the version is known. The
        decode/swap cost is measured separately
        (``model_deliver_seconds``): under fleet fan-out rates that cost
        is what backs this thread up, and stamping after it (the old
        behavior) conflated wire delivery with Python scheduling."""
        poller = zmq.Poller()
        poller.register(self._sub, zmq.POLLIN)
        while not self._stop.is_set():
            self._drain_monitor()
            if not dict(poller.poll(_POLL_MS)):
                continue
            frames = self._sub.recv_multipart()
            rx_ns = time.monotonic_ns()  # pre-decode receipt stamp
            if len(frames) != 2 or frames[0] != MODEL_TOPIC:
                continue
            raw_frames = [frames[1]]
            if self._fault_model is not None:
                # chaos plane: drop/delay/corrupt/duplicate the model
                # frame between the wire and the decode — a corrupted
                # frame must die in the CRC/decode guards below, a
                # dropped one waits out the keyframe cadence.
                raw_frames = []
                for delay_s, part in self._fault_model.inject(frames[1]):
                    if delay_s > 0:
                        time.sleep(delay_s)
                    raw_frames.append(part)
            for raw in raw_frames:
                self._deliver_model_frame(raw, rx_ns)

    def _deliver_model_frame(self, raw: bytes, rx_ns: int) -> None:
        try:
            version, bundle, pub_ns = unpack_model_frame_ex(raw)
        except Exception as e:
            swallow_decode_error("zmq", "model_listener", e)
            return
        self._m["model_recv_bytes"].inc(len(raw))
        bundle = self._reasm.feed(bundle)
        if bundle is None:
            return  # mid-chunk: the receipt stamps on the last part
        self._ledger.append(version, rx_ns)
        self._m["model_recv_total"].inc()
        if pub_ns is not None and 0 <= rx_ns - pub_ns < int(300e9):
            # Same-host monotonic pair only. CLOCK_MONOTONIC is
            # per-boot, so a cross-host pair is off by the uptime
            # difference in EITHER direction — the negative half is
            # obvious, but the positive half would pin every sample
            # in the +Inf bucket. Anything beyond 300s cannot be a
            # real fan-out latency on this plane; treat it as skew
            # and drop the sample.
            self._m["receipt_latency_seconds"].observe(
                (rx_ns - pub_ns) / 1e9)
        self.on_model(version, bundle)
        self._m["model_deliver_seconds"].observe(
            (time.monotonic_ns() - rx_ns) / 1e9)
        # Downstream trace: the receipt hop (receipt stamp → swap
        # applied) + the actor-side model-age observation off the
        # publisher's monotonic stamp (same skew guard as above).
        from relayrl_tpu.telemetry.trace import record_model_receipt

        record_model_receipt(version, rx_ns, pub_ns, "zmq")

    def _drain_monitor(self) -> None:
        """Process queued PUSH-socket monitor events (model-listener
        thread): a CONNECTED following a DISCONNECTED is a healed
        trajectory pipe — the replay-on-reconnect trigger for this
        backend, which otherwise has no failure signal at all (PUSH
        sends never error; libzmq re-queues silently)."""
        mon = self._push_monitor
        if mon is None:
            return
        try:
            from zmq.utils.monitor import recv_monitor_message

            while mon.poll(0):
                evt = recv_monitor_message(mon)["event"]
                if evt == zmq.EVENT_DISCONNECTED:
                    self._push_broken = True
                elif evt == zmq.EVENT_CONNECTED and self._push_broken:
                    self._push_broken = False
                    self._notify_reconnect()
        except (zmq.ZMQError, KeyError, OSError):
            pass  # monitor died (socket rebuilt): detection degrades

    def drain_receipts(self, max_n: int = 65536) -> list[tuple[int, int]]:
        """Drain the pre-decode receipt ledger: ``[(version,
        rx_mono_ns), ...]`` — same surface and semantics as the native
        C++ ledger (``rl_sub_receipts``), so soak fan-out accounting is
        backend-uniform."""
        return self._ledger.drain(max_n)

    # Resync-request floor: a decoder stuck awaiting a keyframe raises
    # WireBaseMismatch once, but repeated divergences (chaos drills,
    # relay failover) must not turn into a request storm on the ROUTER.
    _RESYNC_MIN_INTERVAL_S = 1.0
    _last_resync_req = 0.0

    def request_resync(self, held_version: int = -1) -> None:
        """Broadcast-plane resync (ISSUE 11 satellite): one CMD_RESYNC
        on the DEALER asks the publisher to make its next publish a
        keyframe (root: coalesced force_keyframe; relay: cached-keyframe
        serve or upstream escalation, decided on ``held_version``) — the
        blackout bound drops from ``<= keyframe_interval`` publishes to
        <= 1. Fire-and-forget and client-side rate-limited; runs on the
        model-listener thread, so the dealer lock hold is a single
        send."""
        now = time.monotonic()
        if now - self._last_resync_req < self._RESYNC_MIN_INTERVAL_S:
            return
        self._last_resync_req = now
        try:
            with self._dealer_lock:
                self._dealer.send_multipart(
                    [CMD_RESYNC, str(int(held_version)).encode()],
                    zmq.DONTWAIT)
        except zmq.ZMQError:
            pass  # full pipe / closing socket: the keyframe cadence heals

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.join(timeout=5)
            self._listener = None
        for sock in (self._dealer, self._push, self._sub,
                     self._push_monitor):
            if sock is not None:
                sock.close(linger=0)
        self._sub = None
        self._push_monitor = None
