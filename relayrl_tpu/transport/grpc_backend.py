"""gRPC transport backend.

Capability parity with the reference's tonic service
(reference: relayrl_framework/proto/relayrl_grpc.proto:33-36 — service
``RelayRLRoute { SendActions, ClientPoll }``; server impl
src/network/server/training_grpc.rs:565-798; client
src/network/client/agent_grpc.rs). The two-RPC surface is kept:

* ``SendActions``  — trajectory envelope in, ack out (train is async,
  matching training_grpc.rs:637-641's immediate reply).
* ``ClientPoll``   — ``{agent_id, version, first_time}`` in; blocks until a
  model newer than ``version`` exists or the idle timeout lapses, then
  returns the bundle (long-poll replacing the reference's watch channel,
  training_grpc.rs:731-796 — with the timeout honored in *seconds*, fixing
  the seconds-as-millis bug at :757).

Implementation note: handlers are registered dynamically via
``grpc.method_handlers_generic_handler`` with msgpack bodies — the wire
contract is this module, not a compiled proto, so the native C++ backend and
any future proto can interoperate by speaking the same envelopes.

Departure: the reference agent calls ``process::exit(1)`` on a failed
trajectory send (agent_grpc.rs:529-531); here send errors raise to the
caller.
"""

from __future__ import annotations

import threading
import time
from concurrent import futures

import grpc
import msgpack

from relayrl_tpu.transport.base import (
    NACK_OVERLOADED,
    NACK_QUARANTINED,
    AgentTransport,
    IngestNack,
    ReceiptLedger,
    ServerTransport,
    agent_wire_metrics,
    server_wire_metrics,
    swallow_decode_error,
    unpack_trajectory_envelope,
)
from relayrl_tpu.transport.retry import RetryPolicy

_SERVICE = "relayrl.RelayRLRoute"


def _identity(x: bytes) -> bytes:
    return x


class _Servicer:
    def __init__(self, owner: "GrpcServerTransport"):
        self._owner = owner

    def send_actions(self, request: bytes, context) -> bytes:
        self._owner._m["recv_total"].inc()
        self._owner._m["recv_bytes"].inc(len(request))
        try:
            agent_id, payload = unpack_trajectory_envelope(request)
        except Exception as e:
            # data-shaped decode errors drop with a counter; programming
            # errors re-raise (grpc surfaces them to the caller as an
            # RPC error instead of a silent code-0 ack).
            swallow_decode_error("grpc", "trajectory_ingest", e)
            return msgpack.packb({"code": 0, "error": "malformed envelope"})
        verdict = None
        if self._owner.check_ingest is not None:
            # Guardrail admission (quarantine / overload-nack): this
            # plane HAS a back-channel, so a refused send is a typed
            # nack the sender's spool can act on instead of a silent
            # server-side shed (transport/base.py NACK_* codes).
            verdict = self._owner.check_ingest(agent_id)
        if verdict is not None:
            code, reason, retry_after = verdict
            return msgpack.packb({"code": int(code), "error": str(reason),
                                  "retry_after_s": float(retry_after)})
        self._owner.on_trajectory(agent_id, payload)
        return msgpack.packb({"code": 1})

    def _model_update(self, known_version: int) -> tuple[int, bytes]:
        """The freshest blob a subscriber holding ``known_version`` can
        decode: the model-wire v2 delta/keyframe frame when the embedder
        installed ``get_model_update`` (the delta-vs-full choice is
        per-subscriber on this pull plane), else the full bundle."""
        fn = self._owner.get_model_update
        if fn is not None:
            return fn(known_version)
        return self._owner.get_model()

    def _model_version(self) -> int:
        """Version probe for long-poll wakeups — must not force a full
        bundle serialize (wire-v2 servers serialize v1 bytes lazily)."""
        fn = self._owner.get_model_version
        if fn is not None:
            return int(fn())
        return self._owner.get_model()[0]

    def get_actions(self, request: bytes, context) -> bytes:
        """Serving-plane RPC (disaggregated batched inference): hand the
        observation request to the embedder's InferenceService and block
        this RPC thread until its batch executes. Without a service
        installed the reply is a pointed error, not a hang.

        Parked inference RPCs share the worker pool with SendActions and
        the ClientPoll long-polls, so their CONCURRENCY is capped at half
        the pool (``_infer_slots``): beyond it, arrivals get an immediate
        typed overload nack — an inference flood must degrade to client
        backoff, never to fleet-wide ingest starvation."""
        from relayrl_tpu.transport.base import (
            NACK_OVERLOADED,
            NACK_UNAVAILABLE,
        )
        from relayrl_tpu.transport.serving import pack_infer_nack

        if self._owner.on_infer is None:
            return pack_infer_nack(
                -1, NACK_UNAVAILABLE,
                "inference serving is not enabled on this server "
                "(set serving.enabled: true)")
        if not self._owner._infer_slots.acquire(blocking=False):
            return pack_infer_nack(
                -1, NACK_OVERLOADED,
                "inference RPC slots exhausted (serving shares the RPC "
                "pool with ingest)", 0.05)
        try:
            return self._owner.on_infer(request)
        finally:
            self._owner._infer_slots.release()

    def stream_actions(self, request_iterator, context):
        """Bidi serving stream (serving v2): every inbound frame is a
        pipelined inference request handed to the embedder's
        non-blocking submit hook; replies flow back on THIS stream in
        whatever order their batches execute (req-id matched client
        side). One stream parks ONE RPC thread regardless of its
        in-flight depth — the pipelining reason to prefer it over N
        parked GetActions unary calls — so it is not gated by the
        ``_infer_slots`` semaphore; the InferenceService's own
        ``queue_limit`` overload nacks are the backpressure."""
        import queue as queue_mod

        from relayrl_tpu.transport.base import NACK_UNAVAILABLE
        from relayrl_tpu.transport.serving import pack_infer_nack

        submit = self._owner.on_infer_submit
        if submit is None:
            yield pack_infer_nack(
                -1, NACK_UNAVAILABLE,
                "inference serving is not enabled on this server "
                "(set serving.enabled: true)")
            return
        out: "queue_mod.Queue[bytes | None]" = queue_mod.Queue()
        state = {"inflight": 0, "drained": False}
        lock = threading.Lock()

        def reply(b: bytes) -> None:
            # Runs on batch-worker (or pump) threads: deliver, then
            # close the stream once the client half-closed AND the last
            # in-flight reply is out.
            with lock:
                state["inflight"] -= 1
                last = state["drained"] and state["inflight"] == 0
            out.put(b)
            if last:
                out.put(None)

        def pump() -> None:
            try:
                for payload in request_iterator:
                    with lock:
                        state["inflight"] += 1
                    submit(payload, reply)
            except Exception:
                pass  # cancelled/broken stream: drain and fall through
            finally:
                with lock:
                    state["drained"] = True
                    empty = state["inflight"] == 0
                if empty:
                    out.put(None)

        threading.Thread(target=pump, name="grpc-serving-stream-pump",
                         daemon=True).start()
        while True:
            item = out.get()
            if item is None:
                return
            yield item

    def client_poll(self, request: bytes, context) -> bytes:
        req = msgpack.unpackb(request, raw=False)
        agent_id = str(req.get("id", "?"))
        known_version = int(req.get("ver", -1))
        first_time = bool(req.get("first", False))
        self._owner._note_subscriber(agent_id)
        if first_time:
            self._owner.on_register(agent_id)
        # Version probe only on entry: get_model() would force the
        # wire-v2 server's LAZY v1 serialize for every published version
        # (under its bundle lock, on an RPC thread) even when the reply
        # ships a delta frame — the bundle is fetched only on the
        # branches that actually send it.
        version = self._model_version()
        if first_time and version <= known_version:
            # Logical-lane registration (vector hosts): the registrant
            # already holds the current model, so the ack is
            # metadata-sized instead of shipping the full bundle once
            # per lane. Genuine handshakes send ver=-1 and still get
            # the bundle below.
            return msgpack.packb({"code": 1, "ver": version},
                                 use_bin_type=True)
        if first_time or known_version < 0:
            # Handshakes and explicit resyncs (re-poll with ver=-1) get
            # the full bundle unconditionally.
            version, bundle = self._owner.get_model()
            return msgpack.packb({"code": 1, "ver": version, "model": bundle},
                                 use_bin_type=True)
        if version > known_version:
            version, blob = self._model_update(known_version)
            return msgpack.packb({"code": 1, "ver": version, "model": blob},
                                 use_bin_type=True)
        # long poll: wait for a newer model or timeout
        deadline = time.monotonic() + self._owner.idle_timeout_s
        with self._owner._model_cv:
            while True:
                version = self._model_version()
                if version > known_version:
                    version, blob = self._model_update(known_version)
                    return msgpack.packb(
                        {"code": 1, "ver": version, "model": blob},
                        use_bin_type=True)
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not context.is_active():
                    return msgpack.packb({"code": 0, "ver": version})
                self._owner._model_cv.wait(timeout=min(remaining, 1.0))


class GrpcServerTransport(ServerTransport):
    #: GetActions rides this server in-band (see base.ServerTransport);
    #: every thin client parks one RPC thread per in-flight request, so
    #: max_workers bounds the serving fleet alongside the long-polls.
    supports_inband_infer = True

    def __init__(self, bind_addr: str, idle_timeout_s: float = 30.0,
                 max_workers: int = 128):
        # max_workers bounds concurrent RPCs, and every subscribed agent
        # parks one long-poll (ClientPoll) thread on the server: the pool
        # must exceed the fleet size or late joiners' handshakes starve
        # behind parked polls (observed at 64 actors with the old 16).
        # The reference's tonic server is async and has no such limit —
        # this is the sync-grpcio translation of that property.
        super().__init__()
        self._bind_addr = bind_addr
        self.idle_timeout_s = float(idle_timeout_s)
        self._max_workers = max_workers
        self._server: grpc.Server | None = None
        self._model_cv = threading.Condition()
        # In-band serving concurrency bound: at most half the RPC pool
        # may park in GetActions waits, so trajectory ingest and the
        # long-polls always keep worker headroom (see get_actions).
        self._infer_slots = threading.Semaphore(max(8, max_workers // 2))
        # publish here is a long-poll wakeup, not a broadcast: there are
        # no broadcast bytes to count.
        self._m = server_wire_metrics("grpc", include_publish_bytes=False)
        # Subscriber table for the relayrl_transport_subscribers
        # pull-gauge: on this pull plane a "stream" is a poll loop, so
        # count distinct poller ids seen within the last poll window
        # (idle timeout + grace). One-shot lane registrations age out.
        self._poll_table: dict[str, float] = {}
        self._poll_table_lock = threading.Lock()

    def _note_subscriber(self, agent_id: str) -> None:
        with self._poll_table_lock:
            self._poll_table[agent_id] = time.monotonic()
            if len(self._poll_table) > 65536:  # runaway-id guard
                self._prune_poll_table_locked()

    def _prune_poll_table_locked(self) -> None:
        horizon = time.monotonic() - (self.idle_timeout_s + 15.0)
        for aid in [a for a, t in self._poll_table.items() if t < horizon]:
            del self._poll_table[aid]

    def _subscriber_count(self) -> int:
        with self._poll_table_lock:
            self._prune_poll_table_locked()
            return len(self._poll_table)

    def start(self) -> None:
        from relayrl_tpu.transport.base import register_subscriber_gauge

        register_subscriber_gauge("grpc", self._subscriber_count,
                                  bind=self._bind_addr)
        servicer = _Servicer(self)
        handlers = {
            "SendActions": grpc.unary_unary_rpc_method_handler(
                servicer.send_actions,
                request_deserializer=_identity, response_serializer=_identity),
            "ClientPoll": grpc.unary_unary_rpc_method_handler(
                servicer.client_poll,
                request_deserializer=_identity, response_serializer=_identity),
            "GetActions": grpc.unary_unary_rpc_method_handler(
                servicer.get_actions,
                request_deserializer=_identity, response_serializer=_identity),
            "StreamActions": grpc.stream_stream_rpc_method_handler(
                servicer.stream_actions,
                request_deserializer=_identity, response_serializer=_identity),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=self._max_workers),
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024)],
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, handlers),))
        self._server.add_insecure_port(self._bind_addr)
        self._server.start()

    def stop(self) -> None:
        if self._server is not None:
            with self._model_cv:
                self._model_cv.notify_all()
            self._server.stop(grace=1).wait()
            self._server = None

    def publish_model(self, version: int, bundle_bytes: bytes) -> None:
        # Models are pulled via ClientPoll long-polls; publishing just wakes
        # the waiters (ref: watch channel notify, training_grpc.rs:600-627).
        self._m["publish_total"].inc()
        with self._model_cv:
            self._model_cv.notify_all()


class GrpcAgentTransport(AgentTransport):
    def __init__(self, server_addr: str, identity: str | None = None,
                 poll_timeout_s: float = 35.0, retry: dict | None = None):
        super().__init__()
        import os
        import secrets

        from relayrl_tpu import faults

        self._retry = RetryPolicy.from_dict(retry)
        self._fault_send = faults.site("agent.send")
        self._fault_model = faults.site("agent.model")
        self.identity = identity or f"AGENT_ID-{os.getpid()}{secrets.token_hex(4)}"
        self._addr = server_addr
        self._poll_timeout_s = poll_timeout_s
        self._channel_lock = threading.Lock()
        self._make_channel()
        self._known_version = -1
        self._inflight = None
        self._stop = threading.Event()
        self._listener: threading.Thread | None = None
        self._m = agent_wire_metrics("grpc")
        # Reconnect accounting matches the native backend's semantics:
        # count a HEAL (first successful poll after a break), not every
        # failed retry — a 60s server restart is ONE reconnect, not 60.
        self._poll_broken = False
        self._poll_fail_streak = 0
        # Pre-decode receipt ledger (base.ReceiptLedger), same surface
        # as the native C++ and zmq ledgers — soak fan-out accounting is
        # backend-uniform.
        self._ledger = ReceiptLedger()

    def _make_channel(self) -> None:
        """(Re)build the channel + stubs. Reconnect backoff is bounded by
        the SAME retry policy that drives the handshake: grpc's default
        channel backoff grows to ~2 minutes between dial attempts, so a
        learner restart could sit unreachable for the whole recovery
        window (observed in the SIGKILL drill)."""
        backoff_min_ms = max(50, int(self._retry.base_delay_s * 1000))
        backoff_max_ms = max(backoff_min_ms,
                             int(self._retry.max_delay_s * 1000))
        self._channel = grpc.insecure_channel(
            self._addr,
            options=[("grpc.max_receive_message_length", 256 * 1024 * 1024),
                     ("grpc.max_send_message_length", 256 * 1024 * 1024),
                     ("grpc.initial_reconnect_backoff_ms", backoff_min_ms),
                     ("grpc.min_reconnect_backoff_ms", backoff_min_ms),
                     ("grpc.max_reconnect_backoff_ms", backoff_max_ms)],
        )
        self._send = self._channel.unary_unary(
            f"/{_SERVICE}/SendActions",
            request_serializer=_identity, response_deserializer=_identity)
        self._poll = self._channel.unary_unary(
            f"/{_SERVICE}/ClientPoll",
            request_serializer=_identity, response_deserializer=_identity)

    def _rebuild_channel(self) -> None:
        """Replace a persistently-broken channel with a fresh one. A
        grpc-core channel whose server died mid-long-poll can wedge its
        subchannel in connect-timeout loops ("FD Shutdown") and never
        reach the restarted server even though a fresh dial succeeds
        immediately — observed in the learner SIGKILL drill. In-flight
        calls on the old channel fail over to the new one on their next
        attempt (retry/spool paths)."""
        with self._channel_lock:
            old = self._channel
            self._make_channel()
        try:
            old.close()
        except Exception:
            pass
        print(f"[grpc] channel to {self._addr} rebuilt after persistent "
              f"connection failure", flush=True)

    def _poll_once(self, first: bool, timeout_s: float,
                   known_version: int | None = None, record: bool = False):
        req = msgpack.packb(
            {"id": self.identity,
             "ver": (self._known_version if known_version is None
                     else known_version),
             "first": first},
            use_bin_type=True)
        # future-based invocation so close() can cancel a parked long-poll
        # instead of waiting out its full timeout (64 agents x 35 s
        # otherwise serializes shutdown into minutes).
        call = self._poll.future(req, timeout=timeout_s)
        self._inflight = call
        try:
            raw = call.result()
        finally:
            self._inflight = None
        rx_ns = time.monotonic_ns()  # receipt stamp BEFORE decode
        resp = msgpack.unpackb(raw, raw=False)
        # A code-1 ack without a bundle (the servicer's metadata-only
        # registration reply) is not a model delivery.
        if resp.get("code") == 1 and "model" in resp:
            self._known_version = int(resp["ver"])
            if record:  # subscription deliveries only, not handshakes
                self._ledger.append(int(resp["ver"]), rx_ns)
                self._m["model_recv_total"].inc()
                self._m["model_recv_bytes"].inc(len(raw))
            return int(resp["ver"]), resp["model"], rx_ns
        return None

    def fetch_model(self, timeout_s: float = 60.0) -> tuple[int, bytes]:
        """Bounded connect/handshake retry under the unified RetryPolicy
        (the reference's init retry loop never decrements its counter and
        can spin forever, agent_grpc.rs:151-171; the old flat 0.2s sleep
        dialect here is replaced by the shared jittered backoff)."""
        deadline = time.monotonic() + timeout_s

        def attempt():
            # ver=-1 regardless of _known_version: a handshake wants
            # the bundle unconditionally — without it, a re-handshake
            # on a transport already at the server's version would
            # draw the metadata-only ack and spin to timeout.
            result = self._poll_once(first=True, timeout_s=min(
                5.0, max(0.1, deadline - time.monotonic())),
                known_version=-1)
            return None if result is None else (result[0], result[1])

        try:
            return self._retry.call(attempt, op="grpc.handshake",
                                    deadline_s=timeout_s,
                                    retry_on=(grpc.RpcError,))
        except (grpc.RpcError, TimeoutError) as e:
            raise TimeoutError(
                f"gRPC model handshake timed out: {e}") from None

    def register(self, agent_id: str | None = None, timeout_s: float = 10.0) -> bool:
        # The connection identity registers via the first_time ClientPoll
        # (one RPC fewer than the ZMQ plane); fetch_model() already did it.
        # A LOGICAL agent id (vector host lane) has no poll loop of its
        # own, so it registers with a one-shot first_time poll carrying
        # the CURRENT known version — the Python servicer then acks
        # metadata-only (no redundant bundle per lane; the native C++
        # gRPC server still ships the bundle, which is discarded — the
        # shared listener owns model delivery for the whole connection).
        if agent_id is None or agent_id == self.identity:
            return True
        req = msgpack.packb({"id": agent_id, "ver": self._known_version,
                             "first": True}, use_bin_type=True)
        try:
            resp = msgpack.unpackb(self._poll(req, timeout=timeout_s),
                                   raw=False)
        except grpc.RpcError:
            return False
        return resp.get("code") == 1

    def send_trajectory(self, payload: bytes,
                        agent_id: str | None = None) -> None:
        from relayrl_tpu.transport.base import pack_trajectory_envelope

        env = pack_trajectory_envelope(agent_id or self.identity, payload)
        if self._fault_send is not None:
            parts = self._fault_send.inject(env)
            if not parts:
                # On an ack'd transport a lost request surfaces as a
                # timeout — raise so the caller (spool) retries/buffers,
                # the same failure shape a real drop produces.
                raise TimeoutError("fault-injected trajectory drop (grpc)")
        else:
            parts = ((0.0, env),)
        t0 = time.monotonic()
        for delay_s, part in parts:
            if delay_s > 0:
                time.sleep(delay_s)
            resp = msgpack.unpackb(self._send(part, timeout=30.0), raw=False)
            self._m["send_total"].inc()
            self._m["send_bytes"].inc(len(part))
            code = resp.get("code")
            if code in (NACK_QUARANTINED, NACK_OVERLOADED):
                # Typed guardrail nack: the server is alive and REFUSED
                # the send — not a wire failure (the spool must not
                # count it against the breaker; see spool._attempt).
                raise IngestNack(code, str(resp.get("error") or ""),
                                 float(resp.get("retry_after_s") or 0.0))
            if code != 1:
                raise RuntimeError(
                    f"trajectory rejected: {resp.get('error')}")
        self._m["send_seconds"].observe(time.monotonic() - t0)

    def start_model_listener(self) -> None:
        if self._listener is not None:
            return
        self._stop.clear()
        self._listener = threading.Thread(
            target=self._poll_loop, name="grpc-model-poll", daemon=True)
        self._listener.start()

    def _poll_loop(self) -> None:
        while not self._stop.is_set():
            try:
                result = self._poll_once(first=False,
                                         timeout_s=self._poll_timeout_s,
                                         record=True)
                if self._poll_broken:
                    # First successful poll after a break: that is the
                    # one reconnect (native counts heals the same way —
                    # semantics must match across backends). The shared
                    # notifier counts it AND fires on_reconnect (spool
                    # replay).
                    self._poll_broken = False
                    self._notify_reconnect()
                self._poll_fail_streak = 0
            except (grpc.RpcError, grpc.FutureCancelledError) as e:
                # FutureCancelledError: close() cancelled the parked poll.
                # A DEADLINE_EXCEEDED is the benign empty long-poll; any
                # other RpcError marks the channel broken until a poll
                # lands again.
                code = getattr(e, "code", lambda: None)()
                if (isinstance(e, grpc.RpcError)
                        and code != grpc.StatusCode.DEADLINE_EXCEEDED
                        and not self._stop.is_set()):
                    self._poll_broken = True
                    self._poll_fail_streak += 1
                    if self._poll_fail_streak >= 5:
                        # grpc-core can wedge a killed server's channel
                        # permanently — rebuild (see _rebuild_channel).
                        self._poll_fail_streak = 0
                        self._rebuild_channel()
                if self._stop.wait(1.0):
                    break
                continue
            if result is not None:
                version, bundle, rx_ns = result
                if self._fault_model is not None:
                    # chaos plane: lose/delay/corrupt the delivery after
                    # the poll returned (a dropped pull just re-polls; a
                    # corrupted one dies in the actor's decode guards
                    # and triggers the resync path).
                    for delay_s, part in self._fault_model.inject(bundle):
                        if delay_s > 0:
                            time.sleep(delay_s)
                        self.on_model(version, part)
                else:
                    self.on_model(version, bundle)
                self._m["model_deliver_seconds"].observe(
                    (time.monotonic_ns() - rx_ns) / 1e9)
                # Downstream trace receipt hop (no publisher stamp on
                # the pull plane — model age stays a broadcast-side
                # observation).
                from relayrl_tpu.telemetry.trace import (
                    record_model_receipt,
                )

                record_model_receipt(version, rx_ns, None, "grpc")

    def drain_receipts(self, max_n: int = 65536) -> list[tuple[int, int]]:
        """Drain the pre-decode receipt ledger (same surface as the
        native C++ and zmq ledgers)."""
        return self._ledger.drain(max_n)

    def request_resync(self, held_version: int = -1) -> None:
        """Model-wire v2 resync: forget the held version so the next
        long-poll carries ``ver=-1`` and the server replies with a full
        bundle instead of an undecodable delta. ``held_version`` is
        irrelevant on this pull plane — the re-poll is the request."""
        self._known_version = -1

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            # Cancel-in-a-loop: a single cancel can miss the window where
            # the listener is between polls and about to park a fresh
            # 35 s future (TOCTOU) — keep cancelling whatever is in
            # flight until the thread exits.
            deadline = time.monotonic() + 10
            while self._listener.is_alive() and time.monotonic() < deadline:
                inflight = self._inflight
                if inflight is not None:
                    inflight.cancel()
                self._listener.join(timeout=0.2)
            self._listener = None
        self._channel.close()
