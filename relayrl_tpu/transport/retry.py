"""Unified retry/backoff policy + circuit breaker for the transport plane.

Before this module each backend grew its own dialect: the zmq handshake
re-polled on a fixed 2 s sub-deadline, grpc retried with a flat
``time.sleep(0.2)``, the native connect loop slept 0.2 s flat, and the
agent handshake bounded all of them with a caller timeout. One policy now
drives every bounded retry loop — jittered exponential backoff under a
per-op deadline — and one breaker guards repeated-failure paths (the
actor's trajectory sends against a dead learner): after
``failure_threshold`` consecutive failures the breaker opens (callers
skip the wire and spool instead), and after ``reset_timeout_s`` a single
half-open probe is let through; its success closes the breaker and
triggers spool replay.

Telemetry (docs/observability.md):

* ``relayrl_retry_attempts_total{op}``  — every retried attempt (not the
  first try: a clean call costs zero counter traffic)
* ``relayrl_retry_exhausted_total{op}`` — deadline/attempt budget spent
* ``relayrl_breaker_state{name}``       — 0 closed / 1 half-open / 2 open
* events ``retry_exhausted`` / ``breaker_open`` / ``breaker_close``
  in the run journal.

Config: the ``transport.retry`` section (ConfigLoader.get_transport_
params parses it; docs/operations.md has the knob table).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff under a per-op deadline.

    ``base_delay_s * multiplier**k`` capped at ``max_delay_s``, each
    delay scaled by ``1 - jitter*u`` (u ~ U[0,1)) so a restarted fleet's
    retries decorrelate instead of thundering in lockstep.
    ``max_attempts=0`` means attempts are bounded only by the deadline.
    """

    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    deadline_s: float = 30.0
    max_attempts: int = 0

    @classmethod
    def from_dict(cls, d: dict | None) -> "RetryPolicy":
        d = dict(d or {})
        kwargs = {}
        for key, cast in (("base_delay_s", float), ("max_delay_s", float),
                          ("multiplier", float), ("jitter", float),
                          ("deadline_s", float), ("max_attempts", int)):
            if key in d:
                try:
                    kwargs[key] = cast(d[key])
                except (TypeError, ValueError):
                    pass  # malformed knob degrades to the default
        return cls(**kwargs)

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        """Backoff before retry ``attempt`` (0-based: the wait after the
        first failure)."""
        raw = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** attempt)
        u = (rng.random() if rng is not None else random.random())
        return max(0.0, raw * (1.0 - self.jitter * u))

    def call(self, fn, *, op: str, deadline_s: float | None = None,
             retry_on: tuple = (Exception,), rng: random.Random | None = None,
             sleep=time.sleep):
        """Run ``fn()`` under this policy: retry on ``retry_on`` (or on a
        ``None`` return — poll-style callees) with jittered backoff until
        the deadline or attempt budget is spent, then raise the last
        exception (or TimeoutError for None-returning pollers). A callee
        that must bound its own inner blocking wait closes over
        :meth:`deadline_at`.
        """
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        deadline = time.monotonic() + budget
        attempt = 0
        last_exc: Exception | None = None
        while True:
            try:
                result = fn()
                if result is not None:
                    return result
            except retry_on as e:  # noqa: PERF203 — the retry loop
                last_exc = e
            out_of_attempts = (self.max_attempts > 0
                               and attempt + 1 >= self.max_attempts)
            remaining = deadline - time.monotonic()
            if out_of_attempts or remaining <= 0:
                _metrics()["exhausted"].labels_inc(op)
                from relayrl_tpu import telemetry

                telemetry.emit("retry_exhausted", op=op, attempts=attempt + 1,
                               deadline_s=budget,
                               error=(repr(last_exc) if last_exc else None))
                if last_exc is not None:
                    raise last_exc
                raise TimeoutError(
                    f"{op}: no result after {attempt + 1} attempt(s) "
                    f"in {budget:.1f}s")
            sleep(min(self.delay(attempt, rng), max(0.0, remaining)))
            attempt += 1
            _metrics()["attempts"].labels_inc(op)

    def deadline_at(self, deadline_s: float | None = None) -> float:
        return time.monotonic() + (self.deadline_s if deadline_s is None
                                   else float(deadline_s))


class _OpCounters:
    """Per-op labeled counter front, lazily materialized per op label.
    Re-resolves against the CURRENT process registry on every call path
    where it changed (benches install a fresh registry per row; a cached
    metric bound to the old one would silently vanish from snapshots)."""

    def __init__(self, name: str, help_text: str):
        self._name = name
        self._help = help_text
        self._by_op: dict[str, object] = {}
        self._registry = None
        self._lock = threading.Lock()

    @classmethod
    def counter(cls, name: str, help_text: str) -> "_OpCounters":
        """Registration constructor: the family name must appear at a
        statically visible ``*.counter("literal", ...)`` site so the
        contracts engine can reconcile it against the docs catalog."""
        return cls(name, help_text)

    def labels_inc(self, op: str, n: int = 1) -> None:
        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        metric = self._by_op.get(op) if reg is self._registry else None
        if metric is None:
            with self._lock:
                if reg is not self._registry:
                    self._by_op.clear()
                    self._registry = reg
                metric = self._by_op.get(op)
                if metric is None:
                    metric = reg.counter(self._name, self._help, {"op": op})
                    self._by_op[op] = metric
        metric.inc(n)


_metrics_cache: dict | None = None
_metrics_lock = threading.Lock()


def _metrics() -> dict:
    global _metrics_cache
    if _metrics_cache is None:
        with _metrics_lock:
            if _metrics_cache is None:
                _metrics_cache = {
                    "attempts": _OpCounters.counter(
                        "relayrl_retry_attempts_total",
                        "retried attempts (first tries are free)"),
                    "exhausted": _OpCounters.counter(
                        "relayrl_retry_exhausted_total",
                        "retry budgets spent without success"),
                }
    return _metrics_cache


def reset_metrics_for_tests() -> None:
    """Drop the cached counter fronts so a fresh test registry sees new
    metric objects (mirrors telemetry.reset_for_tests)."""
    global _metrics_cache
    with _metrics_lock:
        _metrics_cache = None


_BREAKER_CLOSED, _BREAKER_HALF_OPEN, _BREAKER_OPEN = 0, 1, 2


class CircuitBreaker:
    """Consecutive-failure breaker with a half-open probe.

    closed → (``failure_threshold`` consecutive failures) → open →
    (``reset_timeout_s`` elapses) → half-open: :meth:`allow` admits ONE
    probe; its success closes the breaker, its failure re-opens (and
    re-arms the timeout). Thread-safe; the state lands in the
    ``relayrl_breaker_state{name}`` gauge and open/close transitions in
    the run journal.
    """

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 5.0):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self._lock = threading.Lock()
        self._state = _BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_out = False
        from relayrl_tpu import telemetry

        self._m_state = telemetry.get_registry().gauge(
            "relayrl_breaker_state",
            "circuit breaker: 0=closed, 1=half-open, 2=open",
            {"name": name})
        self._m_state.set(0)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return {_BREAKER_CLOSED: "closed",
                    _BREAKER_HALF_OPEN: "half_open",
                    _BREAKER_OPEN: "open"}[self._state]

    def _maybe_half_open(self) -> None:
        # lock held
        if (self._state == _BREAKER_OPEN
                and time.monotonic() - self._opened_at
                >= self.reset_timeout_s):
            self._state = _BREAKER_HALF_OPEN
            self._probe_out = False
            self._m_state.set(_BREAKER_HALF_OPEN)

    def allow(self) -> bool:
        """May the caller touch the wire right now? Open → False;
        half-open → True exactly once per timeout window (the probe)."""
        with self._lock:
            self._maybe_half_open()
            if self._state == _BREAKER_CLOSED:
                return True
            if self._state == _BREAKER_HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED an open/half-open
        breaker (the caller's replay trigger)."""
        with self._lock:
            was_broken = self._state != _BREAKER_CLOSED
            self._state = _BREAKER_CLOSED
            self._failures = 0
            self._probe_out = False
            self._m_state.set(_BREAKER_CLOSED)
        if was_broken:
            from relayrl_tpu import telemetry

            telemetry.emit("breaker_close", name=self.name)
        return was_broken

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the breaker."""
        with self._lock:
            self._failures += 1
            if self._state == _BREAKER_HALF_OPEN:
                # failed probe: straight back to open, timeout re-armed
                self._state = _BREAKER_OPEN
                self._opened_at = time.monotonic()
                self._probe_out = False
                self._m_state.set(_BREAKER_OPEN)
                opened = True
            elif (self._state == _BREAKER_CLOSED
                    and self._failures >= self.failure_threshold):
                self._state = _BREAKER_OPEN
                self._opened_at = time.monotonic()
                self._m_state.set(_BREAKER_OPEN)
                opened = True
            else:
                opened = False
        if opened:
            from relayrl_tpu import telemetry

            telemetry.emit("breaker_open", name=self.name,
                           failures=self._failures)
        return opened


def breaker_from_config(name: str, retry_cfg: dict | None) -> CircuitBreaker:
    d = dict(retry_cfg or {})
    try:
        threshold = int(d.get("breaker_threshold", 5))
    except (TypeError, ValueError):
        threshold = 5
    try:
        reset_s = float(d.get("breaker_reset_s", 5.0))
    except (TypeError, ValueError):
        reset_s = 5.0
    return CircuitBreaker(name, failure_threshold=threshold,
                          reset_timeout_s=reset_s)


__all__ = ["RetryPolicy", "CircuitBreaker", "breaker_from_config",
           "reset_metrics_for_tests"]
