"""ctypes bindings for the native C++ transport core (native/transport.cc).

Implements the same :class:`ServerTransport`/:class:`AgentTransport`
interfaces as the ZMQ/gRPC backends over the framed-TCP protocol: one
control connection (handshake + trajectories) and one subscription
connection (model broadcasts) per agent, one epoll loop thread per server.
"""

from __future__ import annotations

import ctypes
import threading
import time

from relayrl_tpu.transport.base import (
    AgentTransport,
    ServerTransport,
    swallow_decode_error,
    unpack_trajectory_envelope,
)
from relayrl_tpu.transport.probe import parse_host_port as _parse_host_port

_EV_TRAJECTORY = 1
_EV_REGISTER = 2
_EV_UNREGISTER = 3


def _load(lib_path: str) -> ctypes.CDLL:
    lib = ctypes.CDLL(lib_path)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.rl_server_create.restype = ctypes.c_void_p
    lib.rl_server_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.rl_server_start.restype = ctypes.c_int
    lib.rl_server_start.argtypes = [ctypes.c_void_p]
    lib.rl_server_stop.argtypes = [ctypes.c_void_p]
    lib.rl_server_destroy.argtypes = [ctypes.c_void_p]
    lib.rl_server_port.restype = ctypes.c_uint16
    lib.rl_server_port.argtypes = [ctypes.c_void_p]
    lib.rl_server_set_model.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u8p, ctypes.c_size_t]
    lib.rl_server_broadcast.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u8p, ctypes.c_size_t]
    # Wire-v2 opaque-frame broadcast (no stored-model update). Tolerate a
    # stale prebuilt .so without the symbol: publishers then fall back to
    # full-bundle broadcasts (correctness kept, wire savings lost).
    try:
        lib.rl_server_broadcast_frame.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, u8p, ctypes.c_size_t]
    except AttributeError:
        pass
    lib.rl_server_poll.restype = ctypes.c_long
    lib.rl_server_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int), u8p,
        ctypes.c_size_t]
    lib.rl_client_connect.restype = ctypes.c_void_p
    lib.rl_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                      ctypes.c_int]
    lib.rl_client_close.argtypes = [ctypes.c_void_p]
    lib.rl_client_get_model.restype = ctypes.c_long
    lib.rl_client_get_model.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), u8p,
        ctypes.c_size_t]
    lib.rl_client_register.restype = ctypes.c_int
    lib.rl_client_register.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int]
    lib.rl_client_send_traj.restype = ctypes.c_int
    lib.rl_client_send_traj.argtypes = [ctypes.c_void_p, u8p, ctypes.c_size_t]
    lib.rl_client_ping.restype = ctypes.c_int
    lib.rl_client_ping.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rl_sub_ping.restype = ctypes.c_int
    lib.rl_sub_ping.argtypes = [ctypes.c_void_p]
    lib.rl_server_set_idle_timeout.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rl_sub_connect.restype = ctypes.c_void_p
    lib.rl_sub_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16,
                                   ctypes.c_int]
    lib.rl_sub_poll.restype = ctypes.c_long
    lib.rl_sub_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64), u8p,
        ctypes.c_size_t]
    lib.rl_server_poll_batch.restype = ctypes.c_long
    lib.rl_server_poll_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int)]
    lib.rl_sub_start_async.restype = ctypes.c_int
    lib.rl_sub_start_async.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.rl_sub_next.restype = ctypes.c_long
    lib.rl_sub_next.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int64), u8p, ctypes.c_size_t]
    lib.rl_sub_receipts.restype = ctypes.c_long
    lib.rl_sub_receipts.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_long]
    # native gRPC/HTTP-2 server (grpc_server.cc): same embedder surface
    lib.rl_grpc_server_create.restype = ctypes.c_void_p
    lib.rl_grpc_server_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.rl_grpc_server_start.restype = ctypes.c_int
    lib.rl_grpc_server_start.argtypes = [ctypes.c_void_p]
    lib.rl_grpc_server_stop.argtypes = [ctypes.c_void_p]
    lib.rl_grpc_server_destroy.argtypes = [ctypes.c_void_p]
    lib.rl_grpc_server_port.restype = ctypes.c_uint16
    lib.rl_grpc_server_port.argtypes = [ctypes.c_void_p]
    lib.rl_grpc_server_set_model.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u8p, ctypes.c_size_t]
    lib.rl_grpc_server_broadcast.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u8p, ctypes.c_size_t]
    lib.rl_grpc_server_set_idle_timeout.argtypes = [ctypes.c_void_p,
                                                    ctypes.c_int]
    lib.rl_grpc_server_poll.restype = ctypes.c_long
    lib.rl_grpc_server_poll.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int), u8p,
        ctypes.c_size_t]
    lib.rl_grpc_server_poll_batch.restype = ctypes.c_long
    lib.rl_grpc_server_poll_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int)]
    return lib


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else None


class NativeServerTransportImpl(ServerTransport):
    PREFIX = "rl_server"  # symbol prefix: framed-TCP core (transport.cc)
    GAUGE_BACKEND = "native"  # relayrl_transport_subscribers label

    # The C++ core answers kFrameGetModel itself from set_model bytes, so
    # wire-v2 publishes must ride with a full v1 bundle for handshakes.
    needs_handshake_bytes = True

    def __init__(self, lib_path: str, bind_addr: str,
                 idle_timeout_s: float = 0.0, chunk_bytes: int = 0):
        super().__init__()
        self._lib = _load(lib_path)
        self._bind_addr = bind_addr  # subscriber-gauge instance label
        host, port = _parse_host_port(bind_addr)
        self._handle = self._fn("create")(host.encode(), port)
        if not self._handle:
            raise RuntimeError(f"native server bind failed on {bind_addr}")
        # 0 disables reaping; live agents heartbeat well inside any sane
        # timeout, so only crashed/partitioned peers are dropped.
        self._idle_timeout_ms = int(idle_timeout_s * 1000)
        # transport.chunk_bytes — the C++ framed protocol handles big
        # frames natively, so chunking defaults off here; when enabled
        # the chunks ride kFrameModelPush opaquely (pass-through) and the
        # Python sub loop reassembles. NB: each chunk stamps a C++
        # receipt, so fan-out accounting sees per-chunk receipt rows.
        self._chunk_bytes = max(0, int(chunk_bytes))
        self._poller: threading.Thread | None = None
        self._stop = threading.Event()
        self.drain_parse_failures = 0  # lost decoded batches (observable)
        # Registered-agent table for the relayrl_transport_subscribers
        # pull-gauge — the Python mirror of the C++ registry events
        # (register/unregister), maintained in the poll loops before the
        # embedder callbacks fire. Counts LOGICAL agents: the C++ core
        # does not expose its kernel connection table, so vector hosts
        # read as N lanes here (documented in docs/observability.md).
        self._subscriber_table: set[str] = set()
        self._subscriber_lock = threading.Lock()

    def _fn(self, name):
        return getattr(self._lib, f"{self.PREFIX}_{name}")

    def _note_subscriber(self, agent_id: str, alive: bool) -> None:
        with self._subscriber_lock:
            if alive:
                self._subscriber_table.add(agent_id)
            else:
                self._subscriber_table.discard(agent_id)

    def _subscriber_count(self) -> int:
        with self._subscriber_lock:
            return len(self._subscriber_table)

    @property
    def port(self) -> int:
        return int(self._fn("port")(self._handle))

    def start(self) -> None:
        if self._fn("start")(self._handle) != 0:
            raise RuntimeError("native server start failed")
        if self._idle_timeout_ms > 0:
            self._fn("set_idle_timeout")(self._handle,
                                                 self._idle_timeout_ms)
        version, bundle = self.get_model()
        data = _buf(bundle)
        self._fn("set_model")(self._handle, version, data,
                                      len(bundle))
        from relayrl_tpu.transport.base import register_subscriber_gauge

        register_subscriber_gauge(self.GAUGE_BACKEND, self._subscriber_count,
                                  bind=self._bind_addr)
        self._stop.clear()
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="native-server-poll", daemon=True)
        self._poller.start()

    def stop(self) -> None:
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout=5)
            self._poller = None
        self._fn("stop")(self._handle)

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._fn("destroy")(self._handle)
                self._handle = None
        except Exception:
            pass

    def publish_model(self, version: int, bundle_bytes: bytes,
                      handshake_bytes: bytes | None = None) -> None:
        """Legacy (v1) publishes broadcast AND store ``bundle_bytes`` as
        the handshake model in one C++ call. Wire-v2 publishes pass the
        frame as ``bundle_bytes`` plus a full v1 bundle as
        ``handshake_bytes``: the bundle goes to set_model (handshakes),
        the frame rides broadcast_frame opaquely (chunked when
        ``transport.chunk_bytes`` bounds it)."""
        if handshake_bytes is None:
            data = _buf(bundle_bytes)
            self._fn("broadcast")(self._handle, version, data,
                                  len(bundle_bytes))
            return
        hs = _buf(handshake_bytes)
        self._fn("set_model")(self._handle, version, hs, len(handshake_bytes))
        if not hasattr(self._lib, "rl_server_broadcast_frame"):
            # Stale prebuilt .so: broadcast the full bundle instead (the
            # C++ broadcast would otherwise store the frame as the
            # handshake model and poison late joiners).
            data = _buf(handshake_bytes)
            self._fn("broadcast")(self._handle, version, data,
                                  len(handshake_bytes))
            return
        from relayrl_tpu.transport.modelwire import split_frame

        for part in split_frame(bundle_bytes, self._chunk_bytes, version):
            data = _buf(part)
            self._lib.rl_server_broadcast_frame(self._handle, version, data,
                                                len(part))

    def _poll_loop(self) -> None:
        # Two modes, picked at start() by whether the embedder wants the
        # columnar fast path:
        #  * batch drain (TrainingServer): rl_server_poll_batch decodes
        #    whole batches of trajectory envelopes in C++ (GIL released)
        #    and this thread just parses RLD1 headers — one Python
        #    callback per trajectory carrying ready numpy columns.
        #  * legacy per-event: raw envelope bytes through on_trajectory,
        #    byte-compatible for embedders without a decoded handler.
        if self.on_trajectory_decoded is not None:
            self._poll_loop_batch()
        else:
            self._poll_loop_raw()

    def _poll_loop_batch(self) -> None:
        from relayrl_tpu.types.columnar import (
            DecodedTrajectory,
            Registration,
            RawTrajectory,
            Unregistration,
            is_columnar_frame,
            parse_drain,
            parse_frame,
        )

        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        m_frames = reg.counter(
            "relayrl_server_columnar_frames_total",
            "columnar trajectory frames decoded straight into "
            "DecodedTrajectory (the wire fast path)")
        m_frame_bytes = reg.counter(
            "relayrl_server_columnar_bytes_total",
            "columnar trajectory frame bytes decoded")
        m_frame_rejects = reg.counter(
            "relayrl_server_columnar_rejects_total",
            "columnar frames refused at decode (CRC mismatch / "
            "malformed layout) — also counted in dropped_total")
        cap = 1 << 20
        buf = (ctypes.c_uint8 * cap)()
        n_items = ctypes.c_int(0)
        while not self._stop.is_set():
            n = self._fn("poll_batch")(
                self._handle, 100, 256, buf, cap, ctypes.byref(n_items))
            if n < 0:
                continue
            if n_items.value == 0:  # first blob alone exceeds cap: grow
                cap = max(int(n) * 2, cap * 2)
                buf = (ctypes.c_uint8 * cap)()
                continue
            try:
                items = parse_drain(ctypes.string_at(buf, int(n)))
            except Exception as e:
                # A C++/Python RLD1 layout disagreement loses the whole
                # already-dequeued batch — make that observable, never
                # silent (and never crash ingest).
                self.drain_parse_failures += 1
                print(f"[NativeTransport] drain buffer unparseable "
                      f"({e!r}) — a decoded batch was LOST "
                      f"(#{self.drain_parse_failures})", flush=True)
                continue
            # One decoded-batch callback per drain (not per trajectory):
            # at fleet rate the per-item queue handoff was measurable.
            batch = []
            for item in items:
                if isinstance(item, DecodedTrajectory):
                    batch.append(item)
                elif isinstance(item, RawTrajectory):
                    agent_id, payload = item.agent_id, item.payload
                    if item.is_envelope:
                        try:
                            agent_id, payload = unpack_trajectory_envelope(
                                payload)
                        except Exception as e:
                            # truly malformed; Python decode will drop —
                            # but count it, and re-raise non-data errors
                            swallow_decode_error("native",
                                                 "trajectory_ingest", e)
                    if is_columnar_frame(payload):
                        # Columnar wire frame: the C++ envelope decoder
                        # carried it through verbatim (raw fallback, id
                        # intact incl. any seq tag); parse it here and
                        # join the decoded batch — same funnel as the
                        # C++-decoded items (seq dedup + guardrails in
                        # _on_trajectory_decoded).
                        try:
                            batch.append(parse_frame(payload,
                                                     agent_id=agent_id))
                            m_frames.inc()
                            m_frame_bytes.inc(len(payload))
                        except Exception as e:
                            # Same operator surface as the zmq/grpc
                            # staging path: a refused frame is visible
                            # on every transport.
                            m_frame_rejects.inc()
                            swallow_decode_error("native",
                                                 "columnar_frame", e)
                        continue
                    self.on_trajectory(agent_id, payload)
                elif isinstance(item, Registration):
                    self._note_subscriber(item.agent_id, True)
                    self.on_register(item.agent_id)
                elif isinstance(item, Unregistration):
                    self._note_subscriber(item.agent_id, False)
                    self.on_unregister(item.agent_id)
            if batch:
                self.on_trajectory_decoded(batch)

    def _poll_loop_raw(self) -> None:
        # One long-lived buffer, grown on demand: allocating a fresh
        # ctypes array per event zeroes the whole capacity each time and
        # dominated the ingest path (~5x at 64-actor scale).
        cap = 1 << 20
        buf = (ctypes.c_uint8 * cap)()
        ev_type = ctypes.c_int(0)
        while not self._stop.is_set():
            n = self._fn("poll")(self._handle, 100,
                                         ctypes.byref(ev_type), buf, cap)
            if n < 0:
                continue
            if n > cap:  # grow and re-take (event was held back)
                cap = int(n) * 2
                buf = (ctypes.c_uint8 * cap)()
                continue
            payload = ctypes.string_at(buf, int(n))
            if ev_type.value == _EV_TRAJECTORY:
                try:
                    agent_id, traj = unpack_trajectory_envelope(payload)
                except Exception as e:
                    swallow_decode_error("native", "trajectory_ingest", e)
                    continue
                self.on_trajectory(agent_id, traj)
            elif ev_type.value == _EV_REGISTER:
                agent_id = payload.decode(errors="replace")
                self._note_subscriber(agent_id, True)
                self.on_register(agent_id)
            elif ev_type.value == _EV_UNREGISTER:
                agent_id = payload.decode(errors="replace")
                self._note_subscriber(agent_id, False)
                self.on_unregister(agent_id)


class NativeAgentTransportImpl(AgentTransport):
    # Liveness gauge encoding (docs/observability.md): the ping() rc
    # space folded to three operator states.
    _HB_ALIVE, _HB_SLOW, _HB_DEAD = 0, 1, 2

    def __init__(self, lib_path: str, server_addr: str,
                 identity: str | None = None, heartbeat_s: float = 5.0,
                 retry: dict | None = None):
        super().__init__()
        import os
        import secrets

        from relayrl_tpu import faults
        from relayrl_tpu.transport.base import agent_wire_metrics
        from relayrl_tpu.transport.retry import RetryPolicy

        self._retry = RetryPolicy.from_dict(retry)
        self._fault_send = faults.site("agent.send")
        self._fault_model = faults.site("agent.model")
        self._lib = _load(lib_path)
        self.identity = identity or f"AGENT_ID-{os.getpid()}{secrets.token_hex(4)}"
        self._host, self._port = _parse_host_port(server_addr)
        self._ctrl = None
        self._had_ctrl = False  # distinguishes first connect from redial
        # Serializes every C call on the ctrl handle against the
        # fault-plane _kill_ctrl close: without it, a kill_connection
        # injection could free the handle mid-ping/send on another
        # thread (use-after-free in the C library, a REAL crash the
        # drill did not intend). Ping holds it <= its 1s timeout.
        self._ctrl_lock = threading.Lock()
        self._sub = None
        # transport.heartbeat_s config knob (was a hard-coded 5.0 in
        # start_model_listener); <= 0 disables the beat entirely.
        self._heartbeat_default = float(heartbeat_s)
        self._heartbeat_s = 0.0
        self._hb_state = self._HB_ALIVE
        self._listener: threading.Thread | None = None
        self._stop = threading.Event()
        self._m = agent_wire_metrics("native")
        from relayrl_tpu import telemetry

        self._m_liveness = telemetry.get_registry().gauge(
            "relayrl_transport_heartbeat_state",
            "control-channel liveness: 0=alive, 1=slow, 2=dead",
            {"backend": "native"})

    def _ensure_ctrl(self, timeout_s: float):
        """Control-channel connect under the unified RetryPolicy (was a
        flat 0.2s sleep loop — the third per-backend retry dialect this
        policy replaces)."""
        if self._ctrl is None:
            def attempt():
                handle = self._lib.rl_client_connect(
                    self._host.encode(), self._port, 2000)
                return handle or None

            try:
                self._ctrl = self._retry.call(attempt, op="native.connect",
                                              deadline_s=timeout_s)
            except TimeoutError:
                raise TimeoutError(
                    f"native transport: cannot connect to "
                    f"{self._host}:{self._port}") from None
            if self._had_ctrl:
                # A REDIAL, not the first connect: the server reaped the
                # old connection's registrations on kernel close — the
                # owner must re-register its lanes and replay the spool.
                self._notify_reconnect()
            self._had_ctrl = True
        return self._ctrl

    def fetch_model(self, timeout_s: float = 60.0) -> tuple[int, bytes]:
        ctrl = self._ensure_ctrl(timeout_s)
        cap = 1 << 20
        deadline = time.monotonic() + timeout_s
        version = ctypes.c_uint64(0)
        while True:
            remaining = max(100, int((deadline - time.monotonic()) * 1000))
            buf = (ctypes.c_uint8 * cap)()
            with self._ctrl_lock:
                n = self._lib.rl_client_get_model(
                    ctrl, min(remaining, 5000), ctypes.byref(version),
                    buf, cap)
            if 0 <= n <= cap:
                return int(version.value), bytes(buf[: int(n)])
            if n > cap:
                cap = int(n) * 2
                continue
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    "native model handshake timed out — check the server is "
                    "up AND that both ends use the same server_type (a zmq/"
                    "grpc server will silently ignore native framing)")

    def register(self, agent_id: str | None = None, timeout_s: float = 10.0) -> bool:
        ctrl = self._ensure_ctrl(timeout_s)
        with self._ctrl_lock:
            rc = self._lib.rl_client_register(
                ctrl, (agent_id or self.identity).encode(),
                int(timeout_s * 1000))
        return rc == 0

    def send_trajectory(self, payload: bytes,
                        agent_id: str | None = None) -> None:
        from relayrl_tpu.transport.base import pack_trajectory_envelope

        env = pack_trajectory_envelope(agent_id or self.identity, payload)
        if self._fault_send is not None:
            if self._fault_send.take_kill_connection():
                self._kill_ctrl()
            parts = self._fault_send.inject(env)
            if not parts:
                # ack'd transport: a lost frame surfaces as a failed
                # send — raise so the spool buffers and replays it.
                raise RuntimeError("fault-injected trajectory drop (native)")
        else:
            parts = ((0.0, env),)
        ctrl = self._ensure_ctrl(5.0)
        t0 = time.monotonic()
        for delay_s, part in parts:
            if delay_s > 0:
                time.sleep(delay_s)
            data = _buf(part)
            with self._ctrl_lock:
                if self._ctrl is not ctrl:  # killed mid-batch: redial
                    raise RuntimeError(
                        "native trajectory send failed (connection "
                        "killed mid-send)")
                rc = self._lib.rl_client_send_traj(ctrl, data, len(part))
            if rc != 0:
                raise RuntimeError("native trajectory send failed")
            self._m["send_total"].inc()
            self._m["send_bytes"].inc(len(part))
        self._m["send_seconds"].observe(time.monotonic() - t0)

    def _kill_ctrl(self) -> None:
        """Fault-plane connection kill: drop the control channel the way
        a crash would; the next send redials through _ensure_ctrl (and
        the server's kernel-close reaping unregisters this agent). The
        close happens under _ctrl_lock so no other thread can be inside
        a C call on the handle being freed."""
        with self._ctrl_lock:
            ctrl, self._ctrl = self._ctrl, None
            if ctrl:
                self._lib.rl_client_close(ctrl)

    def ping(self, timeout_s: float = 2.0) -> int:
        """Liveness probe on the control channel: 0 alive, 2 slow (no pong
        inside the timeout, connection kept), 1 hard failure healed by
        redial, -1 dead even after redial."""
        ctrl = self._ensure_ctrl(timeout_s)
        with self._ctrl_lock:
            return int(self._lib.rl_client_ping(ctrl,
                                                int(timeout_s * 1000)))

    def start_model_listener(self, heartbeat_s: float | None = None) -> None:
        """``heartbeat_s=None`` uses the constructor's value (the
        ``transport.heartbeat_s`` config knob); an explicit argument
        still overrides per-listener."""
        if self._listener is not None:
            return
        self._sub = self._lib.rl_sub_connect(self._host.encode(), self._port,
                                             5000)
        if not self._sub:
            raise RuntimeError("native subscribe connection failed")
        self._heartbeat_s = (self._heartbeat_default if heartbeat_s is None
                             else float(heartbeat_s))
        # Async mode: a C++ reader thread owns the socket — it parses and
        # CLOCK_MONOTONIC-timestamps every ModelPush the moment it arrives
        # (GIL-free; the receipt ledger is the soak benches' fan-out
        # evidence), owns the sub-channel keepalive, and reconnects. The
        # Python thread below only drains the decoded queue.
        self._lib.rl_sub_start_async(self._sub, int(self._heartbeat_s * 1000))
        self._stop.clear()
        self._listener = threading.Thread(target=self._sub_loop,
                                          name="native-model-sub", daemon=True)
        self._listener.start()

    def drain_receipts(self, max_n: int = 65536) -> list[tuple[int, int]]:
        """Drain the C++ receipt ledger: ``[(version, rx_mono_ns), ...]``,
        stamped at frame parse in the native reader thread — comparable
        against ``time.monotonic_ns()`` of any process on this host."""
        if self._sub is None:
            return []
        vers = (ctypes.c_uint64 * max_n)()
        ts = (ctypes.c_int64 * max_n)()
        n = self._lib.rl_sub_receipts(self._sub, vers, ts, max_n)
        return [(int(vers[i]), int(ts[i])) for i in range(int(n))]

    def _sub_loop(self) -> None:
        from relayrl_tpu.transport.modelwire import ChunkReassembler

        cap = 1 << 20
        buf = (ctypes.c_uint8 * cap)()  # reused; fresh alloc zeroes 1 MiB/poll
        version = ctypes.c_uint64(0)
        rx_ns = ctypes.c_int64(0)
        last_beat = time.monotonic()
        # Chunked wire-v2 frames (server transport.chunk_bytes) ride the
        # C++ core as opaque ModelPush payloads; reassemble before
        # on_model so the embedder always sees whole frames.
        reasm = ChunkReassembler()
        while not self._stop.is_set():
            n = self._lib.rl_sub_next(self._sub, 200, ctypes.byref(version),
                                      ctypes.byref(rx_ns), buf, cap)
            # Control-channel ping still detects a dead server (and redials
            # C++-side) even when the agent is neither stepping nor
            # receiving models; the sub channel's keepalive now lives in
            # the C++ async reader.
            if (self._heartbeat_s > 0
                    and time.monotonic() - last_beat >= self._heartbeat_s):
                last_beat = time.monotonic()
                with self._ctrl_lock:
                    ctrl = self._ctrl
                    rc = (int(self._lib.rl_client_ping(ctrl, 1000))
                          if ctrl else None)
                if rc is not None:
                    # rc: 0 alive, 2 slow (no pong in window), 1 hard
                    # failure healed by redial (counts as a reconnect,
                    # lands alive, and fires on_reconnect so the owner
                    # re-registers + replays its spool), -1 dead even
                    # after redial.
                    if rc == 1:
                        self._notify_reconnect()
                    state = (self._HB_ALIVE if rc in (0, 1)
                             else self._HB_SLOW if rc == 2
                             else self._HB_DEAD)
                    self._m_liveness.set(state)
                    # Journal the TRANSITION only (the gauge carries the
                    # level; one event per ping would swamp the journal).
                    if state != self._hb_state:
                        from relayrl_tpu import telemetry

                        telemetry.emit(
                            "heartbeat",
                            state=("alive", "slow", "dead")[state],
                            prev=("alive", "slow", "dead")[self._hb_state])
                        self._hb_state = state
            if n < 0:
                continue
            if n > cap:
                cap = int(n) * 2
                buf = (ctypes.c_uint8 * cap)()
                continue
            # rx_ns is the C++ reader's frame-parse stamp (the ledger
            # truth); deliver_seconds measures the Python-side handoff
            # from there through the swap.
            self._m["model_recv_bytes"].inc(int(n))
            blob = reasm.feed(ctypes.string_at(buf, int(n)))
            if blob is None:
                continue  # mid-chunk: deliver on the final part
            self._m["model_recv_total"].inc()
            if self._fault_model is not None:
                # chaos plane: the C++ ledger already stamped the
                # receipt; the injected fault hits the delivery layer —
                # corrupt dies in the actor's decode/CRC guards, drop
                # waits out the keyframe cadence.
                for delay_s, part in self._fault_model.inject(blob):
                    if delay_s > 0:
                        time.sleep(delay_s)
                    self.on_model(int(version.value), part)
            else:
                self.on_model(int(version.value), blob)
            self._m["model_deliver_seconds"].observe(
                max(0.0, (time.monotonic_ns() - int(rx_ns.value)) / 1e9))
            # Downstream trace receipt hop off the C++ ledger's stamp.
            from relayrl_tpu.telemetry.trace import record_model_receipt

            record_model_receipt(int(version.value), int(rx_ns.value),
                                 None, "native")

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            self._listener.join(timeout=5)
            self._listener = None
        for handle in (self._ctrl, self._sub):
            if handle:
                self._lib.rl_client_close(handle)
        self._ctrl = self._sub = None


class NativeGrpcServerTransportImpl(NativeServerTransportImpl):
    """The native gRPC plane (native/grpc_server.cc): a from-scratch
    HTTP/2 server speaking the exact gRPC wire protocol of the Python
    backend's two RPCs (SendActions, ClientPoll long-poll), with the same
    embedder surface as the framed core — EventHub batch drain, columnar
    decode, model broadcast waking parked polls. grpcio agents connect to
    it unchanged.

    ``idle_timeout_s`` here is the ClientPoll long-poll window (the
    Python backend's semantic), not connection reaping.
    """

    PREFIX = "rl_grpc_server"
    GAUGE_BACKEND = "grpc"  # relayrl_transport_subscribers label

    # The C++ ClientPoll serves the stored model to every subscriber and
    # cannot pick delta-vs-full per known version: wire-v2 frames would
    # be encoded, paid for, and then discarded. The embedding server
    # reads this and skips the encoder entirely on this plane.
    serves_full_bundles_only = True

    def __init__(self, lib_path: str, bind_addr: str,
                 idle_timeout_s: float = 30.0):
        super().__init__(lib_path, bind_addr, idle_timeout_s=idle_timeout_s)

    @property
    def idle_timeout_s(self) -> float:
        return self._idle_timeout_ms / 1000.0

    @idle_timeout_s.setter
    def idle_timeout_s(self, value: float) -> None:
        # tests/embedders tune the long-poll window after construction
        self._idle_timeout_ms = int(value * 1000)
        self._fn("set_idle_timeout")(self._handle, self._idle_timeout_ms)

    def publish_model(self, version: int, bundle_bytes: bytes,
                      handshake_bytes: bytes | None = None) -> None:
        """The native gRPC plane serves ClientPoll long-polls from the
        C++ stored model, which cannot pick delta-vs-full per subscriber
        — so this plane stays full-bundle: a wire-v2 publish stores and
        wakes pollers with the v1 ``handshake_bytes`` (agents decode it
        through the same sniffing path)."""
        blob = handshake_bytes if handshake_bytes is not None else bundle_bytes
        data = _buf(blob)
        self._fn("broadcast")(self._handle, version, data, len(blob))
