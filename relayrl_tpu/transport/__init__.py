"""Transport plane (ref layer L4, SURVEY.md §1): ZMQ, gRPC, native C++.

``make_server_transport`` / ``make_agent_transport`` resolve a backend by
name the way the reference's wrappers pick ZMQ (default) vs gRPC
(training_server_wrapper.rs:329-379, agent_wrapper.rs:231-270).
"""

from __future__ import annotations

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.transport.base import (
    AgentTransport,
    ServerTransport,
    pack_model_frame,
    pack_trajectory_envelope,
    unpack_model_frame,
    unpack_trajectory_envelope,
)
from relayrl_tpu.transport.probe import (
    ProtocolMismatchError,
    parse_host_port,
    probe_endpoint,
)


def _resolve_auto() -> str:
    """``auto`` -> native framed-TCP when the C++ core loads, else zmq.

    The 64-actor shootout (benches/results/transport_scale.json) shows
    native ~1.5x faster than pyzmq on model fan-out; ``zmq`` stays the
    DEFAULT for reference parity. On the *server* (bind) side this local
    resolution defines the fleet's protocol; on the agent side ``auto``
    additionally *negotiates* against the live server via
    :func:`probe_endpoint`, so a mixed fleet converges on whatever the
    server actually speaks instead of splitting protocols.
    """
    from relayrl_tpu.transport.native_backend import native_available

    return "native" if native_available() else "zmq"


# Conclusive probe verdicts, cached per endpoint with a short TTL: a
# process that builds many agents against one server (soaks, benches,
# vector envs) pays the probe round-trip once, while a server swapped to
# a different backend on the same port ages out quickly. Inconclusive
# verdicts are never cached — the server may simply not be up yet — and
# a mismatch is never raised off a cached verdict (see
# _verify_agent_protocol), only off a fresh probe.
_PROBE_TTL_S = 10.0
_probe_cache: dict[tuple[str, int], tuple[str, float]] = {}


def _probe_cached(host: str, port: int, timeout_s: float = 0.75,
                  refresh: bool = False) -> tuple[str, bool]:
    """Returns ``(verdict, from_cache)`` so callers can tell a fresh probe
    from a cache hit (mismatch errors must never rest on a stale entry)."""
    import time

    hit = _probe_cache.get((host, port))
    if hit is not None and not refresh and time.monotonic() - hit[1] < _PROBE_TTL_S:
        return hit[0], True
    verdict = probe_endpoint(host, port, timeout_s=timeout_s)
    if verdict in ("zmq", "native", "grpc"):
        _probe_cache[(host, port)] = (verdict, time.monotonic())
    else:
        _probe_cache.pop((host, port), None)
    return verdict, False


_KNOWN_TYPES = ("zmq", "grpc", "native")


def _agent_handshake_addr(server_type: str, config: ConfigLoader,
                          overrides: dict) -> str:
    """The single source of each backend's agent-side handshake address —
    used both by the pre-flight probe and by the constructor branches in
    :func:`make_agent_transport`, so the probe can never verify an address
    the transport doesn't actually connect to."""
    if server_type == "zmq":
        return overrides.get("agent_listener_addr",
                             config.get_agent_listener().address)
    if server_type == "grpc":
        return overrides.get("server_addr", config.get_train_server().host_port)
    return overrides.get("server_addr", config.get_traj_server().host_port)


def _negotiate_agent_auto(config: ConfigLoader, overrides: dict,
                          retry_window_s: float = 3.0) -> str:
    """Agent-side ``auto``: probe each candidate backend's handshake
    endpoint and pick the one whose server is actually answering.

    Retries the probe sweep for ``retry_window_s`` (fleets commonly start
    agents before the server finishes binding). If every probe stays
    inconclusive, falls back to local .so resolution — which, in a mixed
    fleet whose server comes up later on a different protocol, can still
    split; the fallback is printed loudly so that case leaves a breadcrumb,
    and pinning ``server_type`` explicitly avoids it entirely."""
    import time

    from relayrl_tpu.transport.native_backend import native_available

    candidates = ["native", "zmq", "grpc"] if native_available() else \
                 ["zmq", "native", "grpc"]
    deadline = time.monotonic() + retry_window_s
    while True:
        verdicts: dict[tuple[str, int], str] = {}
        for cand in candidates:
            host, port = parse_host_port(
                _agent_handshake_addr(cand, config, overrides))
            verdict = verdicts.get((host, port))
            if verdict is None:
                verdict, _ = _probe_cached(host, port)
                verdicts[(host, port)] = verdict
            if verdict == cand:
                print(f"[Transport] auto -> {cand} (negotiated: server at "
                      f"{host}:{port} speaks {verdict})", flush=True)
                return cand
        if time.monotonic() >= deadline:
            break
        time.sleep(0.25)
    fallback = _resolve_auto()
    print(f"[Transport] auto -> {fallback} (LOCAL FALLBACK — no server "
          f"answered the protocol probes ({verdicts}); if the server comes "
          f"up on a different backend this agent will time out. Pin "
          f"server_type explicitly to avoid auto in mixed fleets.)",
          flush=True)
    return fallback


def _verify_agent_protocol(server_type: str, config: ConfigLoader,
                           overrides: dict) -> None:
    """Fail fast when the server at the configured endpoint demonstrably
    speaks a different protocol (instead of a silent handshake timeout)."""
    host, port = parse_host_port(
        _agent_handshake_addr(server_type, config, overrides))
    verdict, from_cache = _probe_cached(host, port)
    if (from_cache and verdict in ("zmq", "native", "grpc")
            and verdict != server_type):
        # Never error off a (possibly stale) cache entry.
        verdict, _ = _probe_cached(host, port, refresh=True)
    if verdict in ("zmq", "native", "grpc") and verdict != server_type:
        raise ProtocolMismatchError(
            f"server at {host}:{port} speaks {verdict!r} but this agent is "
            f"configured with server_type={server_type!r} — fix server_type "
            f"on one end (or use server_type='auto' on agents to negotiate)")


def make_server_transport(server_type: str, config: ConfigLoader,
                          **overrides) -> ServerTransport:
    server_type = (server_type or "zmq").lower()
    if server_type == "auto":
        server_type = _resolve_auto()
        print(f"[Transport] auto -> {server_type} (server bind side)",
              flush=True)
    transport_params = config.get_transport_params()
    chunk_bytes = overrides.get("chunk_bytes",
                                transport_params["chunk_bytes"])
    if int(transport_params.get("wire_version", 2)) < 2:
        # wire_version=1 is the rolling-compat escape hatch for PRE-v2
        # actors — which have no chunk reassembler, so chunk frames
        # would break exactly the fleet that knob serves.
        chunk_bytes = 0
    if server_type == "zmq":
        from relayrl_tpu.transport.zmq_backend import ZmqServerTransport

        return ZmqServerTransport(
            agent_listener_addr=overrides.get(
                "agent_listener_addr", config.get_agent_listener().address),
            trajectory_addr=overrides.get(
                "trajectory_addr", config.get_traj_server().address),
            model_pub_addr=overrides.get(
                "model_pub_addr", config.get_train_server().address),
            chunk_bytes=chunk_bytes,
        )
    if server_type == "grpc":
        bind_addr = overrides.get("bind_addr",
                                  config.get_train_server().host_port)
        idle_s = config.get_grpc_idle_timeout_s()
        # The native C++ gRPC server (grpc_server.cc: HTTP/2 + the two
        # RPCs, EventHub batch decode) is the default when the library is
        # built — same wire protocol, so grpcio agents are unaffected.
        # native_grpc=False pins the pure-grpcio fallback.
        from relayrl_tpu.transport.native_backend import native_available

        if overrides.get("native_grpc", True) and native_available():
            from relayrl_tpu.transport.native_backend import (
                NativeGrpcServerTransport,
            )

            return NativeGrpcServerTransport(bind_addr=bind_addr,
                                             idle_timeout_s=idle_s)
        from relayrl_tpu.transport.grpc_backend import GrpcServerTransport

        return GrpcServerTransport(bind_addr=bind_addr, idle_timeout_s=idle_s)
    if server_type == "native":
        from relayrl_tpu.transport.native_backend import NativeServerTransport

        return NativeServerTransport(
            bind_addr=overrides.get("bind_addr", config.get_traj_server().host_port),
            chunk_bytes=chunk_bytes,
        )
    raise ValueError(f"unknown server_type {server_type!r} (zmq|grpc|native|auto)")


def make_agent_transport(server_type: str, config: ConfigLoader,
                         **overrides) -> AgentTransport:
    """Build an agent transport. ``server_type="auto"`` negotiates the
    protocol against the live server; an explicit type is verified with a
    quick probe so a mismatched fleet errors at construction
    (:class:`ProtocolMismatchError`) rather than timing out on
    ``fetch_model``. Pass ``probe=False`` to skip the pre-flight check.
    """
    server_type = (server_type or "zmq").lower()
    if server_type != "auto" and server_type not in _KNOWN_TYPES:
        raise ValueError(
            f"unknown server_type {server_type!r} (zmq|grpc|native|auto)")
    should_probe = overrides.pop("probe", True)
    # Agents that start long before the server binds can spend more of
    # their handshake budget negotiating instead of hitting the 3s default
    # and splitting a mixed fleet on the local fallback (advisor r3).
    negotiate_window_s = float(overrides.pop("negotiate_window_s", 3.0))
    if server_type == "auto":
        server_type = (_negotiate_agent_auto(
                           config, overrides,
                           retry_window_s=negotiate_window_s)
                       if should_probe else _resolve_auto())
    elif should_probe:
        _verify_agent_protocol(server_type, config, overrides)
    # transport.retry: the unified handshake/connect backoff policy all
    # three backends share (transport/retry.py); an explicit override
    # dict wins over the config section.
    retry_cfg = overrides.get("retry", config.get_transport_params()["retry"])
    if server_type == "zmq":
        from relayrl_tpu.transport.zmq_backend import ZmqAgentTransport

        return ZmqAgentTransport(
            agent_listener_addr=_agent_handshake_addr("zmq", config, overrides),
            trajectory_addr=overrides.get(
                "trajectory_addr", config.get_traj_server().address),
            model_sub_addr=overrides.get(
                "model_sub_addr", config.get_train_server().address),
            identity=overrides.get("identity"),
            retry=retry_cfg,
        )
    if server_type == "grpc":
        from relayrl_tpu.transport.grpc_backend import GrpcAgentTransport

        return GrpcAgentTransport(
            server_addr=_agent_handshake_addr("grpc", config, overrides),
            identity=overrides.get("identity"),
            poll_timeout_s=config.get_grpc_idle_timeout_s() + 5.0,
            retry=retry_cfg,
        )
    from relayrl_tpu.transport.native_backend import NativeAgentTransport

    return NativeAgentTransport(
        server_addr=_agent_handshake_addr("native", config, overrides),
        identity=overrides.get("identity"),
        # transport.heartbeat_s config knob (was hard-coded 5.0 in
        # start_model_listener); an explicit override wins.
        heartbeat_s=overrides.get(
            "heartbeat_s", config.get_transport_params()["heartbeat_s"]),
        retry=retry_cfg,
    )


__all__ = [
    "ServerTransport",
    "AgentTransport",
    "ProtocolMismatchError",
    "probe_endpoint",
    "make_server_transport",
    "make_agent_transport",
    "pack_model_frame",
    "unpack_model_frame",
    "pack_trajectory_envelope",
    "unpack_trajectory_envelope",
]
