"""Transport plane (ref layer L4, SURVEY.md §1): ZMQ, gRPC, native C++.

``make_server_transport`` / ``make_agent_transport`` resolve a backend by
name the way the reference's wrappers pick ZMQ (default) vs gRPC
(training_server_wrapper.rs:329-379, agent_wrapper.rs:231-270).
"""

from __future__ import annotations

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.transport.base import (
    AgentTransport,
    ServerTransport,
    pack_model_frame,
    pack_trajectory_envelope,
    unpack_model_frame,
    unpack_trajectory_envelope,
)


def _resolve_auto() -> str:
    """``auto`` -> native framed-TCP when the C++ core loads, else zmq.

    The 64-actor shootout (benches/results/transport_scale.json) shows
    native ~1.5x faster than pyzmq on model fan-out and tied on ingest
    (both saturate the same Python-callback ceiling). ``zmq`` stays the
    DEFAULT for reference parity.

    WARNING: ``auto`` resolves PER PROCESS from local .so availability —
    both ends must land on the same wire protocol, so use it only in
    homogeneous deployments where every host ships (or lacks) the .so
    identically. A mixed fleet on ``auto`` splits protocols and the
    mismatched agents time out on ``fetch_model``; for mixed fleets pin
    ``server_type`` explicitly on every process.
    """
    from relayrl_tpu.transport.native_backend import native_available

    return "native" if native_available() else "zmq"


def make_server_transport(server_type: str, config: ConfigLoader,
                          **overrides) -> ServerTransport:
    server_type = (server_type or "zmq").lower()
    if server_type == "auto":
        server_type = _resolve_auto()
    if server_type == "zmq":
        from relayrl_tpu.transport.zmq_backend import ZmqServerTransport

        return ZmqServerTransport(
            agent_listener_addr=overrides.get(
                "agent_listener_addr", config.get_agent_listener().address),
            trajectory_addr=overrides.get(
                "trajectory_addr", config.get_traj_server().address),
            model_pub_addr=overrides.get(
                "model_pub_addr", config.get_train_server().address),
        )
    if server_type == "grpc":
        from relayrl_tpu.transport.grpc_backend import GrpcServerTransport

        return GrpcServerTransport(
            bind_addr=overrides.get("bind_addr", config.get_train_server().host_port),
            idle_timeout_s=config.get_grpc_idle_timeout_s(),
        )
    if server_type == "native":
        from relayrl_tpu.transport.native_backend import NativeServerTransport

        return NativeServerTransport(
            bind_addr=overrides.get("bind_addr", config.get_traj_server().host_port),
        )
    raise ValueError(f"unknown server_type {server_type!r} (zmq|grpc|native|auto)")


def make_agent_transport(server_type: str, config: ConfigLoader,
                         **overrides) -> AgentTransport:
    server_type = (server_type or "zmq").lower()
    if server_type == "auto":
        server_type = _resolve_auto()
    if server_type == "zmq":
        from relayrl_tpu.transport.zmq_backend import ZmqAgentTransport

        return ZmqAgentTransport(
            agent_listener_addr=overrides.get(
                "agent_listener_addr", config.get_agent_listener().address),
            trajectory_addr=overrides.get(
                "trajectory_addr", config.get_traj_server().address),
            model_sub_addr=overrides.get(
                "model_sub_addr", config.get_train_server().address),
            identity=overrides.get("identity"),
        )
    if server_type == "grpc":
        from relayrl_tpu.transport.grpc_backend import GrpcAgentTransport

        return GrpcAgentTransport(
            server_addr=overrides.get("server_addr", config.get_train_server().host_port),
            identity=overrides.get("identity"),
            poll_timeout_s=config.get_grpc_idle_timeout_s() + 5.0,
        )
    if server_type == "native":
        from relayrl_tpu.transport.native_backend import NativeAgentTransport

        return NativeAgentTransport(
            server_addr=overrides.get("server_addr", config.get_traj_server().host_port),
            identity=overrides.get("identity"),
        )
    raise ValueError(f"unknown server_type {server_type!r} (zmq|grpc|native|auto)")


__all__ = [
    "ServerTransport",
    "AgentTransport",
    "make_server_transport",
    "make_agent_transport",
    "pack_model_frame",
    "unpack_model_frame",
    "pack_trajectory_envelope",
    "unpack_trajectory_envelope",
]
