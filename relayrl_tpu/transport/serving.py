"""Serving wire plane: the request/response action channel for thin-client
actors (the disaggregated batched-inference tier, ROADMAP item 2).

The trajectory/model planes are one-way (PUSH ingest, PUB model fan-out);
batched inference needs the missing fourth lane — a request/response pair
per action. TorchBeast's dynamic-batching server (arXiv:1910.03552) and
Podracer's Sebulba split (arXiv:2104.06272) are the exemplars: actors ship
observations, the service closes latency-bounded batches, one policy
dispatch answers everyone.

Backends:

* **zmq** — a dedicated ROUTER (service) / DEALER (client) pair on the
  ``server.inference_server`` endpoint. Replies are produced on the
  batch-worker thread but zmq sockets are single-threaded, so the worker
  hands them to the ROUTER loop over an inproc PUSH/PULL pipe (the same
  pattern libzmq documents for cross-thread sends).
* **grpc** — an in-band ``GetActions`` unary RPC on the existing service
  (pure-grpcio ``GrpcServerTransport`` only: the RPC thread blocks until
  its batch executes, the thread pool bounds concurrent clients). The
  native C++ gRPC server does not speak this RPC — those fleets use the
  zmq plane below.
* **native** — passthrough: the framed-TCP core carries trajectories and
  models; inference rides the zmq ROUTER plane bound alongside it (the
  service binds it regardless of the fleet's trajectory transport).

Wire codec (msgpack, raw array bytes — no per-element boxing):

* request  ``{id, req, key, kd, obs, os, od, mask?, ms?}`` — the client's
  CURRENT PRNG key rides the request and the service splits it inside the
  jitted dispatch (exactly ``_fuse_rng``'s composition), returning the
  carried-forward key in the reply. That is what makes a served action
  stream bit-identical to a local PolicyActor holding the same key.
* reply    ``{req, code: 1, ver, act, as, ad, key, aux}`` with ``aux``
  mapping name → ``[bytes, shape, dtype]``.
* nack     ``{req, code, error, retry_after_s}`` — ``code`` reuses the
  typed ingest verdicts (``base.NACK_OVERLOADED`` when the batching queue
  is at ``serving.queue_limit``; the client honors ``retry_after_s``
  without charging its circuit breaker, mirroring the spool's nack
  handling).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

import msgpack
import numpy as np

from relayrl_tpu.transport.base import NACK_OK


def _pack_array(arr: np.ndarray) -> tuple[bytes, list, str]:
    arr = np.asarray(arr)
    # Shape captured BEFORE ascontiguousarray: it promotes 0-d arrays to
    # 1-d, and scalar actions/aux must round-trip as exact 0-d ndarrays
    # (the vector-host wire-dtype lesson applies to shape too).
    shape = list(arr.shape)
    return np.ascontiguousarray(arr).tobytes(), shape, str(arr.dtype)


def _unpack_array(buf: bytes, shape: list, dtype: str) -> np.ndarray:
    # .copy(): frombuffer views are read-only and alias the wire frame;
    # ActionRecords built from them must own their memory.
    return np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape).copy()


def pack_infer_request(agent_id: str, req_id: int, key: np.ndarray,
                       obs: np.ndarray, mask: np.ndarray | None) -> bytes:
    kb, _, kd = _pack_array(key)
    ob, oshape, od = _pack_array(obs)
    req = {"id": agent_id, "req": int(req_id),
           "key": kb, "kd": kd, "obs": ob, "os": oshape, "od": od}
    if mask is not None:
        mb, mshape, _ = _pack_array(np.asarray(mask, np.float32))
        req["mask"] = mb
        req["ms"] = mshape
    return msgpack.packb(req, use_bin_type=True)


def unpack_infer_request(buf: bytes) -> dict:
    """Decoded request: ``{id, req, key, obs, mask}`` with numpy arrays.
    Raises the transport plane's droppable error classes on malformed
    frames (ValueError/KeyError/TypeError)."""
    req = msgpack.unpackb(buf, raw=False)
    key = np.frombuffer(req["key"], dtype=np.dtype(req.get("kd", "uint32")))
    out = {
        "id": str(req.get("id", "?")),
        "req": int(req["req"]),
        "key": key.copy(),
        "obs": _unpack_array(req["obs"], req["os"], req["od"]),
        "mask": None,
    }
    if req.get("mask") is not None:
        out["mask"] = _unpack_array(req["mask"], req["ms"], "float32")
    return out


def pack_action_reply(req_id: int, version: int, act: np.ndarray,
                      next_key: np.ndarray, aux: dict) -> bytes:
    ab, ashape, ad = _pack_array(act)
    reply = {"req": int(req_id), "code": NACK_OK, "ver": int(version),
             "act": ab, "as": ashape, "ad": ad,
             "key": _pack_array(next_key)[0],
             "aux": {k: list(_pack_array(v)) for k, v in aux.items()}}
    return msgpack.packb(reply, use_bin_type=True)


def pack_infer_nack(req_id: int, code: int, reason: str,
                    retry_after_s: float = 0.0) -> bytes:
    return msgpack.packb({"req": int(req_id), "code": int(code),
                          "error": str(reason),
                          "retry_after_s": float(retry_after_s)},
                         use_bin_type=True)


def unpack_infer_reply(buf: bytes) -> dict:
    """Decoded reply: ``{req, code, ...}`` — on code 1 additionally
    ``ver``, ``act`` (ndarray), ``key`` (the carried-forward PRNG key
    bytes, kept raw: the client round-trips them verbatim), ``aux``
    (name → 0-d/array ndarray)."""
    reply = msgpack.unpackb(buf, raw=False)
    out = {"req": int(reply.get("req", -1)), "code": int(reply.get("code", 0)),
           "error": str(reply.get("error") or ""),
           "retry_after_s": float(reply.get("retry_after_s") or 0.0)}
    if out["code"] == NACK_OK and "act" in reply:
        out["ver"] = int(reply.get("ver", -1))
        out["act"] = _unpack_array(reply["act"], reply["as"], reply["ad"])
        out["key"] = reply["key"]
        out["aux"] = {k: _unpack_array(*v)
                      for k, v in (reply.get("aux") or {}).items()}
    return out


# -- server side ------------------------------------------------------------

class ZmqServingPlane:
    """ROUTER request loop + inproc reply pipe for the InferenceService.

    ``on_request(payload: bytes, reply: Callable[[bytes], None])`` runs on
    the ROUTER loop thread (decode + enqueue only — the batching queue is
    the service's); ``reply`` may be called from ANY thread (the batch
    worker) and forwards the encoded reply to the requesting DEALER
    through the inproc pipe, so the ROUTER socket is only ever touched by
    its own loop thread.
    """

    def __init__(self, addr: str,
                 on_request: Callable[[bytes, Callable[[bytes], None]], None]):
        import zmq

        self._zmq = zmq
        self._addr = addr
        self.on_request = on_request
        self._ctx = zmq.Context.instance()
        self._inproc = f"inproc://relayrl-serving-{id(self):x}"
        self._router: object | None = None
        self._pull: object | None = None
        self._push: object | None = None
        self._push_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        zmq = self._zmq
        from relayrl_tpu.transport.zmq_backend import _bind_with_retry

        self._stop.clear()
        self._router = self._ctx.socket(zmq.ROUTER)
        _bind_with_retry(self._router, self._addr)
        # inproc: the PULL must bind before any PUSH connects.
        self._pull = self._ctx.socket(zmq.PULL)
        self._pull.bind(self._inproc)
        self._push = self._ctx.socket(zmq.PUSH)
        self._push.connect(self._inproc)
        self._thread = threading.Thread(
            target=self._loop, name="zmq-serving-router", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Forward any replies still in the inproc pipe (the shutdown
        # nacks the service just sent) before tearing the ROUTER down —
        # the loop thread has exited, so this thread owns the sockets.
        if self._pull is not None and self._router is not None:
            zmq = self._zmq
            try:
                while self._pull.poll(0):
                    self._router.send_multipart(
                        self._pull.recv_multipart(zmq.NOBLOCK))
            except zmq.ZMQError:
                pass
        for sock in (self._router, self._pull, self._push):
            if sock is not None:
                sock.close(linger=0)
        self._router = self._pull = self._push = None

    def _reply_fn(self, identity: bytes) -> Callable[[bytes], None]:
        def reply(payload: bytes) -> None:
            # The push socket is shared across batch-worker callers; the
            # lock serializes whole sends (the ZmqAgentTransport
            # _push_lock precedent). A reply after stop() drops silently
            # — the client's retry owns that window.
            with self._push_lock:
                if self._push is not None:
                    self._push.send_multipart([identity, payload])
        return reply

    def _loop(self) -> None:
        zmq = self._zmq
        from relayrl_tpu.transport.base import swallow_decode_error

        poller = zmq.Poller()
        poller.register(self._router, zmq.POLLIN)
        poller.register(self._pull, zmq.POLLIN)
        while not self._stop.is_set():
            events = dict(poller.poll(100))
            if self._pull in events:
                # Drain every queued reply before the next request sweep:
                # replies are latency-critical (the client is blocked on
                # them) and cheap (one forward per reply).
                while True:
                    try:
                        frames = self._pull.recv_multipart(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    self._router.send_multipart(frames)
            if self._router in events:
                frames = self._router.recv_multipart()
                if len(frames) < 2:
                    continue
                identity, payload = frames[0], frames[-1]
                try:
                    self.on_request(payload, self._reply_fn(identity))
                except Exception as e:
                    swallow_decode_error("zmq", "serving_request", e)


# -- client side ------------------------------------------------------------

class ZmqServingClient:
    """One DEALER against the service's ROUTER. ``request`` is strictly
    request/response per caller (the thin client's env loop is serial);
    stale replies — answers to earlier attempts that timed out client-side
    — are discarded by request-id match, so a retry can never consume its
    predecessor's action."""

    def __init__(self, addr: str, identity: str | None = None):
        import os
        import secrets

        import zmq

        self._zmq = zmq
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(
            zmq.IDENTITY,
            (identity or f"INFER-{os.getpid()}{secrets.token_hex(4)}")
            .encode())
        self._sock.connect(addr)
        self._lock = threading.Lock()

    def request(self, payload: bytes, req_id: int,
                timeout_s: float) -> dict:
        """Send one request and wait for ITS reply (req-id matched).
        Raises TimeoutError when nothing matching arrives in time."""
        zmq = self._zmq
        with self._lock:
            # Drain leftovers from PREVIOUS requests before sending:
            # a late reply (or req=-1 nack) to an attempt that already
            # timed out must not be adopted by THIS request — clearing
            # the buffer first shrinks the -1 branch's ambiguity window
            # to replies generated after this send.
            try:
                while self._sock.poll(0):
                    # NOBLOCK recv after a 0-timeout poll: returns
                    # immediately by construction, never blocks the lock.
                    self._sock.recv(zmq.NOBLOCK)  # jaxlint: disable=CONC01
            except zmq.ZMQError:
                pass
            self._sock.send(payload)
            deadline = time.monotonic() + timeout_s
            poller = zmq.Poller()
            poller.register(self._sock, zmq.POLLIN)
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"inference reply not received in {timeout_s:.2f}s")
                if not dict(poller.poll(max(1, int(remaining * 1000)))):
                    continue
                # deliberate blocking-under-lock: the lock EXISTS to
                # serialize whole request/reply exchanges on the
                # non-thread-safe DEALER (the _dealer_request precedent);
                # poll() above guarantees recv returns immediately and
                # the hold is bounded by the caller's timeout_s.
                raw = self._sock.recv()  # jaxlint: disable=CONC01
                try:
                    reply = unpack_infer_reply(raw)
                except Exception:
                    continue  # corrupt frame: wait out the deadline
                if reply["req"] == req_id:
                    return reply
                if reply["req"] == -1 and reply["code"] != NACK_OK:
                    # The service could not decode the request, so its
                    # error/unavailable reply carries req=-1. This
                    # client is strictly one-request-outstanding, so the
                    # verdict is unambiguously OURS — returning it makes
                    # a corrupted request a fast error-reply retry
                    # (the agent.infer chaos contract) instead of a full
                    # timeout + an unearned breaker charge.
                    return reply
                # stale reply from a timed-out earlier attempt: discard

    def close(self) -> None:
        self._sock.close(linger=0)


class GrpcServingClient:
    """In-band ``GetActions`` unary RPC on the agent's existing channel
    (pure-grpcio fleets). The request/response pairing is the RPC itself,
    so there is no stale-reply window to filter."""

    def __init__(self, agent_transport):
        import grpc

        self._grpc = grpc
        self._transport = agent_transport
        self._stub = None
        self._stub_channel = None

    def _get_stub(self):
        # The agent transport may rebuild its channel after a persistent
        # break (_rebuild_channel); re-derive the stub when it did.
        channel = self._transport._channel
        if self._stub is None or self._stub_channel is not channel:
            self._stub = channel.unary_unary(
                "/relayrl.RelayRLRoute/GetActions",
                request_serializer=lambda x: x,
                response_deserializer=lambda x: x)
            self._stub_channel = channel
        return self._stub

    def request(self, payload: bytes, req_id: int,
                timeout_s: float) -> dict:
        grpc = self._grpc
        try:
            raw = self._get_stub()(payload, timeout=timeout_s)
        except grpc.RpcError as e:
            code = getattr(e, "code", lambda: None)()
            if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise TimeoutError(
                    f"inference RPC deadline ({timeout_s:.2f}s)") from None
            if code == grpc.StatusCode.UNIMPLEMENTED:
                # PERMANENT: this server has no GetActions RPC at all —
                # the native C++ gRPC core. Retrying a misconfiguration
                # would bury it in a deadline exhaustion (the
                # NACK_UNAVAILABLE rationale); RuntimeError passes
                # through the client's retry loop uncaught.
                raise RuntimeError(
                    "inference unavailable: this gRPC server does not "
                    "implement GetActions (native C++ core?) — serve "
                    "inference on the zmq plane (serving_plane=\"zmq\") "
                    "or run the pure-grpcio server") from None
            raise ConnectionError(f"inference RPC failed: {e}") from None
        return unpack_infer_reply(raw)

    def close(self) -> None:
        pass  # the agent transport owns the channel


def make_serving_client(server_type: str, config, transport=None,
                        **overrides):
    """The thin client's action channel for a fleet transport kind:
    gRPC fleets ride the in-band ``GetActions`` RPC on the agent's
    existing channel; zmq and native fleets use the dedicated zmq
    DEALER against ``server.inference_server`` (native passthrough —
    the C++ core has no request/response action RPC). Pass
    ``serving_plane="zmq"`` to force the zmq plane on a grpc fleet whose
    server runs the native C++ gRPC core (it does not speak GetActions)."""
    plane = overrides.get("serving_plane") or (
        "grpc" if server_type == "grpc" else "zmq")
    if plane == "grpc":
        if transport is None or not hasattr(transport, "_channel"):
            raise ValueError(
                "grpc serving plane needs the agent's GrpcAgentTransport")
        return GrpcServingClient(transport)
    addr = overrides.get("serving_addr")
    if addr is None:
        addr = config.get_inference_server().address
    return ZmqServingClient(addr, identity=overrides.get("identity"))


__all__ = [
    "pack_infer_request", "unpack_infer_request", "pack_action_reply",
    "pack_infer_nack", "unpack_infer_reply", "ZmqServingPlane",
    "ZmqServingClient", "GrpcServingClient", "make_serving_client",
]
