"""Serving wire plane: the request/response action channel for thin-client
actors (the disaggregated batched-inference tier, ROADMAP item 2).

The trajectory/model planes are one-way (PUSH ingest, PUB model fan-out);
batched inference needs the missing fourth lane — a request/response pair
per action. TorchBeast's dynamic-batching server (arXiv:1910.03552) and
Podracer's Sebulba split (arXiv:2104.06272) are the exemplars: actors ship
observations, the service closes latency-bounded batches, one policy
dispatch answers everyone.

Backends:

* **zmq** — a dedicated ROUTER (service) / DEALER (client) pair on the
  ``server.inference_server`` endpoint. Replies are produced on the
  batch-worker thread but zmq sockets are single-threaded, so the worker
  hands them to the ROUTER loop over an inproc PUSH/PULL pipe (the same
  pattern libzmq documents for cross-thread sends).
* **grpc** — an in-band ``GetActions`` unary RPC on the existing service
  (pure-grpcio ``GrpcServerTransport`` only: the RPC thread blocks until
  its batch executes, the thread pool bounds concurrent clients). The
  native C++ gRPC server does not speak this RPC — those fleets use the
  zmq plane below.
* **native** — passthrough: the framed-TCP core carries trajectories and
  models; inference rides the zmq ROUTER plane bound alongside it (the
  service binds it regardless of the fleet's trajectory transport).

Wire codec (msgpack, raw array bytes — no per-element boxing):

* request  ``{id, req, key, kd, obs, os, od, mask?, ms?}`` — the client's
  CURRENT PRNG key rides the request and the service splits it inside the
  jitted dispatch (exactly ``_fuse_rng``'s composition), returning the
  carried-forward key in the reply. That is what makes a served action
  stream bit-identical to a local PolicyActor holding the same key.
* reply    ``{req, code: 1, ver, act, as, ad, key, aux}`` with ``aux``
  mapping name → ``[bytes, shape, dtype]``.
* nack     ``{req, code, error, retry_after_s}`` — ``code`` reuses the
  typed ingest verdicts (``base.NACK_OVERLOADED`` when the batching queue
  is at ``serving.queue_limit``; the client honors ``retry_after_s``
  without charging its circuit breaker, mirroring the spool's nack
  handling).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

import msgpack
import numpy as np

from relayrl_tpu.transport.base import NACK_OK


def _pack_array(arr: np.ndarray) -> tuple[bytes, list, str]:
    arr = np.asarray(arr)
    # Shape captured BEFORE ascontiguousarray: it promotes 0-d arrays to
    # 1-d, and scalar actions/aux must round-trip as exact 0-d ndarrays
    # (the vector-host wire-dtype lesson applies to shape too).
    shape = list(arr.shape)
    return np.ascontiguousarray(arr).tobytes(), shape, str(arr.dtype)


def _unpack_array(buf: bytes, shape: list, dtype: str) -> np.ndarray:
    # .copy(): frombuffer views are read-only and alias the wire frame;
    # ActionRecords built from them must own their memory.
    return np.frombuffer(buf, dtype=np.dtype(dtype)).reshape(shape).copy()


def pack_infer_request(agent_id: str, req_id: int, key: np.ndarray,
                       obs: np.ndarray, mask: np.ndarray | None,
                       session: str | None = None, reset: bool = False,
                       window: np.ndarray | None = None,
                       step: int = 0) -> bytes:
    """``session``/``reset``/``window`` are the serving-v2 per-session
    fields (absent on the v1 wire — old clients and old services
    interoperate): ``session`` names the server-side rolling window a
    sequence policy serves from; ``reset`` marks an episode start (the
    service zeroes the window BEFORE pushing this observation);
    ``window`` is the resync payload — the episode's prior observations
    ``[n, obs_dim]`` (oldest first, excluding the current ``obs``) that
    rebuilds the session after a NACK_SESSION_EVICTED or on a fresh
    replica after re-route."""
    kb, _, kd = _pack_array(key)
    ob, oshape, od = _pack_array(obs)
    req = {"id": agent_id, "req": int(req_id),
           "key": kb, "kd": kd, "obs": ob, "os": oshape, "od": od}
    if mask is not None:
        mb, mshape, _ = _pack_array(np.asarray(mask, np.float32))
        req["mask"] = mb
        req["ms"] = mshape
    if session is not None:
        req["sid"] = str(session)
        # Per-episode step counter (1-based, counting this observation):
        # the service's push-idempotency key. A client retry of a served
        # request whose reply was lost arrives with the SAME stp — the
        # service recomputes from the already-pushed window instead of
        # pushing the observation twice (same client key → bit-identical
        # recompute), so at-least-once delivery cannot corrupt state.
        req["stp"] = int(step)
    if reset:
        req["rst"] = True
    if window is not None:
        wb, wshape, _ = _pack_array(np.asarray(window, np.float32))
        req["win"] = wb
        req["ws"] = wshape
    return msgpack.packb(req, use_bin_type=True)


def unpack_infer_request(buf: bytes) -> dict:
    """Decoded request: ``{id, req, key, obs, mask, sid, rst, win}`` with
    numpy arrays (``sid``/``win`` None and ``rst`` False on the v1 wire).
    Raises the transport plane's droppable error classes on malformed
    frames (ValueError/KeyError/TypeError)."""
    return _infer_request_fields(msgpack.unpackb(buf, raw=False))


def _infer_request_fields(req: dict) -> dict:
    key = np.frombuffer(req["key"], dtype=np.dtype(req.get("kd", "uint32")))
    out = {
        "id": str(req.get("id", "?")),
        "req": int(req["req"]),
        "key": key.copy(),
        "obs": _unpack_array(req["obs"], req["os"], req["od"]),
        "mask": None,
        "sid": None if req.get("sid") is None else str(req["sid"]),
        "rst": bool(req.get("rst", False)),
        "stp": int(req.get("stp", 0)),
        "win": None,
    }
    if req.get("mask") is not None:
        out["mask"] = _unpack_array(req["mask"], req["ms"], "float32")
    if req.get("win") is not None:
        out["win"] = _unpack_array(req["win"], req["ws"], "float32")
    return out


def pack_action_reply(req_id: int, version: int, act: np.ndarray,
                      next_key: np.ndarray, aux: dict,
                      ctx: int | None = None) -> bytes:
    reply = {"req": int(req_id), "code": NACK_OK, "ver": int(version),
             "key": _pack_array(next_key)[0],
             "aux": {k: list(_pack_array(v)) for k, v in aux.items()}}
    ab, ashape, ad = _pack_array(act)
    reply.update({"act": ab, "as": ashape, "ad": ad})
    if ctx is not None:
        # Session-served replies carry the service's window length so
        # the client can bound its resync mirror to exactly the rows a
        # resync could ever need (sequence policies only).
        reply["ctx"] = int(ctx)
    return msgpack.packb(reply, use_bin_type=True)


def pack_infer_nack(req_id: int, code: int, reason: str,
                    retry_after_s: float = 0.0) -> bytes:
    return msgpack.packb({"req": int(req_id), "code": int(code),
                          "error": str(reason),
                          "retry_after_s": float(retry_after_s)},
                         use_bin_type=True)


def unpack_infer_reply(buf: bytes) -> dict:
    """Decoded reply: ``{req, code, ...}`` — on code 1 additionally
    ``ver``, ``act`` (ndarray), ``key`` (the carried-forward PRNG key
    bytes, kept raw: the client round-trips them verbatim), ``aux``
    (name → 0-d/array ndarray)."""
    return _infer_reply_fields(msgpack.unpackb(buf, raw=False))


def _infer_reply_fields(reply: dict) -> dict:
    out = {"req": int(reply.get("req", -1)), "code": int(reply.get("code", 0)),
           "error": str(reply.get("error") or ""),
           "retry_after_s": float(reply.get("retry_after_s") or 0.0)}
    if out["code"] == NACK_OK and "act" in reply:
        out["ver"] = int(reply.get("ver", -1))
        out["act"] = _unpack_array(reply["act"], reply["as"], reply["ad"])
        out["key"] = reply["key"]
        out["aux"] = {k: _unpack_array(*v)
                      for k, v in (reply.get("aux") or {}).items()}
        if reply.get("ctx") is not None:
            out["ctx"] = int(reply["ctx"])
    return out


# -- wave frames (coalesced wire) -------------------------------------------
#
# A multiplexing client's per-step wire cost is dominated by per-request
# overhead — one msgpack round + one socket hop each way per lane
# (~190us/step measured on the bench host, ~40% of the total step
# budget). Pipelining alone cannot reclaim it on a saturated core: there
# is no latency to hide, only work to amortize. Wave frames carry a
# whole homogeneous wave in ONE frame with STACKED tensors (one obs
# block, one key block), and the service coalesces replies the same way
# per dispatched batch — per-lane codec cost drops to near zero while
# the decoded rows stay bit-identical to the single-request wire (the
# parity lock covers both).


def pack_infer_wave(entries: list[dict]) -> bytes:
    """One frame for a wave of lane requests. ``entries`` rows:
    ``{id, req, key, obs, mask, sid, stp, rst}``. The caller guarantees
    homogeneity (same obs shape/dtype, same key dtype, masks all None or
    all present at one shape) and that no row carries a resync window —
    resyncs and retries always ride the single-request wire."""
    keys = np.stack([np.asarray(e["key"]) for e in entries])
    obs = np.stack([np.asarray(e["obs"]) for e in entries])
    kb, ks, kd = _pack_array(keys)
    ob, oshape, od = _pack_array(obs)
    wave = {"wave": 1,
            "reqs": [int(e["req"]) for e in entries],
            "ids": [str(e["id"]) for e in entries],
            "key": kb, "ks": ks, "kd": kd,
            "obs": ob, "os": oshape, "od": od}
    if entries[0].get("mask") is not None:
        mb, mshape, _ = _pack_array(np.stack(
            [np.asarray(e["mask"], np.float32) for e in entries]))
        wave["mask"] = mb
        wave["ms"] = mshape
    if entries[0].get("sid") is not None:
        # Session rows: sid == id on the mux wire (one session per lane
        # sid), so only the step/reset columns ship.
        wave["ses"] = True
        wave["stps"] = [int(e.get("stp", 0)) for e in entries]
        wave["rst"] = [1 if e.get("rst") else 0 for e in entries]
    return msgpack.packb(wave, use_bin_type=True)


def _unpack_infer_wave(req: dict) -> list[dict]:
    keys = _unpack_array(req["key"], req["ks"], req["kd"])
    obs = _unpack_array(req["obs"], req["os"], req["od"])
    masks = None
    if req.get("mask") is not None:
        masks = _unpack_array(req["mask"], req["ms"], "float32")
    ids = [str(s) for s in req["ids"]]
    ses = bool(req.get("ses"))
    stps = req.get("stps") or [0] * len(ids)
    rsts = req.get("rst") or [0] * len(ids)
    # Rows are views of the one decoded (owned) block — downstream
    # writes copy (np.stack at dispatch, window-row assignment), so the
    # shared base is never mutated.
    return [{"id": ids[i], "req": int(req["reqs"][i]),
             "key": keys[i], "obs": obs[i],
             "mask": None if masks is None else masks[i],
             "sid": ids[i] if ses else None,
             "rst": bool(rsts[i]), "stp": int(stps[i]), "win": None}
            for i in range(len(ids))]


def unpack_infer_any(buf: bytes) -> list[dict]:
    """Decode either wire shape into request rows: a wave frame expands
    to its lanes, a single request becomes a one-row list."""
    req = msgpack.unpackb(buf, raw=False)
    if req.get("wave"):
        return _unpack_infer_wave(req)
    return [_infer_request_fields(req)]


def pack_reply_wave(req_ids: list, version: int, acts: np.ndarray,
                    keys: np.ndarray, aux: dict,
                    ctx: int | None = None) -> bytes:
    """One frame answering several batchmates from one wave: stacked
    act/key/aux blocks (first axis = the wave rows), one shared version
    (a dispatch batch is single-model-version by construction)."""
    reply = {"wave": 1, "reqs": [int(r) for r in req_ids],
             "code": NACK_OK, "ver": int(version)}
    ab, ashape, ad = _pack_array(acts)
    kb, ks, kd = _pack_array(keys)
    reply.update({"act": ab, "as": ashape, "ad": ad,
                  "key": kb, "ks": ks, "kd": kd,
                  "aux": {k: list(_pack_array(v)) for k, v in aux.items()}})
    if ctx is not None:
        reply["ctx"] = int(ctx)
    return msgpack.packb(reply, use_bin_type=True)


def _unpack_reply_wave(reply: dict) -> list[dict]:
    acts = _unpack_array(reply["act"], reply["as"], reply["ad"])
    keys = _unpack_array(reply["key"], reply["ks"], reply["kd"])
    aux = {k: _unpack_array(*v)
           for k, v in (reply.get("aux") or {}).items()}
    ctx = reply.get("ctx")
    ver = int(reply.get("ver", -1))
    out = []
    for i in range(len(reply["reqs"])):
        # ``[i, ...]`` keeps 0-d rows as 0-d ndarrays (never numpy
        # scalars) — the single-reply wire's exact dtype contract.
        row = {"req": int(reply["reqs"][i]), "code": NACK_OK,
               "error": "", "retry_after_s": 0.0, "ver": ver,
               "act": acts[i, ...],
               "key": keys[i].tobytes(),
               "aux": {k: v[i, ...] for k, v in aux.items()}}
        if ctx is not None:
            row["ctx"] = int(ctx)
        out.append(row)
    return out


def unpack_reply_any(buf: bytes) -> list[dict]:
    """Decode either reply shape into reply rows (nacks are always
    single frames — only served actions coalesce)."""
    reply = msgpack.unpackb(buf, raw=False)
    if reply.get("wave"):
        return _unpack_reply_wave(reply)
    return [_infer_reply_fields(reply)]


# -- server side ------------------------------------------------------------

class ZmqServingPlane:
    """ROUTER request loop + inproc reply pipe for the InferenceService.

    ``on_request(payload: bytes, reply: Callable[[bytes], None])`` runs on
    the ROUTER loop thread (decode + enqueue only — the batching queue is
    the service's); ``reply`` may be called from ANY thread (the batch
    worker) and forwards the encoded reply to the requesting DEALER
    through the inproc pipe, so the ROUTER socket is only ever touched by
    its own loop thread.
    """

    def __init__(self, addr: str,
                 on_request: Callable[[bytes, Callable[[bytes], None]], None]):
        import zmq

        self._zmq = zmq
        self._addr = addr
        self.on_request = on_request
        self._ctx = zmq.Context.instance()
        self._inproc = f"inproc://relayrl-serving-{id(self):x}"
        self._router: object | None = None
        self._pull: object | None = None
        self._push: object | None = None
        self._push_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> None:
        if self._thread is not None:
            return
        zmq = self._zmq
        from relayrl_tpu.transport.zmq_backend import _bind_with_retry

        self._stop.clear()
        self._router = self._ctx.socket(zmq.ROUTER)
        _bind_with_retry(self._router, self._addr)
        # inproc: the PULL must bind before any PUSH connects.
        self._pull = self._ctx.socket(zmq.PULL)
        self._pull.bind(self._inproc)
        self._push = self._ctx.socket(zmq.PUSH)
        self._push.connect(self._inproc)
        self._thread = threading.Thread(
            target=self._loop, name="zmq-serving-router", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        # Forward any replies still in the inproc pipe (the shutdown
        # nacks the service just sent) before tearing the ROUTER down —
        # the loop thread has exited, so this thread owns the sockets.
        if self._pull is not None and self._router is not None:
            zmq = self._zmq
            try:
                while self._pull.poll(0):
                    self._router.send_multipart(
                        self._pull.recv_multipart(zmq.NOBLOCK))
            except zmq.ZMQError:
                pass
        for sock in (self._router, self._pull, self._push):
            if sock is not None:
                sock.close(linger=0)
        self._router = self._pull = self._push = None

    def _reply_fn(self, identity: bytes) -> Callable[[bytes], None]:
        def reply(payload: bytes) -> None:
            # The push socket is shared across batch-worker callers; the
            # lock serializes whole sends (the ZmqAgentTransport
            # _push_lock precedent). A reply after stop() drops silently
            # — the client's retry owns that window.
            with self._push_lock:
                if self._push is not None:
                    self._push.send_multipart([identity, payload])
        return reply

    def _loop(self) -> None:
        zmq = self._zmq
        from relayrl_tpu.transport.base import swallow_decode_error

        poller = zmq.Poller()
        poller.register(self._router, zmq.POLLIN)
        poller.register(self._pull, zmq.POLLIN)
        while not self._stop.is_set():
            events = dict(poller.poll(100))
            if self._pull in events:
                # Drain every queued reply before the next request sweep:
                # replies are latency-critical (the client is blocked on
                # them) and cheap (one forward per reply).
                while True:
                    try:
                        frames = self._pull.recv_multipart(zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    self._router.send_multipart(frames)
            if self._router in events:
                frames = self._router.recv_multipart()
                if len(frames) < 2:
                    continue
                identity, payload = frames[0], frames[-1]
                try:
                    self.on_request(payload, self._reply_fn(identity))
                except Exception as e:
                    swallow_decode_error("zmq", "serving_request", e)


# -- client side ------------------------------------------------------------

class ZmqServingClient:
    """One DEALER against the service's ROUTER. ``request`` is strictly
    request/response per caller (the thin client's env loop is serial);
    stale replies — answers to earlier attempts that timed out client-side
    — are discarded by request-id match, so a retry can never consume its
    predecessor's action."""

    def __init__(self, addr: str, identity: str | None = None):
        import os
        import secrets

        import zmq

        self._zmq = zmq
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(
            zmq.IDENTITY,
            (identity or f"INFER-{os.getpid()}{secrets.token_hex(4)}")
            .encode())
        self._sock.connect(addr)
        self._lock = threading.Lock()

    def request(self, payload: bytes, req_id: int,
                timeout_s: float) -> dict:
        """Send one request and wait for ITS reply (req-id matched).
        Raises TimeoutError when nothing matching arrives in time."""
        zmq = self._zmq
        with self._lock:
            # Drain leftovers from PREVIOUS requests before sending:
            # a late reply (or req=-1 nack) to an attempt that already
            # timed out must not be adopted by THIS request — clearing
            # the buffer first shrinks the -1 branch's ambiguity window
            # to replies generated after this send.
            try:
                while self._sock.poll(0):
                    # NOBLOCK recv after a 0-timeout poll: returns
                    # immediately by construction, never blocks the lock.
                    self._sock.recv(zmq.NOBLOCK)  # jaxlint: disable=CONC01
            except zmq.ZMQError:
                pass
            self._sock.send(payload)
            deadline = time.monotonic() + timeout_s
            poller = zmq.Poller()
            poller.register(self._sock, zmq.POLLIN)
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"inference reply not received in {timeout_s:.2f}s")
                if not dict(poller.poll(max(1, int(remaining * 1000)))):
                    continue
                # deliberate blocking-under-lock: the lock EXISTS to
                # serialize whole request/reply exchanges on the
                # non-thread-safe DEALER (the _dealer_request precedent);
                # poll() above guarantees recv returns immediately and
                # the hold is bounded by the caller's timeout_s.
                raw = self._sock.recv()  # jaxlint: disable=CONC01
                try:
                    reply = unpack_infer_reply(raw)
                except Exception:
                    continue  # corrupt frame: wait out the deadline
                if reply["req"] == req_id:
                    return reply
                if reply["req"] == -1 and reply["code"] != NACK_OK:
                    # The service could not decode the request, so its
                    # error/unavailable reply carries req=-1. This
                    # client is strictly one-request-outstanding, so the
                    # verdict is unambiguously OURS — returning it makes
                    # a corrupted request a fast error-reply retry
                    # (the agent.infer chaos contract) instead of a full
                    # timeout + an unearned breaker charge.
                    return reply
                # stale reply from a timed-out earlier attempt: discard

    def close(self) -> None:
        self._sock.close(linger=0)


class StreamWaiter:
    """One in-flight streamed request: ``wait`` blocks for ITS reply
    (req-id matched by the receiver loop). ``reply`` is None until
    delivery; a waiter failed wholesale (stream broke, client closing)
    completes with ``error`` set instead."""

    __slots__ = ("req_id", "event", "reply", "error")

    def __init__(self, req_id: int):
        self.req_id = int(req_id)
        self.event = threading.Event()
        self.reply: dict | None = None
        self.error: str | None = None

    def resolve(self, reply: dict) -> None:
        self.reply = reply
        self.event.set()

    def fail(self, error: str) -> None:
        self.error = error
        self.event.set()


class ZmqStreamingClient:
    """Pipelined DEALER against the service's ROUTER: N requests in
    flight per client, replies matched by request id, out-of-order
    completion legal — the serving-v2 stream channel that lets one thin
    process drive dozens of env lanes over in-flight windows instead of
    lock-step round-trips.

    The DEALER is owned by ONE receiver thread (zmq sockets are not
    thread-safe); submitting threads hand their frames to it over an
    inproc PUSH/PULL pipe (the ZmqServingPlane pattern, mirrored
    client-side), so a submit never waits on a reply and never touches
    the DEALER. ``inflight_high_water`` records the deepest concurrent
    pipeline seen — the bench/test evidence that streaming actually
    streams (≥2 asserted by the serving smoke)."""

    def __init__(self, addr: str, identity: str | None = None):
        import os
        import secrets

        import zmq

        self._zmq = zmq
        self._ctx = zmq.Context.instance()
        self._dealer = self._ctx.socket(zmq.DEALER)
        self._dealer.setsockopt(
            zmq.IDENTITY,
            (identity or f"INFER-{os.getpid()}{secrets.token_hex(4)}")
            .encode())
        self._dealer.connect(addr)
        self._inproc = f"inproc://relayrl-serving-cli-{id(self):x}"
        self._pull = self._ctx.socket(zmq.PULL)
        self._pull.bind(self._inproc)
        self._push = self._ctx.socket(zmq.PUSH)
        self._push.connect(self._inproc)
        self._push_lock = threading.Lock()
        self._pending: dict[int, StreamWaiter] = {}
        self._plock = threading.Lock()
        self.inflight_high_water = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="zmq-serving-stream", daemon=True)
        self._thread.start()

    def submit(self, payload: bytes, req_id: int) -> StreamWaiter:
        """Queue one request for send and return its waiter — returns
        immediately; the reply lands on the waiter whenever its batch
        executes, in any order relative to other in-flight requests."""
        waiter = StreamWaiter(req_id)
        with self._plock:
            if self._stop.is_set():
                waiter.fail("streaming client closed")
                return waiter
            self._pending[req_id] = waiter
            depth = len(self._pending)
            if depth > self.inflight_high_water:
                self.inflight_high_water = depth
        with self._push_lock:
            self._push.send(payload)
        return waiter

    def submit_wave(self, payload: bytes,
                    req_ids: list[int]) -> list[StreamWaiter]:
        """Queue one coalesced wave frame (``pack_infer_wave``) carrying
        several requests; returns one waiter per request, resolved
        independently (replies may coalesce differently than requests —
        the receiver matches by req id either way)."""
        waiters = [StreamWaiter(r) for r in req_ids]
        with self._plock:
            if self._stop.is_set():
                for waiter in waiters:
                    waiter.fail("streaming client closed")
                return waiters
            for waiter in waiters:
                self._pending[waiter.req_id] = waiter
            depth = len(self._pending)
            if depth > self.inflight_high_water:
                self.inflight_high_water = depth
        with self._push_lock:
            self._push.send(payload)
        return waiters

    def wait(self, waiter: StreamWaiter, timeout_s: float) -> dict:
        """Block for one waiter's reply. On timeout the waiter is
        RETRACTED (a late reply is dropped by the receiver, never
        adopted by a retry — retries carry fresh req ids)."""
        if not waiter.event.wait(timeout_s):
            self.cancel(waiter.req_id)
            # Resolve-vs-cancel race: the receiver may have completed
            # the waiter between the wait timeout and the pop.
            if not waiter.event.is_set():
                raise TimeoutError(
                    f"streamed inference reply not received in "
                    f"{timeout_s:.2f}s")
        if waiter.error is not None:
            raise ConnectionError(waiter.error)
        return waiter.reply

    def request(self, payload: bytes, req_id: int, timeout_s: float) -> dict:
        """Serial-compatible surface (ZmqServingClient drop-in): submit
        and wait. Callers that never overlap submits get exactly the
        lock-step behavior, over the same pipelined channel."""
        return self.wait(self.submit(payload, req_id), timeout_s)

    def cancel(self, req_id: int) -> None:
        with self._plock:
            self._pending.pop(req_id, None)

    def _loop(self) -> None:
        zmq = self._zmq
        poller = zmq.Poller()
        poller.register(self._dealer, zmq.POLLIN)
        poller.register(self._pull, zmq.POLLIN)
        while not self._stop.is_set():
            events = dict(poller.poll(100))
            if self._pull in events:
                while True:
                    try:
                        frame = self._pull.recv(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    self._dealer.send(frame)
            if self._dealer in events:
                while True:
                    try:
                        raw = self._dealer.recv(zmq.NOBLOCK)
                    except zmq.ZMQError:
                        break
                    try:
                        rows = unpack_reply_any(raw)
                    except Exception:
                        continue  # corrupt frame: its waiters time out
                    for reply in rows:
                        with self._plock:
                            waiter = self._pending.pop(reply["req"], None)
                        # req=-1 decode-failure nacks are ambiguous on a
                        # pipelined channel (unlike the serial client's
                        # one-outstanding adoption rule) — unmatched
                        # replies drop and the affected waiter retries
                        # on timeout.
                        if waiter is not None:
                            waiter.resolve(reply)

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._plock:
            pending, self._pending = list(self._pending.values()), {}
        for waiter in pending:
            waiter.fail("streaming client closed")
        with self._push_lock:
            # Under the send lock: a racing submit that passed the _stop
            # check must finish its send before the socket dies.
            self._push.close(linger=0)
        for sock in (self._dealer, self._pull):
            sock.close(linger=0)


class GrpcServingClient:
    """In-band ``GetActions`` unary RPC on the agent's existing channel
    (pure-grpcio fleets). The request/response pairing is the RPC itself,
    so there is no stale-reply window to filter."""

    def __init__(self, agent_transport):
        import grpc

        self._grpc = grpc
        self._transport = agent_transport
        self._stub = None
        self._stub_channel = None

    def _get_stub(self):
        # The agent transport may rebuild its channel after a persistent
        # break (_rebuild_channel); re-derive the stub when it did.
        channel = self._transport._channel
        if self._stub is None or self._stub_channel is not channel:
            self._stub = channel.unary_unary(
                "/relayrl.RelayRLRoute/GetActions",
                request_serializer=lambda x: x,
                response_deserializer=lambda x: x)
            self._stub_channel = channel
        return self._stub

    def request(self, payload: bytes, req_id: int,
                timeout_s: float) -> dict:
        grpc = self._grpc
        try:
            raw = self._get_stub()(payload, timeout=timeout_s)
        except grpc.RpcError as e:
            code = getattr(e, "code", lambda: None)()
            if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                raise TimeoutError(
                    f"inference RPC deadline ({timeout_s:.2f}s)") from None
            if code == grpc.StatusCode.UNIMPLEMENTED:
                # PERMANENT: this server has no GetActions RPC at all —
                # the native C++ gRPC core. Retrying a misconfiguration
                # would bury it in a deadline exhaustion (the
                # NACK_UNAVAILABLE rationale); RuntimeError passes
                # through the client's retry loop uncaught.
                raise RuntimeError(
                    "inference unavailable: this gRPC server does not "
                    "implement GetActions (native C++ core?) — serve "
                    "inference on the zmq plane (serving_plane=\"zmq\") "
                    "or run the pure-grpcio server") from None
            raise ConnectionError(f"inference RPC failed: {e}") from None
        return unpack_infer_reply(raw)

    def close(self) -> None:
        pass  # the agent transport owns the channel


class GrpcStreamingClient:
    """Bidi ``StreamActions`` on the agent's existing channel — the grpc
    equivalent of :class:`ZmqStreamingClient`: N requests in flight,
    req-id matched, out-of-order replies legal. One long-lived
    stream-stream call carries every request; a broken stream fails the
    in-flight waiters (their owners retry) and the next submit opens a
    fresh call on whatever channel the transport currently holds (so a
    ``_rebuild_channel`` heal is picked up automatically)."""

    def __init__(self, agent_transport):
        import grpc

        self._grpc = grpc
        self._transport = agent_transport
        self._lock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict[int, StreamWaiter] = {}
        self.inflight_high_water = 0
        self._queue = None          # outbound request queue of the live call
        self._receiver = None
        self._closed = False
        self._permanent: str | None = None

    def _ensure_stream_locked(self):
        import queue as queue_mod

        if self._queue is not None:
            return self._queue
        channel = self._transport._channel
        stub = channel.stream_stream(
            "/relayrl.RelayRLRoute/StreamActions",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x)
        q: "queue_mod.Queue[bytes | None]" = queue_mod.Queue()

        def request_iter():
            while True:
                item = q.get()
                if item is None:
                    return
                yield item

        responses = stub(request_iter())
        self._queue = q
        self._receiver = threading.Thread(
            target=self._recv_loop, args=(q, responses),
            name="grpc-serving-stream", daemon=True)
        self._receiver.start()
        return q

    def _recv_loop(self, q, responses) -> None:
        grpc = self._grpc
        error = "inference stream closed"
        try:
            for raw in responses:
                try:
                    reply = unpack_infer_reply(raw)
                except Exception:
                    continue
                with self._plock:
                    waiter = self._pending.pop(reply["req"], None)
                if waiter is not None:
                    waiter.resolve(reply)
        except grpc.RpcError as e:
            code = getattr(e, "code", lambda: None)()
            if code == grpc.StatusCode.UNIMPLEMENTED:
                # PERMANENT: no StreamActions RPC on this server (native
                # C++ core, or a pre-v2 pure-grpcio build) — same
                # misconfiguration contract as GetActions UNIMPLEMENTED.
                self._permanent = (
                    "inference unavailable: this gRPC server does not "
                    "implement StreamActions — serve inference on the "
                    "zmq plane (serving_plane=\"zmq\") or run a "
                    "serving-v2 pure-grpcio server")
                error = self._permanent
            else:
                error = f"inference stream broke: {e}"
        # Stream over (server gone, half-close, or error): fail every
        # in-flight waiter and let the next submit reopen.
        with self._lock:
            if self._queue is q:
                self._queue = None
                self._receiver = None
        with self._plock:
            pending, self._pending = list(self._pending.values()), {}
        for waiter in pending:
            waiter.fail(error)

    def submit(self, payload: bytes, req_id: int) -> StreamWaiter:
        waiter = StreamWaiter(req_id)
        if self._permanent is not None:
            raise RuntimeError(self._permanent)
        with self._lock:
            if self._closed:
                waiter.fail("streaming client closed")
                return waiter
            q = self._ensure_stream_locked()
            with self._plock:
                self._pending[req_id] = waiter
                depth = len(self._pending)
                if depth > self.inflight_high_water:
                    self.inflight_high_water = depth
            q.put(payload)
        return waiter

    def wait(self, waiter: StreamWaiter, timeout_s: float) -> dict:
        if not waiter.event.wait(timeout_s):
            with self._plock:
                self._pending.pop(waiter.req_id, None)
            if not waiter.event.is_set():
                raise TimeoutError(
                    f"streamed inference reply not received in "
                    f"{timeout_s:.2f}s")
        if waiter.error is not None:
            raise ConnectionError(waiter.error)
        return waiter.reply

    def request(self, payload: bytes, req_id: int, timeout_s: float) -> dict:
        return self.wait(self.submit(payload, req_id), timeout_s)

    def cancel(self, req_id: int) -> None:
        with self._plock:
            self._pending.pop(req_id, None)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            q, self._queue = self._queue, None
            receiver, self._receiver = self._receiver, None
        if q is not None:
            q.put(None)  # half-close; the receiver fails any stragglers
        if receiver is not None:
            receiver.join(timeout=5)


def make_serving_client(server_type: str, config, transport=None,
                        **overrides):
    """The thin client's action channel for a fleet transport kind:
    gRPC fleets ride the in-band ``GetActions`` RPC on the agent's
    existing channel; zmq and native fleets use the dedicated zmq
    DEALER against ``server.inference_server`` (native passthrough —
    the C++ core has no request/response action RPC). Pass
    ``serving_plane="zmq"`` to force the zmq plane on a grpc fleet whose
    server runs the native C++ gRPC core (it does not speak GetActions).
    ``stream=True`` returns the pipelined streaming client for the plane
    instead of the lock-step one (N in-flight requests, out-of-order
    replies — the serving-v2 channel)."""
    plane = overrides.get("serving_plane") or (
        "grpc" if server_type == "grpc" else "zmq")
    stream = bool(overrides.get("stream", False))
    if plane == "grpc":
        if transport is None or not hasattr(transport, "_channel"):
            raise ValueError(
                "grpc serving plane needs the agent's GrpcAgentTransport")
        return (GrpcStreamingClient(transport) if stream
                else GrpcServingClient(transport))
    addr = overrides.get("serving_addr")
    if addr is None:
        addr = config.get_inference_server().address
    cls = ZmqStreamingClient if stream else ZmqServingClient
    return cls(addr, identity=overrides.get("identity"))


__all__ = [
    "pack_infer_request", "unpack_infer_request", "pack_action_reply",
    "pack_infer_nack", "unpack_infer_reply", "ZmqServingPlane",
    "ZmqServingClient", "ZmqStreamingClient", "GrpcServingClient",
    "GrpcStreamingClient", "StreamWaiter", "make_serving_client",
]
