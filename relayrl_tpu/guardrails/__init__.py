"""Training-health guardrails: the learning plane's immune system.

PR 6 made the *delivery* plane crash-safe; this package guards the
*learning* plane against the failures delivery correctness cannot see —
poisoned data, diverging optimization, and ingest overload. Four
cooperating pieces, all wired through :class:`~relayrl_tpu.runtime.
server.TrainingServer` (config section ``guardrails.*``,
docs/operations.md "Training-health guardrails"):

* **Ingest validation** (validate.py) — schema/dtype/shape/length/
  finiteness checks on every decoded trajectory before it touches the
  staging slabs; columnar-aware so the common case is a few vectorized
  numpy passes.
* **Quarantine** (quarantine.py) — per-agent strike accounting that
  isolates a poison-*emitting* agent (typed nack where the transport
  can answer, server-side shed elsewhere) with auto-parole.
* **Divergence watchdog** (watchdog.py) — device-side finite/param-norm/
  update-norm probes resolved lazily at the in-flight fence plus
  loss-spike and reward-collapse rolling detectors. Probes are
  observers: guardrails-on params are bit-identical to guardrails-off.
* **Backpressure** (admission.py) — soft-bounded admission with a
  per-agent-fair shed policy (drop-oldest or nack-with-retry-after).

The watchdog's trips drive the server's last-known-good auto-rollback
(checkpoint ring tagged healthy-at-save, ledger-sidecar-consistent
restore, forced model-wire keyframe) — see TrainingServer._execute_
rollback and the runbook.

``build_guardrails(config)`` returns None when ``guardrails.enabled``
is false: every hook site then holds a None and costs one identity
check, the telemetry/faults process-model precedent.
"""

from __future__ import annotations

from relayrl_tpu.guardrails.admission import (  # noqa: F401
    SHED_POLICIES,
    AdmissionController,
)
from relayrl_tpu.guardrails.quarantine import QuarantineBook  # noqa: F401
from relayrl_tpu.guardrails.validate import (  # noqa: F401
    params_tree_finite,
    trajectory_reward,
    validate_trajectory,
)
from relayrl_tpu.guardrails.watchdog import (  # noqa: F401
    DivergenceWatchdog,
    GuardProbes,
    Trip,
)

VALIDATION_MODES = ("enforce", "warn", "off")


class Guardrails:
    """The assembled guardrail set one TrainingServer owns."""

    def __init__(self, params: dict):
        from relayrl_tpu import telemetry

        self.params = dict(params)
        self.validation_mode = self.params["ingest_validation"]
        self.max_steps = int(self.params.get("max_steps") or 0)
        self.quarantine = QuarantineBook(
            strike_threshold=self.params["strike_threshold"],
            strike_window_s=self.params["strike_window_s"],
            cooldown_s=self.params["quarantine_cooldown_s"])
        self.watchdog = None
        if self.params["watchdog"]:
            self.watchdog = DivergenceWatchdog(
                max_param_norm=self.params["max_param_norm"],
                max_update_norm=self.params["max_update_norm"],
                loss_spike_factor=self.params["loss_spike_factor"],
                loss_window=self.params["loss_window"],
                loss_key=self.params["loss_key"],
                reward_collapse_drop=self.params["reward_collapse_drop"],
                reward_window=self.params["reward_window"])
        self.admission = None
        if int(self.params["ingest_soft_limit"]) > 0:
            self.admission = AdmissionController(
                soft_limit=self.params["ingest_soft_limit"],
                policy=self.params["shed_policy"],
                agent_share=self.params["agent_share"],
                retry_after_s=self.params["nack_retry_after_s"])
        reg = telemetry.get_registry()
        self._m_rejected = {}
        self._reg = reg
        self._m_publish_blocked = reg.counter(
            "relayrl_guard_publish_blocked_total",
            "model publishes refused because host params were non-finite")
        self._m_rollbacks = reg.counter(
            "relayrl_guard_rollbacks_total",
            "last-known-good auto-rollbacks executed")
        self._m_halted = reg.gauge(
            "relayrl_guard_halted",
            "1 when guardrails halted training (rollback budget spent)")
        self._m_halted.set(0)
        self._m_halted_drops = reg.counter(
            "relayrl_guard_halted_drops_total",
            "trajectories ignored while halted")

    # -- validation funnel (server ingest paths) --
    def count_reject(self, reason: str) -> None:
        metric = self._m_rejected.get(reason)
        if metric is None:
            metric = self._reg.counter(
                "relayrl_guard_rejected_total",
                "trajectories rejected by ingest validation",
                {"reason": reason})
            self._m_rejected[reason] = metric
        metric.inc()

    def _feed_reward(self, item) -> None:
        """Reward feed for the collapse detector — every admitted
        trajectory, in every validation mode: "off" stands down the
        validator and strikes, NOT a detector the operator armed."""
        if (self.watchdog is not None
                and self.watchdog.reward_collapse_drop > 0):
            reward = trajectory_reward(item)
            if reward is not None:
                self.watchdog.observe_reward(reward)

    def validate(self, agent_id: str, item):
        """Run one decoded trajectory through validation + strikes.
        Returns the item when it should continue into the learner plane
        (clean, or rejected-but-warn-mode), else None."""
        if self.validation_mode == "off":
            self._feed_reward(item)
            return item
        reason = validate_trajectory(item, self.max_steps)
        if reason is None:
            self._feed_reward(item)
            return item
        self.count_reject(reason)
        self.quarantine.strike(agent_id, reason)
        if self.validation_mode == "warn":
            # Observe-only posture: strikes and counters accrue (the
            # quarantine still engages) but the item trains — the
            # defense-in-depth drill's deliberately-torn first layer.
            return item
        return None

    def attach_algorithm(self, algo) -> None:
        """Install the device probes and align the per-algorithm finite
        guard with the configured validation mode (in ``warn`` mode the
        algorithm's own drop-nonfinite belt must stand down, or the
        observe-only posture silently re-enforces)."""
        if self.watchdog is not None and self.params["probes"]:
            algo._guard_probes = GuardProbes(
                update_norm=self.params["update_norm_probe"])
        if self.validation_mode == "warn":
            algo.ingest_finite_guard = False

    def accounting(self) -> dict:
        """The drill/bench evidence block (rides chaos rows)."""
        out = {
            "validation_mode": self.validation_mode,
            "quarantine": self.quarantine.accounting(),
        }
        if self.watchdog is not None:
            out["watchdog"] = self.watchdog.accounting()
        if self.admission is not None:
            out["admission"] = self.admission.accounting()
        return out


def build_guardrails(config) -> Guardrails | None:
    """Guardrails from a ConfigLoader (None when disabled)."""
    params = config.get_guardrails_params()
    if not params["enabled"]:
        return None
    if params.get("max_steps") is None:
        # null derives from max_traj_length; an explicit 0 stays 0 —
        # the documented "length bound disabled" opt-out.
        params["max_steps"] = config.get_max_traj_length()
    return Guardrails(params)


__all__ = [
    "Guardrails", "build_guardrails", "VALIDATION_MODES",
    "AdmissionController", "QuarantineBook", "DivergenceWatchdog",
    "GuardProbes", "Trip", "validate_trajectory", "trajectory_reward",
    "params_tree_finite", "SHED_POLICIES",
]
