"""Divergence watchdog: device-side health probes + rolling-window
detectors over the learner's update stream.

**Probes are observers, never perturbations.** The update's own jitted
program is untouched (guardrails-on params are BIT-identical to
guardrails-off — asserted by tests/test_guardrails.py for REINFORCE and
PPO); instead, two tiny *separate* jitted programs run around each
dispatch:

* ``pre_update``  — an async device-to-device copy of the params (only
  when the update-norm probe is enabled), dispatched BEFORE the donating
  update so the old buffers are still live;
* ``post_update`` — nonfinite-element count, global param L2 norm, and
  (with the copy) the update-step L2 norm ``||new - old||`` — the
  grad-norm proxy that needs no access to the update's internals.

All three come back as **unresolved device scalars** merged into the
update's metrics dict: they ride the same in-flight window as the
metrics (same XLA stream ⇒ "probe ready" implies "update done") and are
resolved lazily at the fence, exactly like
:class:`~relayrl_tpu.runtime.pipeline.LazyMetrics` — zero host sync on
the dispatch hot path (jaxlint JAX02/JAX06 clean by construction).

The :class:`DivergenceWatchdog` consumes resolved probes plus two host
signals — per-update loss (spike detector over a rolling median) and
per-trajectory reward (collapse detector over a rolling mean) — and
turns threshold crossings into a :class:`Trip` the server's rollback
path consumes (docs/operations.md "Training-health guardrails").
"""

from __future__ import annotations

import statistics
import threading
from collections import deque
from dataclasses import dataclass

#: Reserved metric keys the probes merge into each update's metrics.
PROBE_NONFINITE = "GuardNonfiniteParams"
PROBE_PARAM_NORM = "GuardParamNorm"
PROBE_UPDATE_NORM = "GuardUpdateNorm"

TRIP_SIGNALS = ("nonfinite_params", "param_norm", "update_norm",
                "loss_nonfinite", "loss_spike", "reward_collapse",
                "publish_nonfinite")


@dataclass(frozen=True)
class Trip:
    """One watchdog firing: what crossed which line, at which update."""

    signal: str
    value: float
    threshold: float
    dispatch_count: int | None = None

    def to_dict(self) -> dict:
        return {"signal": self.signal, "value": self.value,
                "threshold": self.threshold,
                "dispatch_count": self.dispatch_count}


class GuardProbes:
    """The two jitted observer programs (built lazily, once per
    instance). Float leaves only; integer/bool leaves (step counters)
    carry no divergence signal. Norms accumulate in float32 — a sumsq
    overflow needs leaf values beyond ~1e19, itself a divergence the
    nonfinite probe then reports as inf."""

    def __init__(self, update_norm: bool = True):
        self.update_norm = bool(update_norm)
        self._copy_fn = None
        self._probe_fn = None
        self._probe_delta_fn = None

    @staticmethod
    def _float_leaves(tree):
        import jax
        import jax.numpy as jnp

        return [leaf for leaf in jax.tree_util.tree_leaves(tree)
                if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact)]

    @classmethod
    def _stats(cls, tree):
        import jax.numpy as jnp

        leaves = cls._float_leaves(tree)
        if not leaves:
            return jnp.int32(0), jnp.float32(0)
        nonfinite = sum(
            jnp.sum(~jnp.isfinite(leaf.astype(jnp.float32)))
            for leaf in leaves)
        sumsq = sum(jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                    for leaf in leaves)
        return nonfinite.astype(jnp.int32), jnp.sqrt(sumsq)

    def pre_update(self, params):
        """Async D2D copy of the float leaves (dispatched before the
        donating update, so it reads the still-live old buffers); None
        when the update-norm probe is off."""
        if not self.update_norm:
            return None
        import jax
        import jax.numpy as jnp

        if self._copy_fn is None:
            self._copy_fn = jax.jit(
                lambda tree: jax.tree_util.tree_map(jnp.copy, tree))
        return self._copy_fn(params)

    def post_update(self, old_copy, new_params) -> dict:
        """Probe the post-update params; returns unresolved device
        scalars under the reserved Guard* keys."""
        import jax
        import jax.numpy as jnp

        if old_copy is None:
            if self._probe_fn is None:
                self._probe_fn = jax.jit(self._stats)
            nonfinite, norm = self._probe_fn(new_params)
            return {PROBE_NONFINITE: nonfinite, PROBE_PARAM_NORM: norm}

        if self._probe_delta_fn is None:
            def probe(old, new):
                nonfinite, norm = self._stats(new)
                old_leaves = self._float_leaves(old)
                new_leaves = self._float_leaves(new)
                delta_sq = sum(
                    jnp.sum(jnp.square(n.astype(jnp.float32)
                                       - o.astype(jnp.float32)))
                    for o, n in zip(old_leaves, new_leaves)) \
                    if old_leaves else jnp.float32(0)
                return nonfinite, norm, jnp.sqrt(delta_sq)

            # old_copy is dead after this probe — donate it so the copy
            # buffers free immediately on backends that support donation.
            self._probe_delta_fn = jax.jit(probe, donate_argnums=0)
        nonfinite, norm, delta = self._probe_delta_fn(old_copy, new_params)
        return {PROBE_NONFINITE: nonfinite, PROBE_PARAM_NORM: norm,
                PROBE_UPDATE_NORM: delta}


class DivergenceWatchdog:
    """Rolling-window trip logic over resolved probes + host signals.

    Thread model: ``observe_dispatch``/``poll`` run on the learner
    thread only; ``observe_reward`` runs on staging/transport threads;
    ``trip_external`` may fire from the publisher thread — the small
    lock covers the shared deques and the external-trip slot, and no
    device fence ever happens under it.
    """

    def __init__(self, max_param_norm: float = 0.0,
                 max_update_norm: float = 0.0,
                 loss_spike_factor: float = 0.0, loss_window: int = 16,
                 loss_key: str = "auto",
                 reward_collapse_drop: float = 0.0,
                 reward_window: int = 32):
        from relayrl_tpu import telemetry

        self.max_param_norm = float(max_param_norm or 0.0)
        self.max_update_norm = float(max_update_norm or 0.0)
        self.loss_spike_factor = float(loss_spike_factor or 0.0)
        self.loss_window = max(4, int(loss_window))
        self.loss_key = loss_key
        self.reward_collapse_drop = float(reward_collapse_drop or 0.0)
        self.reward_window = max(4, int(reward_window))
        self._lock = threading.Lock()
        self._pending: deque = deque()   # (dispatch_count, metrics mapping)
        self._losses: deque = deque(maxlen=self.loss_window)
        self._rewards: deque = deque(maxlen=self.reward_window)
        self._best_reward_mean: float | None = None
        self._external: Trip | None = None
        self._resolved_ok = True
        self.trips_total = 0
        self.last_trip: Trip | None = None
        reg = telemetry.get_registry()
        self._m_trips = {
            sig: reg.counter("relayrl_guard_watchdog_trips_total",
                             "divergence watchdog firings",
                             {"signal": sig})
            for sig in TRIP_SIGNALS
        }

    # -- feeds --
    def observe_dispatch(self, dispatch_count: int, metrics) -> None:
        """Queue one dispatched update's (lazy) metrics for evaluation
        once the in-flight window fences it. Learner thread only."""
        with self._lock:
            self._pending.append((dispatch_count, metrics))

    def observe_reward(self, total_reward: float) -> None:
        """One validated trajectory's total reward (staging threads)."""
        with self._lock:
            self._rewards.append(float(total_reward))

    def trip_external(self, signal: str, value: float = float("nan"),
                      threshold: float = 0.0) -> None:
        """An out-of-band trip (the publish gate's nonfinite detection,
        fired from the publisher thread); the learner thread's next
        :meth:`poll` surfaces it."""
        with self._lock:
            if self._external is None:
                self._external = Trip(signal, value, threshold)

    # -- evaluation --
    def _loss_of(self, metrics) -> float | None:
        key = self.loss_key
        if key == "auto":
            for candidate in ("LossPi", "LossQ", "Loss", "LossQ1"):
                if candidate in metrics:
                    key = candidate
                    break
            else:
                return None
        try:
            value = metrics.get(key)
            return None if value is None else float(value)
        except Exception:
            return None

    def _check_resolved(self, dc: int, metrics) -> Trip | None:
        import math

        def read(key):
            try:
                value = metrics.get(key)
                return None if value is None else float(value)
            except Exception:
                return None

        nonfinite = read(PROBE_NONFINITE)
        if nonfinite is not None and nonfinite > 0:
            return Trip("nonfinite_params", nonfinite, 0.0, dc)
        norm = read(PROBE_PARAM_NORM)
        if norm is not None and not math.isfinite(norm):
            # sumsq overflow: params beyond float32 range — divergence.
            return Trip("param_norm", norm, self.max_param_norm, dc)
        if (self.max_param_norm > 0 and norm is not None
                and norm > self.max_param_norm):
            return Trip("param_norm", norm, self.max_param_norm, dc)
        delta = read(PROBE_UPDATE_NORM)
        if (self.max_update_norm > 0 and delta is not None
                and (delta > self.max_update_norm
                     or not math.isfinite(delta))):
            return Trip("update_norm", delta, self.max_update_norm, dc)
        loss = self._loss_of(metrics)
        if loss is not None:
            if not math.isfinite(loss):
                return Trip("loss_nonfinite", loss, 0.0, dc)
            if self.loss_spike_factor > 0:
                with self._lock:
                    history = list(self._losses)
                    self._losses.append(abs(loss))
                if len(history) >= self.loss_window // 2:
                    baseline = statistics.median(history)
                    bar = self.loss_spike_factor * max(baseline, 1e-8)
                    if abs(loss) > bar:
                        return Trip("loss_spike", abs(loss), bar, dc)
            else:
                with self._lock:
                    self._losses.append(abs(loss))
        return None

    def _check_rewards(self) -> Trip | None:
        if self.reward_collapse_drop <= 0:
            return None
        with self._lock:
            rewards = list(self._rewards)
        if len(rewards) < self.reward_window:
            return None
        mean = sum(rewards) / len(rewards)
        if self._best_reward_mean is None or mean > self._best_reward_mean:
            self._best_reward_mean = mean
            return None
        drop = self._best_reward_mean - mean
        if drop > self.reward_collapse_drop:
            return Trip("reward_collapse", mean, self.reward_collapse_drop)
        return None

    def poll(self, fenced_count: int) -> Trip | None:
        """Resolve every pending probe whose update the in-flight window
        has fenced (resolution is free post-fence — the LazyMetrics
        deferral) and evaluate all detectors. Returns the first Trip, or
        None. Learner thread only."""
        with self._lock:
            external, self._external = self._external, None
        trip = external
        while trip is None:
            with self._lock:
                if not self._pending or self._pending[0][0] > fenced_count:
                    break
                dc, metrics = self._pending.popleft()
            trip = self._check_resolved(dc, metrics)
            if trip is None:
                with self._lock:
                    self._resolved_ok = True
        if trip is None:
            trip = self._check_rewards()
        if trip is not None:
            self._fire(trip)
        return trip

    def _fire(self, trip: Trip) -> None:
        from relayrl_tpu import telemetry

        with self._lock:
            self.trips_total += 1
            self.last_trip = trip
            self._resolved_ok = False
        self._m_trips.get(trip.signal, self._m_trips["nonfinite_params"]) \
            .inc()
        telemetry.emit("watchdog_trip", **trip.to_dict())
        print(f"[guardrails] WATCHDOG TRIP: {trip.signal} "
              f"value={trip.value:.6g} threshold={trip.threshold:.6g}",
              flush=True)

    def healthy(self) -> bool:
        """True when the most recently RESOLVED probes were clean and no
        trip is pending — the checkpoint plane's healthy-at-save tag.
        Deliberately conservative: an un-polled external trip, any
        un-cleared firing, or a probe still awaiting resolution reads
        unhealthy — a pending probe may be the one carrying the NaN, so
        tagging through it would let restore_latest_healthy hand back
        poisoned params."""
        with self._lock:
            return (self._resolved_ok and self._external is None
                    and not self._pending)

    def reset_after_rollback(self) -> None:
        """Drop every pending probe and detector window — they describe
        the rolled-back line of history — and re-arm."""
        with self._lock:
            self._pending.clear()
            self._losses.clear()
            self._rewards.clear()
            self._best_reward_mean = None
            self._external = None
            self._resolved_ok = True

    def accounting(self) -> dict:
        with self._lock:
            return {
                "trips_total": self.trips_total,
                "last_trip": (self.last_trip.to_dict()
                              if self.last_trip else None),
                "pending_probes": len(self._pending),
            }


__all__ = ["GuardProbes", "DivergenceWatchdog", "Trip", "TRIP_SIGNALS",
           "PROBE_NONFINITE", "PROBE_PARAM_NORM", "PROBE_UPDATE_NORM"]
