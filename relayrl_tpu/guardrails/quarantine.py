"""Per-agent strike accounting and poison-agent quarantine.

A single bad trajectory is data (dropped, counted); a *stream* of them is
an agent — buggy preprocessing, a corrupted host, or a hostile client.
The :class:`QuarantineBook` turns repeated validation rejections into a
per-agent lifecycle:

    clean → (``strike_threshold`` strikes within ``strike_window_s``) →
    quarantined (sends rejected with a typed nack where the transport
    has a back-channel; silently shed on broadcast planes) →
    (``cooldown_s`` elapses) → paroled → clean

Strikes age out of the sliding window, so a one-off glitch never
accumulates into a quarantine across hours; parole is lazy (evaluated on
the next contact with the agent) so the book needs no timer thread.
Every transition lands in telemetry and the run journal
(``agent_quarantined`` / ``agent_paroled`` events — the runbook's
greppable breadcrumbs, docs/operations.md).
"""

from __future__ import annotations

import threading
import time


class QuarantineBook:
    """Thread-safe strike book + quarantine set (transport threads hit
    this from every ingest path)."""

    def __init__(self, strike_threshold: int = 3,
                 strike_window_s: float = 60.0,
                 cooldown_s: float = 300.0):
        from relayrl_tpu import telemetry

        self.strike_threshold = max(1, int(strike_threshold))
        self.strike_window_s = float(strike_window_s)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._strikes: dict[str, list[float]] = {}   # agent -> strike times
        self._quarantined: dict[str, float] = {}     # agent -> parole time
        self.quarantines_total = 0
        self.paroles_total = 0
        reg = telemetry.get_registry()
        self._m_strikes = reg.counter(
            "relayrl_guard_strikes_total",
            "validation strikes recorded against agents")
        self._m_quarantines = reg.counter(
            "relayrl_guard_quarantines_total",
            "agents placed in quarantine (transitions, not population)")
        self._m_paroles = reg.counter(
            "relayrl_guard_paroles_total",
            "agents released from quarantine after cooldown")
        self._m_population = reg.gauge(
            "relayrl_guard_quarantined_agents",
            "agents currently quarantined")
        self._m_rejected_sends = reg.counter(
            "relayrl_guard_quarantine_rejects_total",
            "sends rejected because the agent is quarantined")

    # -- lifecycle --
    def strike(self, agent_id: str, reason: str) -> bool:
        """Record one validation strike; True when THIS strike pushed the
        agent into quarantine (the caller's event hook already fired)."""
        now = time.monotonic()
        with self._lock:
            if agent_id in self._quarantined:
                return False  # already out — strikes don't stack inside
            window = self._strikes.setdefault(agent_id, [])
            floor = now - self.strike_window_s
            window[:] = [t for t in window if t > floor]
            window.append(now)
            n = len(window)
            quarantine = n >= self.strike_threshold
            if quarantine:
                self._quarantined[agent_id] = now + self.cooldown_s
                del self._strikes[agent_id]
                self.quarantines_total += 1
                population = len(self._quarantined)
        self._m_strikes.inc()
        if quarantine:
            from relayrl_tpu import telemetry

            self._m_quarantines.inc()
            self._m_population.set(population)
            telemetry.emit("agent_quarantined", agent_id=agent_id,
                           strikes=n, reason=reason,
                           cooldown_s=self.cooldown_s)
            print(f"[guardrails] agent {agent_id!r} QUARANTINED after "
                  f"{n} strike(s) ({reason}); parole in "
                  f"{self.cooldown_s:.0f}s", flush=True)
        return quarantine

    def is_quarantined(self, agent_id: str) -> bool:
        """Quarantine check with lazy parole: an expired cooldown releases
        the agent on this call (event + counters), so no timer thread."""
        now = time.monotonic()
        with self._lock:
            until = self._quarantined.get(agent_id)
            if until is None:
                return False
            if now < until:
                return True
            del self._quarantined[agent_id]
            self.paroles_total += 1
            population = len(self._quarantined)
        from relayrl_tpu import telemetry

        self._m_paroles.inc()
        self._m_population.set(population)
        telemetry.emit("agent_paroled", agent_id=agent_id)
        print(f"[guardrails] agent {agent_id!r} paroled", flush=True)
        return False

    def count_rejected_send(self) -> None:
        """One send rejected because of quarantine (the counter the
        typed-nack path and the server-side shed path share). Named
        apart from ``Guardrails.count_reject(reason)`` — the
        validation-rejection counter — so the two can't be miswired."""
        self._m_rejected_sends.inc()

    def retry_after(self, agent_id: str) -> float:
        """Seconds until parole (0 when not quarantined) — rides the
        typed nack so well-behaved clients can stop hammering."""
        with self._lock:
            until = self._quarantined.get(agent_id)
        return max(0.0, until - time.monotonic()) if until else 0.0

    # -- accounting (bench rows / drills) --
    def accounting(self) -> dict:
        with self._lock:
            return {
                "quarantined": sorted(self._quarantined),
                "quarantines_total": self.quarantines_total,
                "paroles_total": self.paroles_total,
                "strikes_pending": {a: len(ts)
                                    for a, ts in self._strikes.items()},
            }


__all__ = ["QuarantineBook"]
