"""Ingest validation: the semantic trust boundary in front of the learner.

The delivery plane (PR 6) guarantees trajectories *arrive* exactly once;
nothing yet guarantees they are *trainable*. A NaN-bearing payload from a
buggy or hostile client would not crash anything — it would silently
poison the learner state and, through the next publish, the whole fleet
(the scenario RLAX's parameter-distribution layer and MindSpeed RL's
per-stage health gates exist for). This module is the single owner of
"is this decoded trajectory safe to stage?":

* **columnar-aware** — a :class:`~relayrl_tpu.types.columnar.
  DecodedTrajectory` is checked with a handful of vectorized numpy ops
  over its column arrays (dtype kind, leading-dim consistency, length
  bound, finiteness), no per-step Python;
* **record-aware** — an ``ActionRecord`` list (the Python decode path)
  is checked per record, reusing the same dtype/finiteness predicates;
* **never raises past the boundary** — any exception inside a check is
  itself a rejection (``reason="validator_error"``), because a hostile
  payload must not be able to weaponize the validator
  (tests/test_guardrails_fuzz.py drives arbitrary/adversarial payloads
  through here and asserts exactly that).

``validate_trajectory`` returns ``None`` for clean trajectories or a
short machine-readable reason string; the server counts every rejection
in ``relayrl_guard_rejected_total{reason}`` and feeds the per-agent
strike book (quarantine.py). Rejection REASONS are part of the operator
surface (docs/operations.md runbook) — keep them stable.
"""

from __future__ import annotations

import numpy as np

#: dtype kinds a wire column may legally carry. 'V' covers ml_dtypes
#: (bfloat16/float8 surface as void-kind structured scalars); object/
#: str/bytes kinds are rejected outright — nothing downstream can
#: batch them, and an object column is the classic smuggling vector.
_OK_KINDS = frozenset("fiub" + "V")

#: Validation rejection reasons (stable operator vocabulary).
REASONS = ("nonfinite", "schema", "shape", "dtype", "length",
           "validator_error")


def _col_ok(arr, n_steps: int | None) -> str | None:
    """One column's structural checks; returns a reason or None."""
    if not isinstance(arr, np.ndarray):
        return "schema"
    if arr.dtype.kind not in _OK_KINDS:
        return "dtype"
    if n_steps is not None and (arr.ndim < 1 or arr.shape[0] != n_steps):
        return "shape"
    return None


def _value_dtype_ok(value) -> bool:
    """A per-record leaf (obs/act/aux) must coerce to a batchable dtype."""
    arr = np.asarray(value)
    return arr.dtype.kind in _OK_KINDS


#: Columns every decoded trajectory must carry: both producers (the
#: native msgpack decoder and the columnar wire encoder) always emit
#: them, and the padding fast path indexes them unguarded — a
#: hand-rolled hostile frame that omits one must shed here, not as a
#: KeyError inside the learner loop.
_REQUIRED_COLS = ("r", "t", "u", "x")


def _validate_decoded(item, max_steps: int) -> str | None:
    from relayrl_tpu.types.columnar import trajectory_is_finite

    n = item.n_steps
    if not isinstance(n, int) or n < 0:
        return "schema"
    if max_steps and n > max_steps:
        return "length"
    for name in _REQUIRED_COLS:
        if name not in item.columns:
            return "schema"
    for name, col in item.columns.items():
        reason = _col_ok(col, n)
        if reason is not None:
            return reason
    for name, col in item.aux.items():
        reason = _col_ok(col, n)
        if reason is not None:
            return reason
    for final in (item.final_obs, item.final_mask):
        if final is not None:
            reason = _col_ok(final, None)
            if reason is not None:
                return reason
    if not trajectory_is_finite(item):
        return "nonfinite"
    return None


def _validate_records(item, max_steps: int) -> str | None:
    from relayrl_tpu.types.action import ActionRecord
    from relayrl_tpu.types.columnar import trajectory_is_finite

    try:
        n = len(item)
    except TypeError:
        return "schema"
    if max_steps and n > max_steps:
        return "length"
    for rec in item:
        if not isinstance(rec, ActionRecord):
            return "schema"
        # rew must be a real scalar (bool is int-kind and harmless);
        # a complex/str rew would die far later, inside batch assembly.
        if not isinstance(rec.rew, (int, float, np.integer, np.floating)):
            return "schema"
        for value in (rec.obs, rec.act, rec.mask):
            if value is not None and not _value_dtype_ok(value):
                return "dtype"
        for value in (rec.data or {}).values():
            if isinstance(value, (str, bytes, bool)):
                continue  # inert on the training path (columnar parity)
            if not _value_dtype_ok(value):
                return "dtype"
    if not trajectory_is_finite(item):
        return "nonfinite"
    return None


def validate_trajectory(item, max_steps: int = 0) -> str | None:
    """``None`` when ``item`` is safe to stage, else a rejection reason.

    ``max_steps`` bounds trajectory length (0 disables the bound);
    callers pass the config's ``max_traj_length`` so an adversarial
    million-step trajectory sheds here instead of exploding the padder.
    Accepts either wire representation (DecodedTrajectory or an
    ActionRecord sequence); anything else is ``"schema"``. Never raises.
    """
    from relayrl_tpu.types.columnar import DecodedTrajectory

    try:
        if isinstance(item, DecodedTrajectory):
            return _validate_decoded(item, max_steps)
        return _validate_records(item, max_steps)
    except Exception:
        # The boundary contract: a payload that can crash a check is by
        # definition not trainable — reject it, never propagate.
        return "validator_error"


def trajectory_reward(item) -> float | None:
    """Total reward of a VALIDATED trajectory (the watchdog's
    reward-collapse feed); None when it cannot be read cheaply."""
    from relayrl_tpu.types.columnar import DecodedTrajectory

    try:
        if isinstance(item, DecodedTrajectory):
            return item.total_reward
        return float(sum(rec.rew for rec in item))
    except Exception:
        return None


def params_tree_finite(host_params) -> bool:
    """True iff every float leaf of a HOST params tree is finite — the
    publish gate's check (runs on the publisher thread; the wire encoder
    walks the same leaves right after, so the marginal cost is one
    vectorized isfinite pass per leaf)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(host_params):
        arr = np.asarray(leaf)
        if arr.dtype.kind not in "fV":
            continue
        try:
            finite = np.isfinite(arr if arr.dtype.kind == "f"
                                 else arr.astype(np.float32))
        except (TypeError, ValueError):
            continue  # non-numeric void dtype: nothing to check
        if not finite.all():
            return False
    return True


__all__ = ["validate_trajectory", "trajectory_reward",
           "params_tree_finite", "REASONS"]
