"""Bounded ingest admission: overload sheds gracefully instead of
ballooning the queue.

The raw ingest queue's hard cap (100k entries) exists to avoid OOM; by
the time it bites, the learner is minutes behind and every drop is
indiscriminate. The :class:`AdmissionController` adds a *soft* bound
with a configurable shed policy well before that cliff:

* **per-agent fairness first** — an agent holding more than
  ``agent_share`` of the soft limit sheds ITS OWN new arrivals (a
  flooding agent cannot starve the rest of the fleet; the ``flood``
  fault op drills exactly this);
* ``drop_oldest`` (default) — at the soft limit, the globally oldest
  queued trajectory is evicted to admit the new one (freshest-data-wins,
  the right default for on-policy learners). The victim's sequence
  number is retracted from the dedup ledger, so the owning actor's spool
  replay can redeliver it when pressure clears — a shed is backpressure,
  not loss;
* ``nack`` — the incoming send is refused with a typed
  retry-after nack (transports with a back-channel deliver it; the
  actor's spool keeps the entry and replays later, riding the existing
  RetryPolicy cadence).

The controller only tracks counts; the server owns the queue and hands
in an eviction callback, so queue discipline stays in one place.
"""

from __future__ import annotations

import threading

SHED_POLICIES = ("drop_oldest", "nack")


class AdmissionController:
    """Per-agent in-queue accounting + soft-bound shed decisions."""

    def __init__(self, soft_limit: int, policy: str = "drop_oldest",
                 agent_share: float = 0.5, retry_after_s: float = 1.0):
        from relayrl_tpu import telemetry

        self.soft_limit = max(0, int(soft_limit))
        self.policy = policy if policy in SHED_POLICIES else "drop_oldest"
        self.agent_share = min(1.0, max(0.0, float(agent_share)))
        self.retry_after_s = max(0.0, float(retry_after_s))
        self._lock = threading.Lock()
        self._per_agent: dict[str, int] = {}
        self._depth = 0
        self.sheds = {"agent_share": 0, "drop_oldest": 0, "nack": 0}
        reg = telemetry.get_registry()
        self._m_shed = {
            kind: reg.counter(
                "relayrl_guard_shed_total",
                "trajectories shed by ingest backpressure",
                {"policy": kind})
            for kind in self.sheds
        }

    @property
    def agent_cap(self) -> int:
        """Max queue entries one agent may hold (0 = no per-agent cap)."""
        if not self.soft_limit or self.agent_share >= 1.0:
            return 0
        return max(1, int(self.soft_limit * self.agent_share))

    def admit(self, agent_id: str) -> str:
        """Decide for one arriving trajectory: ``"admit"``,
        ``"shed_agent"`` (sender over its fair share), ``"evict"``
        (admit after the caller evicts the global oldest), or
        ``"nack"``. The caller performs the queue action and then calls
        :meth:`note_enqueued` for admitted items."""
        if not self.soft_limit:
            return "admit"
        cap = self.agent_cap
        with self._lock:
            if cap and self._per_agent.get(agent_id, 0) >= cap:
                self.sheds["agent_share"] += 1
                verdict = "shed_agent"
            elif self._depth >= self.soft_limit:
                if self.policy == "nack":
                    self.sheds["nack"] += 1
                    verdict = "nack"
                else:
                    self.sheds["drop_oldest"] += 1
                    verdict = "evict"
            else:
                return "admit"
        kind = {"shed_agent": "agent_share", "nack": "nack",
                "evict": "drop_oldest"}[verdict]
        self._m_shed[kind].inc()
        return verdict

    def note_enqueued(self, agent_id: str) -> None:
        with self._lock:
            self._depth += 1
            self._per_agent[agent_id] = self._per_agent.get(agent_id, 0) + 1

    def note_dequeued(self, agent_id: str) -> None:
        with self._lock:
            self._depth = max(0, self._depth - 1)
            n = self._per_agent.get(agent_id, 0) - 1
            if n > 0:
                self._per_agent[agent_id] = n
            else:
                self._per_agent.pop(agent_id, None)

    def accounting(self) -> dict:
        with self._lock:
            return {"depth": self._depth, "sheds": dict(self.sheds),
                    "soft_limit": self.soft_limit, "policy": self.policy}


__all__ = ["AdmissionController", "SHED_POLICIES"]
