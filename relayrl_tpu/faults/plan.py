"""Deterministic, seed-driven fault plans (the chaos-engineering plane).

A :class:`FaultPlan` is a seed plus a list of :class:`FaultRule`\\ s, each
bound to a named hook *site* (``agent.send``, ``agent.model``,
``server.publish``, ``server.ingest``, ``actor.step`` — the sites the
transports and runtime expose; see docs/operations.md "Failure modes &
recovery"). Every decision is a pure function of ``(seed, site, op_index,
rule_index, salt)`` through BLAKE2b — no global RNG, no wall clock — so
the same plan JSON + seed reproduces the exact injection schedule in any
process, interpreter, or machine (``FaultPlan.schedule`` materializes it;
tests/test_faults.py asserts byte-identity).

Fault ops:

* ``drop``            — the frame never reaches the wire / the handler.
* ``delay``           — the frame is held ``delay_s`` before delivery.
* ``duplicate``       — the frame is delivered twice (retry storm shape).
* ``reorder``         — the frame is held back and emitted after the next
                        one (swap-with-next; network reordering shape).
* ``corrupt``         — ``corrupt_bytes`` flips bytes mid-frame (exercises
                        CRC rejection / decode-error narrowing).
* ``nan_poison``      — decodes the trajectory payload, patches finite
                        floats (rewards + tensor elements) to NaN/Inf,
                        and re-encodes — a VALID frame carrying poison
                        data, the guardrail ingest-validation drill
                        (corrupt breaks the envelope; this breaks the
                        *semantics*). Non-trajectory payloads pass
                        through untouched.
* ``flood``           — burst-amplifies the send ``flood_factor``× (the
                        ingest-backpressure / per-agent-fairness drill).
* ``kill_connection`` — the transport abruptly closes its live socket
                        (heal/redial paths take over).
* ``kill_process``    — the hosting process SIGKILLs itself (the actor
                        crash drill; honored only by loops that opt in
                        via ``take_kill_process``).

Rules fire per-op with probability ``prob``, or exactly at op index
``at``; ``after``/``until`` bound the active window and ``count`` caps
total firings. Injection never raises into the host code path — a fault
plane bug must degrade to "no fault", not take down the system under
test.
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading
from dataclasses import dataclass, field

FAULT_OPS = ("drop", "delay", "duplicate", "reorder", "corrupt",
             "nan_poison", "flood", "kill_connection", "kill_process")

#: Hook sites the runtime/transports expose (free-form sites are legal —
#: a rule naming a site nobody hooks simply never fires).
#: ``agent.infer`` is the serving plane's request/response channel
#: (runtime/inference.RemoteActorClient): drop surfaces as a timeout →
#: retry, corrupt dies in the service's decode guard → error reply →
#: retry, delay stalls the attempt — the thin-client chaos drill.
#: The ``relay.*`` trio is the relay node's plane (relayrl_tpu/relay/):
#: ``relay.model`` injects between the upstream subscription and the
#: downstream re-broadcast (corrupt dies in the per-hop CRC check, drop
#: exercises subtree resync-from-cache), ``relay.forward`` between
#: subtree ingest and the upstream batch-forward (spool replay + root
#: dedup must make the loop whole), and ``relay.step`` is where the
#: relay's run loop polls ``kill_process`` — the relay crash drill.
KNOWN_SITES = ("agent.send", "agent.model", "agent.infer",
               "server.publish", "server.ingest", "actor.step",
               "relay.model", "relay.forward", "relay.step")


def _u01(seed: int, site: str, op_index: int, rule_index: int,
         salt: int) -> float:
    """Uniform [0,1) from a keyed BLAKE2b — stable across processes and
    PYTHONHASHSEED (the determinism contract)."""
    h = hashlib.blake2b(
        f"{seed}:{site}:{op_index}:{rule_index}:{salt}".encode(),
        digest_size=8).digest()
    return struct.unpack(">Q", h)[0] / 2.0**64


def corrupt_bytes(payload: bytes, seed: int, site: str,
                  op_index: int) -> bytes:
    """Deterministically flip a few bytes mid-payload (never the first
    byte: frame-type sniffing should survive so the corruption lands in
    the decoder/CRC, the interesting failure)."""
    if len(payload) < 2:
        return b"\xff" + payload
    out = bytearray(payload)
    n_flips = 1 + len(payload) // 4096
    for i in range(n_flips):
        pos = 1 + int(_u01(seed, site, op_index, 10_000 + i, 0)
                      * (len(out) - 1))
        out[pos] ^= 0x5A
    return bytes(out)


def nan_poison_bytes(payload: bytes, seed: int, site: str,
                     op_index: int) -> bytes:
    """Deterministically patch a trajectory payload's finite floats to
    NaN/Inf and re-encode: a frame that stays wire-VALID (envelope, CRC,
    msgpack all intact) but carries semantically poisoned data — the
    guardrail ingest-validation drill. Handles both shapes the hook
    sites see: the ``agent.send`` envelope (``{"id", "traj"}``) and the
    bare ``server.ingest`` trajectory frame. Rewards become NaN and the
    first element of each float obs tensor becomes +/-Inf (alternating
    off the plan hash, so drills exercise both non-finite kinds).
    Anything that fails to decode as a Python-codec trajectory (native
    columnar frames, model bundles, junk) passes through untouched —
    injection must never raise into the host path."""
    try:
        import msgpack
        import numpy as np

        from relayrl_tpu.types.trajectory import (
            deserialize_actions,
            serialize_actions,
        )

        agent_id = None
        body = payload
        try:
            env = msgpack.unpackb(bytes(payload), raw=False)
            if isinstance(env, dict) and "traj" in env:
                agent_id = str(env.get("id", "?"))
                body = env["traj"]
        except Exception:
            pass  # not an envelope: try the bare trajectory frame
        records = deserialize_actions(body)
        if not records:
            return payload
        bad = (np.inf if _u01(seed, site, op_index, 20_000, 0) < 0.5
               else -np.inf)
        for rec in records:
            rec.rew = float("nan")
            obs = rec.obs
            if (isinstance(obs, np.ndarray) and obs.dtype.kind == "f"
                    and obs.size):
                obs = obs.copy()
                obs.flat[0] = bad
                rec.obs = obs
        body = serialize_actions(records)
        if agent_id is not None:
            return msgpack.packb({"id": agent_id, "traj": body},
                                 use_bin_type=True)
        return body
    except Exception:
        return payload


@dataclass
class FaultRule:
    site: str
    op: str
    prob: float = 0.0          # per-op firing probability
    at: int | None = None      # fire exactly at this op index instead
    after: int = 0             # active window: op index >= after
    until: int | None = None   # active window: op index < until
    count: int | None = None   # cap on total firings (None = unbounded)
    delay_s: float = 0.0       # for op == "delay"
    flood_factor: int = 8      # for op == "flood": total copies delivered
    salt: int = 0              # decorrelates rules sharing (site, prob)

    def __post_init__(self):
        if self.op not in FAULT_OPS:
            raise ValueError(f"unknown fault op {self.op!r} "
                             f"(one of {FAULT_OPS})")
        if self.at is None and not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"prob must be in [0,1], got {self.prob}")

    def to_dict(self) -> dict:
        d = {"site": self.site, "op": self.op}
        if self.at is not None:
            d["at"] = self.at
        else:
            d["prob"] = self.prob
        if self.after:
            d["after"] = self.after
        if self.until is not None:
            d["until"] = self.until
        if self.count is not None:
            d["count"] = self.count
        if self.delay_s:
            d["delay_s"] = self.delay_s
        if self.op == "flood" and self.flood_factor != 8:
            d["flood_factor"] = self.flood_factor
        if self.salt:
            d["salt"] = self.salt
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        return cls(site=str(d["site"]), op=str(d["op"]),
                   prob=float(d.get("prob", 0.0)),
                   at=(None if d.get("at") is None else int(d["at"])),
                   after=int(d.get("after", 0)),
                   until=(None if d.get("until") is None
                          else int(d["until"])),
                   count=(None if d.get("count") is None
                          else int(d["count"])),
                   delay_s=float(d.get("delay_s", 0.0)),
                   flood_factor=int(d.get("flood_factor", 8)),
                   salt=int(d.get("salt", 0)))

    def fires(self, seed: int, op_index: int, fired_so_far: int) -> bool:
        """Pure decision for one op — the determinism kernel."""
        if op_index < self.after:
            return False
        if self.until is not None and op_index >= self.until:
            return False
        if self.count is not None and fired_so_far >= self.count:
            return False
        if self.at is not None:
            return op_index == self.at
        if self.prob <= 0.0:
            return False
        return _u01(seed, self.site, op_index,
                    id_stable(self), self.salt) < self.prob


def id_stable(rule: FaultRule) -> int:
    """A rule's stable index-within-plan substitute: plans key decisions
    by the rule's position, set by FaultPlan at construction."""
    return getattr(rule, "_plan_index", 0)


@dataclass
class _Decision:
    """What a site injector decided for one op (returned by schedule)."""

    op_index: int
    ops: list  # fired op names, in rule order

    def to_dict(self) -> dict:
        return {"i": self.op_index, "ops": list(self.ops)}


#: Decision domains: each entry point advances its OWN op counter and
#: decides only the rules it can actually apply — ``inject`` the payload
#: ops, ``take_kill_connection``/``take_kill_process`` their kill op.
#: Without the split, a send site polling kills before injecting would
#: consume two indices per op, and a fired-but-unapplied rule would
#: corrupt the injection ledger (counted faults that never happened).
_OP_CLASS = {"drop": "payload", "delay": "payload",
             "duplicate": "payload", "reorder": "payload",
             "corrupt": "payload", "nan_poison": "payload",
             "flood": "payload", "kill_connection": "kill_connection",
             "kill_process": "kill_process"}


class SiteInjector:
    """Per-site fault applicator: owns per-domain op counters and the
    reorder hold-back buffer. Thread-safe (transports may hit one site
    from several threads). Obtain via :meth:`FaultPlan.site`."""

    def __init__(self, plan: "FaultPlan", site: str,
                 rules: list[FaultRule]):
        self._plan = plan
        self.site = site
        self._rules = rules
        self._lock = threading.Lock()
        self._op_index = {"payload": 0, "kill_connection": 0,
                          "kill_process": 0}
        self._fired = [0] * len(rules)
        self._held: list[bytes] = []  # reorder hold-back
        self.injected = 0  # total faults fired (observable for tests)
        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m = {
            op: reg.counter(
                "relayrl_faults_injected_total",
                "fault-plan injections fired at hook sites",
                {"site": site, "op": op})
            for op in FAULT_OPS
        }

    def _decide(self, domain: str) -> list[FaultRule]:
        """Advance ``domain``'s op counter and return its fired rules
        (in rule order), so appliers see each rule's own parameters
        (delay_s). Every returned rule WILL be applied by the caller —
        the ledger invariant."""
        with self._lock:
            k = self._op_index[domain]
            self._op_index[domain] += 1
            fired = []
            for i, rule in enumerate(self._rules):
                if (_OP_CLASS[rule.op] == domain
                        and rule.fires(self._plan.seed, k, self._fired[i])):
                    self._fired[i] += 1
                    fired.append(rule)
            if fired:
                self.injected += len(fired)
        for rule in fired:
            self._m[rule.op].inc()
        if fired:
            from relayrl_tpu import telemetry

            telemetry.emit("fault_injected", site=self.site,
                           ops=[r.op for r in fired], op_index=k)
        return fired

    def inject(self, payload: bytes) -> list[tuple[float, bytes]]:
        """Run one payload through the plan: returns ``[(delay_s,
        payload), ...]`` for the caller to deliver in order (empty =
        dropped). ``corrupt`` mutates bytes; ``duplicate`` doubles the
        entry; ``reorder`` holds this payload back and prepends it to the
        NEXT op's delivery; ``delay`` attaches a sleep the caller honors
        OUTSIDE any lock. kill ops are not applied here — poll
        :meth:`take_kill_connection` / :meth:`take_kill_process`."""
        if not self._plan.active:
            # deactivated plan: pass-through, but still release any
            # reorder hold-back so no frame is stranded
            with self._lock:
                held, self._held = self._held, []
            return [(0.0, h) for h in held] + [(0.0, payload)]
        fired = self._decide("payload")
        k = self._op_index["payload"] - 1
        delay = 0.0
        out_payload = payload
        copies = 1
        dropped = reordered = False
        for rule in fired:
            if rule.op == "drop":
                dropped = True
            elif rule.op == "delay":
                delay += rule.delay_s  # several delay rules stack
            elif rule.op == "duplicate":
                copies += 1
            elif rule.op == "reorder":
                reordered = True
            elif rule.op == "corrupt":
                out_payload = corrupt_bytes(out_payload, self._plan.seed,
                                            self.site, k)
            elif rule.op == "nan_poison":
                out_payload = nan_poison_bytes(out_payload,
                                               self._plan.seed,
                                               self.site, k)
            elif rule.op == "flood":
                # Burst-amplify: this op delivers flood_factor copies in
                # one call (stacks multiplicatively with duplicate — a
                # retry storm atop a flood is a legal drill).
                copies *= max(1, int(rule.flood_factor))
        with self._lock:
            held, self._held = self._held, []
        out: list[tuple[float, bytes]] = [(0.0, h) for h in held]
        if dropped:
            return out
        if reordered:
            with self._lock:
                self._held.append(out_payload)
            return out
        out.extend((delay, out_payload) for _ in range(copies))
        return out

    def _take_kill(self, op: str) -> bool:
        if not self._plan.active:
            return False
        # Cheap short-circuit: a site with no rules of this kill kind
        # must not advance the domain counter at all (the common case —
        # payload-only plans polled by send paths every op).
        if not any(_OP_CLASS[r.op] == op for r in self._rules):
            return False
        return any(rule.op == op for rule in self._decide(op))

    def take_kill_connection(self) -> bool:
        """Poll-style check for connection kills (its own op domain —
        polling it never perturbs the payload-op schedule)."""
        return self._take_kill("kill_connection")

    def take_kill_process(self) -> bool:
        """Poll-style check for process kills (its own op domain)."""
        return self._take_kill("kill_process")


class FaultPlan:
    """Seed + rules; JSON round-trippable; hands out per-site injectors."""

    def __init__(self, seed: int = 0, rules: list[FaultRule] | None = None):
        self.seed = int(seed)
        # Kill switch: hook sites cache their SiteInjector, so "stop
        # injecting" must be a flag those injectors consult — the chaos
        # harness deactivates the plan before its convergence phase
        # (faults stop, the system must heal; the standard chaos-
        # engineering shape).
        self.active = True
        self.rules = list(rules or [])
        for i, rule in enumerate(self.rules):
            rule._plan_index = i  # stable decision key (see id_stable)
        self._site_injectors: dict[str, SiteInjector] = {}
        self._lock = threading.Lock()

    # -- construction / serialization --
    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "rules": [r.to_dict() for r in self.rules]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(seed=int(d.get("seed", 0)),
                   rules=[FaultRule.from_dict(r)
                          for r in d.get("rules", [])])

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path, "r") as f:
            return cls.from_dict(json.load(f))

    # -- injector surface --
    def site(self, site: str) -> SiteInjector | None:
        """The injector for ``site``, or None when no rule targets it —
        hook points keep a None and pay a single identity check per op."""
        rules = [r for r in self.rules if r.site == site]
        if not rules:
            return None
        with self._lock:
            inj = self._site_injectors.get(site)
            if inj is None:
                inj = SiteInjector(self, site, rules)
                self._site_injectors[site] = inj
            return inj

    def injected_total(self) -> int:
        with self._lock:
            return sum(i.injected for i in self._site_injectors.values())

    # -- determinism surface --
    def schedule(self, site: str, n_ops: int) -> list[dict]:
        """Materialize the injection schedule for ``site`` over ops
        ``0..n_ops-1`` WITHOUT consuming any live injector state: the
        reproducibility artifact (same seed + plan → byte-identical
        ``json.dumps(schedule)``). Op indices are per decision DOMAIN
        (payload vs each kill kind — see _OP_CLASS), exactly matching
        the live injector's counters: entry ``{"i": k, "ops": [...]}``
        merges whatever fires at index ``k`` of any domain."""
        rules = [r for r in self.rules if r.site == site]
        fired = [0] * len(rules)
        by_index: dict[int, list[str]] = {}
        for domain in ("payload", "kill_connection", "kill_process"):
            for k in range(n_ops):
                for i, rule in enumerate(rules):
                    if (_OP_CLASS[rule.op] == domain
                            and rule.fires(self.seed, k, fired[i])):
                        fired[i] += 1
                        by_index.setdefault(k, []).append(rule.op)
        return [_Decision(k, by_index[k]).to_dict()
                for k in sorted(by_index)]


__all__ = ["FAULT_OPS", "KNOWN_SITES", "FaultRule", "FaultPlan",
           "SiteInjector", "corrupt_bytes", "nan_poison_bytes"]
