"""Fault-injection plane (chaos engineering for the actor↔learner loop).

Process model mirrors :mod:`relayrl_tpu.telemetry`: at most ONE
:class:`~relayrl_tpu.faults.plan.FaultPlan` per process, installed
explicitly (:func:`install_plan`) or from the ``RELAYRL_FAULT_PLAN`` env
var — a path to a plan JSON — via :func:`maybe_install_from_env`, which
every config-bearing runtime component (TrainingServer, Agent,
VectorAgent) calls at construction. With no plan installed every hook
site resolves to ``None`` and the hot-path cost is one identity check
per operation; production processes that never set the env var pay
nothing and can never fault themselves.

Hook sites (see plan.KNOWN_SITES and docs/operations.md):

* ``agent.send``     — trajectory envelopes leaving an agent transport
* ``agent.model``    — model frames arriving at an agent transport
* ``agent.infer``    — serving-plane action requests leaving a thin
  client (RemoteActorClient; drop → timeout-retry, corrupt → service
  decode guard → error reply → retry)
* ``server.publish`` — model frames leaving the server transport
* ``server.ingest``  — trajectory envelopes arriving at the server
* ``actor.step``     — env-loop steps (kill_process drills)

Every injection increments ``relayrl_faults_injected_total{site,op}``
and lands a ``fault_injected`` event in the run journal, so a chaos
artifact carries its own injection ledger alongside the recovery
counters it provoked.
"""

from __future__ import annotations

import os
import threading

from relayrl_tpu.faults.plan import (  # noqa: F401
    FAULT_OPS,
    KNOWN_SITES,
    FaultPlan,
    FaultRule,
    SiteInjector,
    corrupt_bytes,
)

_lock = threading.Lock()
_plan: FaultPlan | None = None

ENV_VAR = "RELAYRL_FAULT_PLAN"


def install_plan(plan: FaultPlan | None) -> FaultPlan | None:
    """Install (or clear, with None) the process fault plan. Components
    constructed AFTER the install see its sites; the chaos harness
    installs before building agents/servers."""
    global _plan
    with _lock:
        _plan = plan
        return _plan


def get_plan() -> FaultPlan | None:
    return _plan


def maybe_install_from_env() -> FaultPlan | None:
    """Idempotently install the plan named by ``RELAYRL_FAULT_PLAN``
    (a JSON file path). A missing/unreadable file degrades loudly to
    no-plan: the fault plane must never take down the process it tests."""
    global _plan
    path = os.environ.get(ENV_VAR)
    if not path:
        return _plan
    with _lock:
        if _plan is not None:
            return _plan
        try:
            _plan = FaultPlan.from_file(path)
            print(f"[faults] plan installed from {path}: seed="
                  f"{_plan.seed}, {len(_plan.rules)} rule(s)", flush=True)
        except Exception as e:
            # ANY malformed plan (bad JSON, wrong types, a list root —
            # TypeError territory, not just ValueError) must degrade to
            # no-plan: this runs inside Agent/TrainingServer
            # constructors, and the fault plane must never take down the
            # process it tests.
            print(f"[faults] plan at {path} unusable ({e!r}) — running "
                  f"fault-free", flush=True)
        return _plan


def deactivate() -> None:
    """Stop all injection (cached site injectors pass through from the
    next op on). The chaos harness calls this before its convergence
    phase: faults stop, then the system must prove it heals."""
    plan = _plan
    if plan is not None:
        plan.active = False


def site(name: str) -> SiteInjector | None:
    """The installed plan's injector for ``name``, or None (the common
    case — hook points cache this at construction)."""
    plan = _plan
    return None if plan is None else plan.site(name)


def reset_for_tests() -> None:
    global _plan
    with _lock:
        _plan = None


__all__ = [
    "FAULT_OPS", "KNOWN_SITES", "FaultPlan", "FaultRule", "SiteInjector",
    "corrupt_bytes", "install_plan", "get_plan", "maybe_install_from_env",
    "site", "deactivate", "reset_for_tests", "ENV_VAR",
]
