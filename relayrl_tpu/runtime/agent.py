"""The agent (actor) process: local policy inference + trajectory streaming
+ model hot-swap.

Capability parity with the reference's agent stack
(reference: relayrl_framework/src/network/client/agent_wrapper.rs:213-270
facade; agent_zmq.rs / agent_grpc.rs; PyO3 surface
src/bindings/python/network/client/o3_agent.rs:49-330 —
``RelayRLAgent(model_path, config_path, server_type, ...)``,
``request_for_action(obs, mask, reward)``, ``flag_last_action(reward)``,
``record_action``, restart/enable/disable).

Bring-up mirrors the reference handshake (agent_zmq.rs:316-442): fetch model
→ validate with a dummy forward → persist to ``client_model`` path →
register → start the model listener. Hot-swaps are version-gated and
arch-checked (the reference's version field is unimplemented server-side —
training_grpc.rs:722-725; here it's real).
"""

from __future__ import annotations

import os

import numpy as np

from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.runtime.policy_actor import PolicyActor
from relayrl_tpu.transport import make_agent_transport
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle


def _deliver_model(actor_host, transport, client_model_path: str, tag: str,
                   version: int, blob: bytes) -> None:
    """Shared model-delivery handler for Agent and VectorAgent (both own
    one subscription feeding one wire-aware swap): sniffing decode via
    ``swap_from_wire``, resync on a base mismatch (raised once per
    divergence — pull transports re-poll with ``ver=-1``, broadcast
    transports wait out the keyframe interval), isolation of any other
    decode/validation failure, and the client-model persist on install.
    One body, so resync semantics can never drift between the two
    actor-host kinds."""
    from relayrl_tpu.transport.modelwire import WireBaseMismatch

    try:
        installed = actor_host.swap_from_wire(version, blob)
    except WireBaseMismatch as e:
        from relayrl_tpu import telemetry

        telemetry.emit("model_resync", agent_id=transport.identity,
                       base=e.base, held=e.held, side="agent")
        # The held version rides the request: a relay serves a late
        # joiner from cache but must ESCALATE a subscriber newer than
        # its cached keyframe (stale keyframes are dropped by decoders).
        transport.request_resync(e.held)
        return
    except Exception as e:
        print(f"[{tag}] rejected model update: {e!r}", flush=True)
        return
    if installed is not None:
        try:
            installed.save(client_model_path)
        except OSError:
            pass


def _trace_emit(agent_id: str, born_ns: int, enc0_ns: int, enc1_ns: int,
                version: int):
    """Distributed-tracing emission hook shared by Agent and VectorAgent
    (telemetry/trace.py): sample a trajectory trace context and record
    the actor-side ``env`` (production) and ``encode`` (serialize) hop
    spans. Returns the context (riding the wire as the ``#t`` id tag)
    or None — one tracer read per *trajectory*, never per step."""
    from relayrl_tpu.telemetry import trace as trace_mod

    tracer = trace_mod.get_tracer()
    if not tracer.enabled or not born_ns:
        return None
    ctx = tracer.sample_traj(born_ns, version)
    if ctx is None:
        return None
    import time

    now = time.monotonic_ns()
    enc0 = enc0_ns if born_ns <= enc0_ns <= now else now
    enc1 = max(enc0, min(enc1_ns, now)) if enc1_ns else enc0
    tracer.span("traj", ctx.trace_id, "env", born_ns, enc0,
                agent=agent_id, version=int(version))
    if enc1 > enc0:
        tracer.span("traj", ctx.trace_id, "encode", enc0, enc1,
                    agent=agent_id)
    return ctx


def _trace_send_span(ctx, agent_id: str, t0_ns: int) -> None:
    if ctx is None:
        return
    import time

    from relayrl_tpu.telemetry import trace as trace_mod

    trace_mod.get_tracer().span("traj", ctx.trace_id, "send", t0_ns,
                                time.monotonic_ns(), agent=agent_id)


def _bind_spool_impl(owner, name: str) -> None:
    """Create (first enable) or re-bind (restart) the owner's trajectory
    spool (runtime/spool.py). Shared by Agent and VectorAgent so the
    spool lifecycle — survives restart_agent with its seq counters and
    retained window intact, send_fn re-bound to the fresh transport —
    exists exactly once. ``actor.spool_entries: 0`` disables the spool
    (sends go straight to the transport, untagged)."""
    params = owner.config.get_actor_params()
    if params["spool_entries"] <= 0:
        owner.spool = None
        return

    def send_fn(payload: bytes, tagged_id: str) -> None:
        owner.transport.send_trajectory(payload, agent_id=tagged_id)

    if owner.spool is None:
        from relayrl_tpu.runtime.spool import TrajectorySpool
        from relayrl_tpu.transport.retry import breaker_from_config

        retry_cfg = owner.config.get_transport_params()["retry"]
        owner.spool = TrajectorySpool(
            send_fn=send_fn,
            max_entries=params["spool_entries"],
            max_bytes=params["spool_bytes"],
            directory=params["spool_dir"],
            name=name,
            breaker=breaker_from_config(f"agent:{name}", retry_cfg),
        )
        if params["spool_dir"] and owner.spool.depth:
            # A prior process life left trajectories in flight (actor
            # crash drill): replay them now that a transport is live.
            owner.spool.replay()
    else:
        owner.spool.send_fn = send_fn


def _start_fleet_emitter(owner, tier: str):
    """Start the per-process fleet snapshot emitter (ISSUE 15,
    telemetry/aggregate.py) when the plane is on: registry live AND
    ``telemetry.fleet_interval_s`` > 0. The frame rides the owner's
    agent transport beside trajectories (no new socket); shared by
    Agent / VectorAgent / RemoteActorClient so the gating and the wire
    id convention exist exactly once. Returns the emitter or None."""
    from relayrl_tpu import telemetry

    reg = telemetry.get_registry()
    try:
        interval = float(owner.config.get_telemetry_params()
                         .get("fleet_interval_s") or 0.0)
    except Exception:
        interval = 0.0
    if not reg.enabled or interval <= 0:
        return None
    from relayrl_tpu.telemetry.aggregate import FleetEmitter

    transport = owner.transport

    def send(frame: bytes, wire_id: str) -> None:
        transport.send_trajectory(frame, agent_id=wire_id)

    return FleetEmitter(send, proc=transport.identity, tier=tier,
                        interval_s=interval, registry=reg)


def _close_fleet_emitter(owner) -> None:
    """Final-frame flush + thread stop BEFORE the transport closes (the
    last frame carries this life's closing totals to the root)."""
    emitter = getattr(owner, "_fleet_emitter", None)
    if emitter is not None:
        emitter.close(final=True)
        owner._fleet_emitter = None


def _handle_reconnect_impl(owner, agent_ids: list[str]) -> None:
    """Shared transport-heal handler: re-register every logical agent
    (the server may have reaped them on kernel close — _on_register
    dedups, so this is idempotent on servers that kept them) and replay
    the spool window (the server's sequence dedup makes the replay
    exactly-once). Runs on a transport thread; failures degrade to the
    next heal rather than killing the listener."""
    from relayrl_tpu import telemetry

    for agent_id in agent_ids:
        try:
            owner.transport.register(agent_id, timeout_s=5.0)
        except Exception as e:
            print(f"[Agent] re-register {agent_id!r} after reconnect "
                  f"failed: {e!r}", flush=True)
    replayed = owner.spool.replay() if owner.spool is not None else 0
    telemetry.emit("agent_reconnect",
                   agent_id=agent_ids[0] if agent_ids else "?",
                   lanes=len(agent_ids), replayed=replayed)


class Agent:
    def __init__(
        self,
        model_path: str | None = None,
        config_path: str | None = None,
        server_type: str = "zmq",
        handshake_timeout_s: float = 60.0,
        seed: int | None = None,
        start: bool = True,
        **addr_overrides,
    ):
        self.config = ConfigLoader(None, config_path)
        # Actor-process observability: idempotent, so an agent living in
        # the server's process joins the registry the server installed.
        from relayrl_tpu import faults, telemetry

        telemetry.configure_from_config(self.config)
        # Fault plan (chaos drills): env-driven install must precede
        # transport construction so its hook sites resolve.
        faults.maybe_install_from_env()
        self.server_type = server_type
        self._addr_overrides = addr_overrides
        self.client_model_path = model_path or self.config.get_client_model_path()
        self._handshake_timeout_s = handshake_timeout_s
        self._seed = os.getpid() if seed is None else seed
        self.actor: PolicyActor | None = None
        self.transport = None
        self.spool = None  # TrajectorySpool, built on first enable
        self._fleet_emitter = None
        self.active = False
        if start:
            self.enable_agent()

    # -- bring-up / lifecycle (ref: agent_zmq.rs:163-300) --
    def enable_agent(self) -> None:
        if self.active:
            return
        # Auto-negotiation may retry-probe until the server binds; give it
        # the agent's own handshake budget rather than a fixed 3s window.
        overrides = dict(self._addr_overrides)
        overrides.setdefault("negotiate_window_s",
                             min(self._handshake_timeout_s * 0.5, 30.0))
        self.transport = make_agent_transport(
            self.server_type, self.config, **overrides)
        version, bundle_bytes = self.transport.fetch_model(self._handshake_timeout_s)
        bundle = ModelBundle.from_bytes(bundle_bytes,
                                        params_template=ModelBundle.RAW_TREE)
        bundle.version = version
        # Persist before loading, like the reference writes client_model.pt
        # (agent_zmq.rs:388-396) — survives restarts / aids debugging.
        try:
            bundle.save(self.client_model_path)
        except OSError:
            pass
        self._bind_spool()
        if self.actor is None:
            self.actor = PolicyActor(
                bundle,
                max_traj_length=self.config.get_max_traj_length(),
                on_send=self._send_traj,
                seed=self._seed,
            )
        else:
            self.actor.maybe_swap(bundle)
            self.actor.trajectory._on_send = self._send_traj
        if not self.transport.register(self.transport.identity):
            raise RuntimeError("agent registration (MODEL_SET/ID_LOGGED) failed")
        self.transport.on_model = self._on_model
        self.transport.on_reconnect = self._handle_reconnect
        self.transport.start_model_listener()
        self._fleet_emitter = _start_fleet_emitter(self, "actor")
        self.active = True
        from relayrl_tpu import telemetry

        telemetry.emit("agent_register", agent_id=self.transport.identity,
                       version=version, side="agent")

    def _send_traj(self, payload: bytes) -> None:
        # Runs inside Trajectory.flush, so the trajectory's born/encode
        # stamps describe exactly the chunk in `payload`.
        traj = self.actor.trajectory
        ctx = _trace_emit(self.transport.identity, traj.born_ns,
                          traj.encode_t0_ns, traj.encode_t1_ns,
                          self.actor.version)
        t0 = 0
        if ctx is not None:
            import time

            t0 = time.monotonic_ns()
        if self.spool is not None:
            self.spool.send(payload, self.transport.identity,
                            trace=None if ctx is None else ctx.encode())
        else:  # actor.spool_entries == 0: the pre-recovery direct path
            from relayrl_tpu.transport.base import IngestNack, tag_agent_trace

            try:
                self.transport.send_trajectory(
                    payload,
                    agent_id=(None if ctx is None else tag_agent_trace(
                        self.transport.identity, ctx.encode())))
            except IngestNack:
                # The server answered with a guardrail verdict
                # (quarantine/overload). Spool-less there is nothing to
                # retain or replay — drop, never crash the env loop
                # (the spooled path routes this through spool._attempt).
                pass
        _trace_send_span(ctx, self.transport.identity, t0)

    def _bind_spool(self) -> None:
        name = self._addr_overrides.get("identity") or "agent"
        _bind_spool_impl(self, name)

    def _handle_reconnect(self) -> None:
        _handle_reconnect_impl(self, [self.transport.identity])

    def disable_agent(self) -> None:
        if not self.active:
            return
        _close_fleet_emitter(self)
        if self.spool is not None:
            # The spool outlives the transport (its retained window and
            # seq counters survive restart_agent); detach the send hook
            # so a send while disabled buffers instead of touching a
            # closed socket.
            self.spool.send_fn = None
        self.transport.close()
        self.transport = None
        self.active = False

    def restart_agent(self, **addr_overrides) -> None:
        from relayrl_tpu import telemetry

        self.disable_agent()
        self._addr_overrides.update(addr_overrides)
        self.enable_agent()
        if self.spool is not None:
            # An explicit restart exists because something broke: replay
            # the retained window (dedup makes it exactly-once).
            self.spool.replay()
        telemetry.emit("agent_reconnect", agent_id=self.transport.identity)

    def _on_model(self, version: int, bundle_bytes: bytes) -> None:
        _deliver_model(self.actor, self.transport, self.client_model_path,
                       "Agent", version, bundle_bytes)

    # -- action API (ref: o3_agent.rs:117-217) --
    def request_for_action(self, obs, mask=None, reward: float = 0.0) -> ActionRecord:
        self._require_active()
        return self.actor.request_for_action(obs, mask, reward)

    def flag_last_action(self, reward: float = 0.0, truncated: bool = False,
                         final_obs=None, terminated: bool | None = None,
                         final_mask=None) -> None:
        self._require_active()
        self.actor.flag_last_action(reward, truncated=truncated,
                                    final_obs=final_obs, terminated=terminated,
                                    final_mask=final_mask)

    def record_action(self, action: ActionRecord) -> None:
        self._require_active()
        self.actor.record_action(action)

    @property
    def model_version(self) -> int:
        return -1 if self.actor is None else self.actor.version

    def _require_active(self) -> None:
        if not self.active or self.actor is None:
            raise RuntimeError("agent is not active (call enable_agent())")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disable_agent()


class VectorAgent:
    """Networked vector actor host: N logical agents over ONE connection.

    The process-topology answer to the north-star "64 actors" row: where
    64 :class:`Agent` processes oversubscribe a host, one VectorAgent
    steps ``num_envs`` environment lanes through a single batched jitted
    policy dispatch (:class:`~relayrl_tpu.runtime.vector_actor.
    VectorActorHost`) and presents each lane to the training server as
    its own logical agent — N registry entries, N attributed trajectory
    streams, one socket, one model subscription, one atomic hot-swap.

    Agent-compatible lifecycle (``enable_agent``/``disable_agent``/
    context manager/``model_version``); the action surface is batched
    (``request_for_actions`` / per-lane ``flag_last_action``) because
    that is the point.

    ``host_mode="anakin"`` (or config ``actor.host_mode: "anakin"``)
    swaps the per-step batched host for the fused on-device rollout
    engine (:class:`~relayrl_tpu.runtime.anakin.AnakinActorHost`): the
    env itself runs as pure JAX (``actor.jax_env``) and the action
    surface becomes :meth:`rollout` — one dispatch per
    ``num_envs × actor.unroll_length`` window. Everything network-side
    is IDENTICAL: N logical lane registrations, N attributed trajectory
    streams through the same spool, one model subscription, one atomic
    swap gate — the server cannot tell the tiers apart.
    """

    def __init__(
        self,
        num_envs: int | None = None,
        model_path: str | None = None,
        config_path: str | None = None,
        server_type: str = "zmq",
        handshake_timeout_s: float = 60.0,
        seed: int | None = None,
        start: bool = True,
        identity: str | None = None,
        host_mode: str | None = None,
        jax_env: str | None = None,
        jax_env_kwargs: dict | None = None,
        unroll_length: int | None = None,
        columnar_wire: bool | None = None,
        async_emit: bool | None = None,
        emit_coalesce_frames: int | None = None,
        window_size: int | None = None,
        record_bver: bool = False,
        send_interceptor=None,
        rng_keys=None,
        **addr_overrides,
    ):
        # Dataflow-stage hook (the RLHF scheduler's seam,
        # rlhf/scheduler.py): when set, every completed lane episode is
        # offered to ``send_interceptor(lane, payload)`` BEFORE the
        # spool/transport path. A non-None return ships immediately
        # (possibly rewritten); None means the stage took ownership and
        # will re-inject via :meth:`emit_lane` once its own work (reward
        # scoring) is done — generate and downstream stages decouple
        # without forking the send path.
        self._send_interceptor = send_interceptor
        # Per-lane PRNG override (vector tier only): the bit-identity
        # locks hand lane 0 the exact key a single PolicyActor carries.
        self._rng_keys = rng_keys
        self.config = ConfigLoader(None, config_path)
        from relayrl_tpu import faults, telemetry

        telemetry.configure_from_config(self.config)
        faults.maybe_install_from_env()
        actor_params = self.config.get_actor_params()
        self.num_envs = int(num_envs if num_envs is not None
                            else actor_params.get("num_envs", 1))
        if self.num_envs < 1:
            raise ValueError(f"num_envs must be >= 1, got {self.num_envs}")
        self.host_mode = str(host_mode if host_mode is not None
                             else actor_params["host_mode"])
        if self.host_mode not in ("vector", "anakin"):
            # A VectorAgent *is* the vector topology; "process" configs
            # constructing one explicitly just mean the batched default.
            self.host_mode = "vector"
        self.jax_env = str(jax_env if jax_env is not None
                           else actor_params["jax_env"])
        # Env-construction kwargs for the anakin tier (e.g. TokenGen's
        # vocab_size/prompt_len/max_new_tokens), forwarded to the JAX
        # env registry; inert on the vector tier (host-bound envs are
        # built by the driver, not the agent).
        self.jax_env_kwargs = dict(jax_env_kwargs or {})
        self.unroll_length = int(unroll_length if unroll_length is not None
                                 else actor_params["unroll_length"])
        # actor.window_size: narrows the sequence-policy rolling window
        # below the model context (anakin scan carry; the vector host
        # sizes its windows from the model arch directly).
        self.window_size = (actor_params.get("window_size")
                            if window_size is None else window_size)
        # Per-token behavior-version evidence (RLHF): stamp ``bver``
        # into each record's aux on the anakin tier.
        self.record_bver = bool(record_bver)
        # actor.columnar_wire: "auto" -> columnar frames on the anakin
        # tier (whole-segment frames decoded server-side straight into
        # the staging slabs), per-record wire on the host-bound tiers.
        if columnar_wire is None:
            columnar_wire = actor_params.get("columnar_wire", "auto")
        self.columnar_wire = (self.host_mode == "anakin"
                              if not isinstance(columnar_wire, bool)
                              else bool(columnar_wire))
        # actor.async_emit: off-thread frame emitter on the anakin tier
        # (the ROADMAP item 1 host shave); inert on the vector tier.
        self.async_emit = bool(actor_params.get("async_emit", False)
                               if async_emit is None else async_emit)
        # actor.emit_coalesce_frames: pack several completed columnar
        # segments per lane into one send (inert on the vector tier).
        self.emit_coalesce_frames = max(1, int(
            actor_params.get("emit_coalesce_frames", 1)
            if emit_coalesce_frames is None else emit_coalesce_frames))
        self.server_type = server_type
        self._addr_overrides = addr_overrides
        self._identity = identity
        self.client_model_path = (model_path
                                  or self.config.get_client_model_path())
        self._handshake_timeout_s = handshake_timeout_s
        self._seed = os.getpid() if seed is None else seed
        self.host = None
        self.transport = None
        self.spool = None
        self._fleet_emitter = None
        self.agent_ids: list[str] = []
        self.active = False
        if start:
            self.enable_agent()

    def enable_agent(self) -> None:
        if self.active:
            return
        from relayrl_tpu.runtime.vector_actor import VectorActorHost

        overrides = dict(self._addr_overrides)
        overrides.setdefault("negotiate_window_s",
                             min(self._handshake_timeout_s * 0.5, 30.0))
        if self._identity is not None:
            overrides.setdefault("identity", self._identity)
        self.transport = make_agent_transport(
            self.server_type, self.config, **overrides)
        version, bundle_bytes = self.transport.fetch_model(
            self._handshake_timeout_s)
        bundle = ModelBundle.from_bytes(bundle_bytes,
                                        params_template=ModelBundle.RAW_TREE)
        bundle.version = version
        try:
            bundle.save(self.client_model_path)
        except OSError:
            pass
        # Lane ids derive from the connection identity so a fleet of
        # vector hosts never collides; the server sees N distinct agents.
        self.agent_ids = [f"{self.transport.identity}.lane{k}"
                          for k in range(self.num_envs)]
        _bind_spool_impl(self, self._identity or "vector")
        if self.host is not None and hasattr(self.host, "start_emitter"):
            # Re-enable after a disable: the emitter thread was closed
            # with the transport; a reused host needs it back.
            self.host.start_emitter()
        if self.host is None:
            if self.host_mode == "anakin":
                from relayrl_tpu.runtime.anakin import AnakinActorHost

                self.host = AnakinActorHost(
                    bundle,
                    env=self.jax_env,
                    num_envs=self.num_envs,
                    unroll_length=self.unroll_length,
                    max_traj_length=self.config.get_max_traj_length(),
                    on_send=self._send_lane,
                    seed=self._seed,
                    rng_keys=self._rng_keys,
                    columnar_wire=self.columnar_wire,
                    async_emit=self.async_emit,
                    emit_coalesce_frames=self.emit_coalesce_frames,
                    window_size=self.window_size,
                    record_bver=self.record_bver,
                    **self.jax_env_kwargs,
                )
            else:
                self.host = VectorActorHost(
                    bundle,
                    num_envs=self.num_envs,
                    max_traj_length=self.config.get_max_traj_length(),
                    on_send=self._send_lane,
                    seed=self._seed,
                    rng_keys=self._rng_keys,
                )
        else:
            self.host.maybe_swap(bundle)
        # One registration round-trip per logical lane, all over the one
        # connection (the transports' multi-id contract, base.py).
        for agent_id in self.agent_ids:
            if not self.transport.register(agent_id):
                raise RuntimeError(
                    f"logical-agent registration failed for {agent_id!r}")
        self.transport.on_model = self._on_model
        self.transport.on_reconnect = (
            lambda: _handle_reconnect_impl(self, self.agent_ids))
        self.transport.start_model_listener()
        self._fleet_emitter = _start_fleet_emitter(self, "actor")
        self.active = True
        from relayrl_tpu import telemetry

        telemetry.emit("agent_register", agent_id=self.transport.identity,
                       lanes=self.num_envs, version=version, side="agent")

    def disable_agent(self) -> None:
        if not self.active:
            return
        if hasattr(self.host, "close"):
            # Async-emit anakin hosts: drain queued windows onto the
            # wire, then stop the emitter thread — a disable/enable
            # cycle must not leak one thread (and one pinned host) per
            # cycle; enable_agent restarts it via start_emitter.
            self.host.close()
        _close_fleet_emitter(self)
        if self.spool is not None:
            self.spool.send_fn = None  # see Agent.disable_agent
        self.transport.close()
        self.transport = None
        self.active = False

    def _send_lane(self, lane: int, payload: bytes) -> None:
        # Emission stamps read BEFORE the interceptor (it may withhold
        # and re-inject much later, when the host's stamps describe a
        # different episode — re-injected payloads trace through the
        # RLHF plane's own stage spans instead).
        stamps = self._emit_stamps(lane)
        if self._send_interceptor is not None:
            payload = self._send_interceptor(lane, payload)
            if payload is None:
                return  # the stage owns it now; emit_lane re-injects
        self.emit_lane(lane, payload, _stamps=stamps)

    def _emit_stamps(self, lane: int):
        """(born_ns, encode_t0_ns, encode_t1_ns) for the payload being
        emitted right now, or None when tracing is off: anakin columnar
        hosts stamp ``_last_emit_stamps`` per frame; the per-record
        tiers read the lane trajectory's chunk stamps (we are inside
        its flush)."""
        from relayrl_tpu.telemetry import trace as trace_mod

        if not trace_mod.get_tracer().enabled:
            return None
        host = self.host
        stamps = getattr(host, "_last_emit_stamps", None)
        if stamps is not None:
            return stamps
        trajs = getattr(host, "trajectories", None)
        if trajs is None:
            return None
        traj = trajs[lane]
        return (traj.born_ns, traj.encode_t0_ns, traj.encode_t1_ns)

    def emit_lane(self, lane: int, payload: bytes, _stamps=None) -> None:
        """Ship one lane's serialized episode through the normal
        spool/seq/transport path — the re-injection surface for a
        ``send_interceptor`` stage (the RLHF score stage emits here
        after assigning the terminal reward). Spool sequence numbers are
        assigned HERE, so withheld episodes only enter the at-least-once
        window once they are final — a replay after a crash redelivers
        the scored bytes, never the unscored ones."""
        ctx = None
        t0 = 0
        if _stamps is not None:
            born_ns, enc0, enc1 = _stamps
            ctx = _trace_emit(self.agent_ids[lane], born_ns, enc0, enc1,
                              self.host.version)
            if ctx is not None:
                import time

                t0 = time.monotonic_ns()
        if self.spool is not None:
            self.spool.send(payload, self.agent_ids[lane],
                            trace=None if ctx is None else ctx.encode())
        else:
            from relayrl_tpu.transport.base import IngestNack, tag_agent_trace

            try:
                self.transport.send_trajectory(
                    payload,
                    agent_id=(self.agent_ids[lane] if ctx is None
                              else tag_agent_trace(self.agent_ids[lane],
                                                   ctx.encode())))
            except IngestNack:
                pass  # guardrail verdict, spool-less: drop (see Agent)
        _trace_send_span(ctx, self.agent_ids[lane], t0)

    def _on_model(self, version: int, bundle_bytes: bytes) -> None:
        # ONE receipt serves all lanes: a single wire-aware swap
        # atomically installs the new params for the whole batch.
        _deliver_model(self.host, self.transport, self.client_model_path,
                       "VectorAgent", version, bundle_bytes)

    # -- batched action API --
    def request_for_actions(self, obs, masks=None, rewards=None):
        self._require_active()
        if self.host_mode == "anakin":
            raise RuntimeError(
                "anakin host: the env steps on-device inside rollout() — "
                "there is no per-step action request surface")
        return self.host.request_for_actions(obs, masks=masks,
                                             rewards=rewards)

    # -- fused rollout API (host_mode="anakin") --
    def rollout(self) -> dict:
        """One fused ``[num_envs, unroll_length]`` on-device window:
        dispatch + unstack into the N logical-agent trajectory streams
        (see :meth:`AnakinActorHost.rollout`)."""
        self._require_active()
        if self.host_mode != "anakin":
            raise RuntimeError(
                "rollout() is the anakin-host surface; this agent runs "
                f"host_mode={self.host_mode!r} (per-step "
                "request_for_actions)")
        return self.host.rollout()

    def flag_last_action(self, lane: int, reward: float = 0.0,
                         truncated: bool = False, final_obs=None,
                         terminated: bool | None = None,
                         final_mask=None) -> None:
        self._require_active()
        if self.host_mode == "anakin":
            raise RuntimeError(
                "anakin host: episode boundaries happen in-scan "
                "(autoreset) — terminal markers are emitted by the "
                "window unstacker, not by the driver")
        self.host.flag_last_action(lane, reward, truncated=truncated,
                                   final_obs=final_obs,
                                   terminated=terminated,
                                   final_mask=final_mask)

    @property
    def model_version(self) -> int:
        return -1 if self.host is None else self.host.version

    def _require_active(self) -> None:
        if not self.active or self.host is None:
            raise RuntimeError(
                "vector agent is not active (call enable_agent())")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disable_agent()


def run_gym_loop(agent: Agent, env, episodes: int, max_steps: int = 1000,
                 seed: int | None = None) -> list[float]:
    """The reference's canonical notebook loop (examples/README.md:125-152):
    request_for_action → env.step → flag_last_action."""
    returns = []
    for ep in range(episodes):
        obs, _ = env.reset(seed=None if seed is None else seed + ep)
        ep_ret, reward = 0.0, 0.0
        terminated = truncated = False
        for _ in range(max_steps):
            record = agent.request_for_action(obs, reward=reward)
            obs, reward, terminated, truncated, _ = env.step(
                coerce_env_action(record.act))
            ep_ret += float(reward)
            if terminated or truncated:
                break
        # A time-limit ending (env truncation or this loop's max_steps cap)
        # ships the post-step obs so value targets bootstrap through it; a
        # genuine terminal takes precedence even when both flags are set.
        time_limited = not terminated
        agent.flag_last_action(reward, truncated=time_limited,
                               final_obs=obs if time_limited else None)
        returns.append(ep_ret)
    return returns


def coerce_env_action(act) -> object:
    """Wire action → what ``env.step`` expects: python scalar for 0-d
    (int for integer dtypes, float otherwise), ndarray for vectors."""
    arr = np.asarray(act)
    if arr.ndim == 0:
        return int(arr) if np.issubdtype(arr.dtype, np.integer) else float(arr)
    return arr


def greedy_episodes(actor, env, episodes: int, max_steps: int = 1000,
                    seed: int | None = None) -> list[float]:
    """The shared deterministic-eval loop: greedy actions, nothing recorded
    or shipped to the learner. Refuses to run mid-episode — a sampling
    episode in flight would be silently corrupted by the window/cache
    resets (finish it with ``flag_last_action`` first); any stale eval
    serving state is cleared up front."""
    if actor.trajectory.get_actions():
        raise RuntimeError(
            "greedy eval requested mid-episode: the current sampling "
            "episode has unsent steps — call flag_last_action first")
    actor.reset_episode()
    returns = []
    for ep in range(episodes):
        obs, _ = env.reset(seed=None if seed is None else seed + ep)
        ep_ret = 0.0
        for _ in range(max_steps):
            act = actor.deterministic_action(obs)
            obs, reward, terminated, truncated, _ = env.step(
                coerce_env_action(act))
            ep_ret += float(reward)
            if terminated or truncated:
                break
        actor.reset_episode()
        returns.append(ep_ret)
    return returns


def run_eval_loop(agent: Agent, env, episodes: int,
                  max_steps: int = 1000,
                  seed: int | None = None) -> list[float]:
    """Deterministic (greedy) evaluation episodes through a networked
    Agent — the policy is probed, not trained (the reference has no eval
    path at all; its only loop is the training notebook loop)."""
    agent._require_active()
    return greedy_episodes(agent.actor, env, episodes, max_steps, seed)
