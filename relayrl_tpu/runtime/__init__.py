"""Runtime processes: actors, vector actor hosts, local runner, training
server, and the batched-inference serving plane."""

from relayrl_tpu.runtime.application import ApplicationAbstract
from relayrl_tpu.runtime.policy_actor import PolicyActor
from relayrl_tpu.runtime.local_runner import LocalRunner

__all__ = ["ApplicationAbstract", "PolicyActor", "LocalRunner",
           "VectorActorHost", "VectorAgent", "InferenceService",
           "RemoteActorClient", "StandaloneInferenceHost"]


def __getattr__(name):
    if name in ("TrainingServer", "Agent", "VectorAgent"):
        from relayrl_tpu.runtime import server as _server, agent as _agent

        return {"TrainingServer": _server.TrainingServer,
                "Agent": _agent.Agent,
                "VectorAgent": _agent.VectorAgent}[name]
    if name == "VectorActorHost":
        from relayrl_tpu.runtime import vector_actor as _va

        return _va.VectorActorHost
    if name in ("InferenceService", "RemoteActorClient",
                "StandaloneInferenceHost"):
        from relayrl_tpu.runtime import inference as _inf

        return getattr(_inf, name)
    raise AttributeError(f"module 'relayrl_tpu.runtime' has no attribute {name!r}")
