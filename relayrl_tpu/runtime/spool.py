"""Delivery correctness under churn: actor trajectory spool + server
sequence ledger (the two halves of exactly-once trajectory training).

**Actor half — :class:`TrajectorySpool`.** Every outbound trajectory gets
a per-agent monotonic sequence number (riding the wire as an envelope-id
suffix, :func:`~relayrl_tpu.transport.base.tag_agent_seq`) and is
retained in a bounded in-memory (optionally file-backed) window BEFORE
the send is attempted. Sends run under a short
:class:`~relayrl_tpu.transport.retry.RetryPolicy` behind a
:class:`~relayrl_tpu.transport.retry.CircuitBreaker`: while the learner
is down the breaker opens and the actor keeps stepping at full speed,
spooling instead of blocking; the half-open probe notices the restart,
and :meth:`replay` re-ships the whole retained window in order. Replay is
*at-least-once* by design — a trajectory that was already delivered goes
out again — which is exactly what makes it safe to fire on every
reconnect signal, because of the second half:

**Server half — :class:`SequenceLedger`.** Per-agent monotonic
acceptance with a bounded dedup window: a sequence number is accepted at
most once; replays and duplicate-injection faults drop with a counter.
Ledger state snapshots to a JSON sidecar alongside each learner
checkpoint (keyed by model version), so a learner SIGKILL → orbax resume
restores the dedup state CONSISTENT with the restored params:
trajectories trained after the restored checkpoint are absent from the
restored ledger and therefore re-accepted on replay — correct, since the
updates they fed were rolled back with the params — while trajectories
the restored params already learned from stay deduplicated. Zero loss,
zero double-training, asserted end-to-end by tests/test_recovery.py and
``bench_soak --chaos``.

The spool file format (``dir`` given) is a flat append log:
``SPL1`` magic, then per record ``u32 total_len | u32 seq | u16 id_len |
id | payload``. Loads tolerate a torn tail (the crash case). Compaction
rewrites the retained window when the log grows past twice the byte
bound.
"""

from __future__ import annotations

import io
import os
import struct
import threading
import time

_MAGIC = b"SPL1"
_REC_HDR = struct.Struct(">IIH")  # total_len, seq, id_len


class TrajectorySpool:
    """Bounded at-least-once send buffer for one agent connection
    (covering all its logical lanes — per-lane ids key the seq spaces).

    ``send_fn(payload: bytes, tagged_agent_id: str)`` performs one wire
    attempt (the agent binds it to ``transport.send_trajectory``); it may
    raise. ``None`` disables wire sends entirely (buffer-only mode, used
    by tests).
    """

    def __init__(self, send_fn=None, max_entries: int = 512,
                 max_bytes: int = 64 << 20, directory: str | None = None,
                 name: str = "spool", retry=None, breaker=None):
        from relayrl_tpu import telemetry
        from relayrl_tpu.transport.retry import CircuitBreaker, RetryPolicy

        self.send_fn = send_fn
        self.max_entries = max(1, int(max_entries))
        self.max_bytes = max(1 << 16, int(max_bytes))
        # Send attempts must not stall the actor's env loop for long: a
        # tight default budget (two tries inside ~1s) — persistent
        # failure is the breaker's job, not backoff's.
        self.retry = retry if retry is not None else RetryPolicy(
            base_delay_s=0.05, max_delay_s=0.25, deadline_s=1.0,
            max_attempts=2)
        self.breaker = breaker if breaker is not None else CircuitBreaker(
            f"spool:{name}", failure_threshold=3, reset_timeout_s=2.0)
        self._lock = threading.Lock()
        # (agent_id, seq, payload); seq None = verbatim entry (the id
        # ships as-is, no tag — relay forwards, see send_verbatim)
        self._entries: list[tuple[str, int | None, bytes]] = []
        # Overload-nack backoff: entries nacked NACK_OVERLOADED stay
        # retained, and the next fresh send at/after this monotonic
        # deadline triggers a replay (honoring the server's
        # retry_after_s). Without it a never-breaking connection would
        # only redeliver them at end-of-run flush().
        self._replay_due: float | None = None
        self._bytes = 0
        self._next_seq: dict[str, int] = {}
        self._dir = directory
        self._path = (os.path.join(directory, f"{name}.spool")
                      if directory else None)
        self._fh: io.BufferedWriter | None = None
        self._file_bytes = 0
        reg = telemetry.get_registry()
        self._m_spooled = reg.counter(
            "relayrl_spool_entries_total",
            "trajectories entered into the send spool")
        self._m_evicted = reg.counter(
            "relayrl_spool_evicted_total",
            "spooled trajectories evicted by the window bound "
            "(lost if never delivered)")
        self._m_replayed = reg.counter(
            "relayrl_spool_replayed_total",
            "trajectories re-sent by replay-on-reconnect")
        self._m_send_failures = reg.counter(
            "relayrl_spool_send_failures_total",
            "wire send attempts that failed into the spool")
        self._m_nacked = reg.counter(
            "relayrl_spool_nacked_total",
            "sends the server answered with a typed ingest nack "
            "(quarantine discards the entry; overload retains it)")
        self._m_depth = reg.gauge(
            "relayrl_spool_depth", "entries currently retained")
        if self._path is not None:
            self._load_disk()
            self._open_disk()

    # -- public surface --
    def next_seq(self, agent_id: str) -> int:
        with self._lock:
            return self._next_seq.get(agent_id, 0) + 1

    def sent_counts(self) -> dict[str, int]:
        """Per-agent highest assigned seq (the accounting the chaos bench
        reconciles against the server ledger)."""
        with self._lock:
            return dict(self._next_seq)

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def send(self, payload: bytes, agent_id: str,
             trace: str | None = None) -> int:
        """Assign the next seq for ``agent_id``, retain, and attempt
        delivery (unless the breaker is open). Returns the seq. Never
        raises on wire failure — the entry is already retained and the
        breaker/replay machinery owns recovery.

        ``trace`` (telemetry/trace.py, a sampled trajectory's encoded
        context) rides the wire id as a ``#t`` tag BETWEEN the agent id
        and the ``#s`` seq tag — the seq SPACE stays keyed by the clean
        agent id (a per-trajectory tag in the key would reset every
        trajectory to seq 1 and dedup the fleet into silence), while
        the retained entry keeps the tagged id so a replay re-ships the
        context verbatim."""
        wire_id = agent_id
        if trace is not None:
            from relayrl_tpu.transport.base import tag_agent_trace

            wire_id = tag_agent_trace(agent_id, trace)
        with self._lock:
            seq = self._next_seq.get(agent_id, 0) + 1
            self._next_seq[agent_id] = seq
            self._retain_locked(wire_id, seq, payload)
        self._m_spooled.inc()
        self._m_depth.set(len(self._entries))
        self._attempt(wire_id, seq, payload)
        return seq

    def send_verbatim(self, payload: bytes, wire_id: str) -> None:
        """Retain + attempt with ``wire_id`` shipped VERBATIM — no seq
        assignment, no ``#s`` tag. The relay plane's forward surface
        (ISSUE 11): a relay retains subtree envelopes/batches whose
        inner ids already carry the LEAF actors' seq tags, so replay
        after a relay crash re-ships them untouched and the root
        ledger's per-leaf dedup keeps the replay exactly-once. A fresh
        relay process must therefore never mint its own seq space (a
        restarted relay restarting at seq 1 would be deduplicated into
        silence). Verbatim entries are excluded from :meth:`sent_counts`
        and persist to disk with a seq-0 sentinel."""
        with self._lock:
            self._retain_locked(wire_id, None, payload)
        self._m_spooled.inc()
        self._m_depth.set(len(self._entries))
        self._attempt(wire_id, None, payload)

    def replay(self) -> int:
        """Re-send the whole retained window in order (reconnect path —
        at-least-once; the server ledger dedups). Returns entries
        attempted; stops early if the wire breaks again."""
        if self.send_fn is None:
            return 0
        with self._lock:
            window = list(self._entries)
        n = 0
        for agent_id, seq, payload in window:
            if not self._attempt(agent_id, seq, payload, replay=True):
                break
            n += 1
        if n:
            from relayrl_tpu import telemetry

            telemetry.emit("spool_replay", entries=n,
                           depth=len(window))
        return n

    def flush(self, deadline_s: float = 30.0) -> bool:
        """Replay until one FULL pass of the retained window succeeds
        (or the deadline lapses): end-of-run delivery guarantee for
        drills/benches. Rides out an open breaker by waiting for its
        half-open probe windows."""
        import time

        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            with self._lock:
                target = len(self._entries)
            if self.replay() >= target:
                return True
            time.sleep(0.5)
        return False

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    # -- delivery --
    def _attempt(self, agent_id: str, seq: int, payload: bytes,
                 replay: bool = False) -> bool:
        """One policy-bounded wire attempt; updates the breaker. A
        success that CLOSES the breaker triggers a full replay (the
        reconnect may have been silent — e.g. a zmq PUSH that never
        errors).

        Typed ingest nacks (transport/base.IngestNack — the guardrail
        plane's verdicts on ack-capable transports) are NOT wire
        failures: the server answered. A *quarantine* nack discards the
        entry (retrying is pointless until parole and would replay
        poison forever); an *overload* nack keeps it retained for a
        later replay. Neither touches the breaker."""
        if self.send_fn is None:
            return True
        if not self.breaker.allow():
            return False
        from relayrl_tpu.transport.base import IngestNack, tag_agent_seq

        # seq None = verbatim entry (send_verbatim): the id ships as-is.
        tagged = agent_id if seq is None else tag_agent_seq(agent_id, seq)

        def attempt_once():
            try:
                self.send_fn(payload, tagged)
            except IngestNack as nack:
                return nack  # a verdict, not a failure — escape the retry
            return True

        try:
            result = self.retry.call(attempt_once, op="spool.send")
        except Exception as e:
            self._m_send_failures.inc()
            if self.breaker.record_failure():
                print(f"[spool] breaker OPEN after send failure: {e!r} — "
                      f"buffering until the server answers a probe",
                      flush=True)
            return False
        if isinstance(result, IngestNack):
            self._m_nacked.inc()
            healed = self.breaker.record_success()  # the server IS alive
            if result.quarantined:
                self.discard(agent_id, seq)
                if healed and not replay:
                    # The outage may have eaten OTHER agents'/lanes'
                    # entries; the quarantined ones replayed here just
                    # nack-and-discard again (bounded by the window).
                    self.replay()
                return True  # delivered-and-refused: nothing to replay
            # Overloaded: stays retained; schedule the redelivery the
            # server asked for instead of replaying into the overload
            # (a heal-triggered replay would do exactly that).
            self._replay_due = time.monotonic() + max(
                0.25, result.retry_after_s)
            return False
        if replay:
            self._m_replayed.inc()
            self.breaker.record_success()  # may be flush()'s half-open probe
            return True
        if self.breaker.record_success():
            # Broken → healed on a live send: replay everything the
            # outage may have eaten (runs on the caller thread; bounded
            # by the spool window).
            self.replay()
        elif (self._replay_due is not None
              and time.monotonic() >= self._replay_due):
            # Overload-nacked entries come due: one replay pass
            # redelivers them (the server ledger dedups the rest).
            self._replay_due = None
            self.replay()
        return True

    def discard(self, agent_id: str, seq: int) -> None:
        """Drop one retained entry (quarantine nack: the server will
        never accept it — retaining it would replay poison on every
        reconnect)."""
        with self._lock:
            for i, (aid, s, payload) in enumerate(self._entries):
                if aid == agent_id and s == seq:
                    del self._entries[i]
                    self._bytes -= len(payload)
                    break
        self._m_depth.set(len(self._entries))

    # -- retention --
    def _retain_locked(self, agent_id: str, seq: int, payload: bytes) -> None:
        self._entries.append((agent_id, seq, payload))
        self._bytes += len(payload)
        evicted = 0
        while (len(self._entries) > self.max_entries
               or self._bytes > self.max_bytes):
            _, _, old = self._entries.pop(0)
            self._bytes -= len(old)
            evicted += 1
        if evicted:
            self._m_evicted.inc(evicted)
        if self._fh is not None:
            self._append_disk(agent_id, seq, payload)

    # -- disk backing --
    def _append_disk(self, agent_id: str, seq: int | None,
                     payload: bytes) -> None:
        # lock held. seq 0 is the verbatim-entry sentinel on disk (live
        # seqs start at 1), mapped back to None on load.
        try:
            ident = agent_id.encode()
            rec = _REC_HDR.pack(len(ident) + len(payload), seq or 0,
                                len(ident)) + ident + payload
            self._fh.write(rec)
            self._fh.flush()
            self._file_bytes += len(rec)
            if self._file_bytes > 2 * self.max_bytes:
                self._compact_locked()
        except OSError as e:
            print(f"[spool] disk append failed ({e!r}) — continuing "
                  f"in-memory only", flush=True)
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _compact_locked(self) -> None:
        """Rewrite the log to just the retained window (atomic replace)."""
        tmp = f"{self._path}.tmp"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            for agent_id, seq, payload in self._entries:
                ident = agent_id.encode()
                f.write(_REC_HDR.pack(len(ident) + len(payload), seq or 0,
                                      len(ident)) + ident + payload)
        self._fh.close()
        os.replace(tmp, self._path)
        self._open_disk()

    def _open_disk(self) -> None:
        try:
            os.makedirs(self._dir, exist_ok=True)
            fresh = not os.path.exists(self._path)
            self._fh = open(self._path, "ab")
            if fresh:
                self._fh.write(_MAGIC)
                self._fh.flush()
            self._file_bytes = self._fh.tell()
            if getattr(self, "_force_compact", False):
                self._force_compact = False
                self._compact_locked()
        except OSError as e:
            print(f"[spool] spool file unavailable ({self._path}: {e!r}) "
                  f"— continuing in-memory only", flush=True)
            self._fh = None

    def _load_disk(self) -> None:
        """Restore the retained window (and seq counters) from a prior
        process life; tolerates a torn tail record."""
        if not self._path or not os.path.exists(self._path):
            return
        try:
            with open(self._path, "rb") as f:
                data = f.read()
        except OSError:
            return
        if not data.startswith(_MAGIC):
            return
        off = len(_MAGIC)
        loaded = 0
        while off + _REC_HDR.size <= len(data):
            total_len, seq, id_len = _REC_HDR.unpack_from(data, off)
            body_start = off + _REC_HDR.size
            if body_start + total_len > len(data) or id_len > total_len:
                break  # torn tail
            ident = data[body_start:body_start + id_len].decode(
                errors="replace")
            payload = data[body_start + id_len:body_start + total_len]
            self._retain_from_load(ident, seq, payload)
            loaded += 1
            off = body_start + total_len
        if off < len(data):
            # Torn tail: TRUNCATE to the last whole record before the
            # append handle opens, or every record appended after the
            # torn bytes would be unreachable to the NEXT load (it stops
            # at the first torn record) — losing exactly the in-flight
            # window this file exists to preserve.
            try:
                os.truncate(self._path, off)
                print(f"[spool] truncated torn tail in {self._path} "
                      f"({len(data) - off} bytes)", flush=True)
            except OSError as e:
                # Fall back to a full rewrite once the handle opens —
                # the retained window is already in memory.
                self._force_compact = True
                print(f"[spool] torn-tail truncate failed ({e!r}) — "
                      f"will compact on open", flush=True)
        if loaded:
            print(f"[spool] restored {len(self._entries)} retained "
                  f"trajectories from {self._path}", flush=True)

    def _retain_from_load(self, agent_id: str, seq: int,
                          payload: bytes) -> None:
        self._entries.append((agent_id, seq or None, payload))
        self._bytes += len(payload)
        while (len(self._entries) > self.max_entries
               or self._bytes > self.max_bytes):
            _, _, old = self._entries.pop(0)
            self._bytes -= len(old)
        if seq:
            # Stored wire ids may carry a per-trajectory trace tag; the
            # seq space is keyed by the CLEAN id (see send), so restore
            # the counter under the same key.
            from relayrl_tpu.transport.base import split_agent_trace

            clean_id, _ = split_agent_trace(agent_id)
            if seq > self._next_seq.get(clean_id, 0):
                self._next_seq[clean_id] = seq


class SequenceLedger:
    """Server-side idempotent-ingest ledger: per-agent monotonic sequence
    acceptance with a bounded out-of-order window.

    Accept iff ``seq`` is above the agent's low watermark (``max_seq -
    window``) and not already seen; anything at or below the watermark is
    treated as a duplicate (it either arrived long ago or was evicted —
    conservatively never re-train). ``retract`` un-sees a seq whose
    enqueue failed downstream (queue-full), so the actor's replay can
    land it later.
    """

    def __init__(self, window: int = 4096):
        self.window = max(1, int(window))
        self._lock = threading.Lock()
        # agent -> [max_seq, seen_set, accepted_count]
        self._agents: dict[str, list] = {}
        self.duplicates = 0

    def accept(self, agent_id: str, seq: int) -> bool:
        with self._lock:
            entry = self._agents.get(agent_id)
            if entry is None:
                entry = [0, set(), 0]
                self._agents[agent_id] = entry
            max_seq, seen, _ = entry
            low = max_seq - self.window
            if seq <= low or seq in seen:
                self.duplicates += 1
                return False
            seen.add(seq)
            if seq > max_seq:
                entry[0] = seq
                new_low = seq - self.window
                if new_low > low:
                    # prune the window floor (amortized)
                    entry[1] = {s for s in seen if s > new_low}
            entry[2] += 1
            return True

    def retract(self, agent_id: str, seq: int) -> None:
        with self._lock:
            entry = self._agents.get(agent_id)
            if entry is not None and seq in entry[1]:
                entry[1].discard(seq)
                entry[2] -= 1

    # -- accounting / persistence --
    def counts(self) -> dict[str, dict]:
        """Per-agent ``{max_seq, accepted, contiguous}`` — ``contiguous``
        is the zero-loss predicate (every seq 1..max_seq accepted
        exactly once, within window resolution)."""
        with self._lock:
            return {
                aid: {"max_seq": e[0], "accepted": e[2],
                      "contiguous": e[2] == e[0]}
                for aid, e in self._agents.items()
            }

    def total_duplicates(self) -> int:
        with self._lock:
            return self.duplicates

    def state_dict(self) -> dict:
        with self._lock:
            return {
                "window": self.window,
                "duplicates": self.duplicates,
                "agents": {aid: {"max_seq": e[0],
                                 "seen": sorted(e[1]),
                                 "accepted": e[2]}
                           for aid, e in self._agents.items()},
            }

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            self._agents.clear()
            self.duplicates = int(state.get("duplicates", 0))
            for aid, e in (state.get("agents") or {}).items():
                self._agents[str(aid)] = [int(e.get("max_seq", 0)),
                                          set(int(s) for s in
                                              e.get("seen", ())),
                                          int(e.get("accepted", 0))]

    def save(self, path: str) -> None:
        """Atomic JSON sidecar write (rides each learner checkpoint)."""
        import json

        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.state_dict(), f)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "SequenceLedger":
        import json

        with open(path, "r") as f:
            state = json.load(f)
        ledger = cls(window=int(state.get("window", 4096)))
        ledger.load_state_dict(state)
        return ledger


__all__ = ["TrajectorySpool", "SequenceLedger"]
