"""In-process actor↔learner loop — the "minimum slice" (SURVEY.md §7.3).

Wires a Gymnasium env → policy apply → epoch buffer → jitted learner step
with no sockets at all. This validates the learning math end-to-end (the
reference's equivalent is its example notebooks, examples/README.md:125-152,
driving CartPole through the full network stack) and doubles as the fake
in-process transport for integration tests.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Mapping

import numpy as np

from relayrl_tpu.algorithms import build_algorithm
from relayrl_tpu.runtime.policy_actor import PolicyActor
from relayrl_tpu.types.trajectory import deserialize_actions


class LocalRunner:
    """Single-process trainer: env steps feed the algorithm directly.

    The actor still goes through the *wire codec* (serialize → deserialize on
    episode hand-off) so the exact bytes that would cross the network are
    exercised every episode.
    """

    def __init__(
        self,
        env,
        algorithm_name: str = "REINFORCE",
        config_path: str | None = None,
        env_dir: str | None = None,
        seed: int | None = None,
        **hyperparams,
    ):
        self.env = env
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = (
            env.action_space.n
            if hasattr(env.action_space, "n")
            else int(np.prod(env.action_space.shape))
        )
        # An explicit seed seeds BOTH sides: the actor's sampling stream
        # below and the learner's init/update stream (forwarded as the
        # algorithm `seed` hyperparam, which trumps any config-file seed
        # — explicit overrides always win over config params in
        # build_algorithm) — so `--hp seed=N` runs land in `..._sN` log
        # dirs and vary the whole pipeline, not just action sampling.
        # Only `seed_salt` is independent of this seed: the learner folds
        # in that per-process salt (default pid, mirroring the
        # reference's `seed + 10000*pid`), so two runs at the same seed
        # are independent unless seed_salt is pinned too.
        if seed is not None:
            hyperparams.setdefault("seed", seed)
        self.algorithm = build_algorithm(
            algorithm_name,
            env_dir=env_dir,
            config_path=config_path,
            obs_dim=obs_dim,
            act_dim=int(act_dim),
            **hyperparams,
        )
        self._episode_bytes: list[bytes] = []
        # On-policy epoch buffers expose length buckets; the off-policy step
        # replay ring has none — cap trajectories at a fixed horizon there.
        # (PolicyActor adds marker headroom on top of this cap.)
        buckets = getattr(self.algorithm.buffer, "buckets", None)
        self.actor = PolicyActor(
            self.algorithm.bundle(),
            max_traj_length=buckets[-1] if buckets else 1000,
            on_send=self._episode_bytes.append,
            seed=0 if seed is None else seed,
        )
        self.seed = seed
        self.updates = 0
        # Rolling window across train() calls: per-call windows can be
        # as short as a handful of episodes for off-policy families
        # (updates land ~every episode), letting an early-stop target
        # trigger on a lucky streak. 50 episodes is the SpinningUp-style
        # smoothing horizon.
        self._recent_returns: deque[float] = deque(maxlen=50)

    def run_episode(self, max_steps: int = 1000) -> tuple[float, int]:
        obs, _ = self.env.reset(seed=None)
        ep_ret, ep_len = 0.0, 0
        reward = 0.0
        terminated = truncated = False
        for _ in range(max_steps):
            record = self.actor.request_for_action(obs, reward=reward)
            obs, reward, terminated, truncated, _ = self.env.step(
                self._to_env_action(record.act)
            )
            ep_ret += float(reward)
            ep_len += 1
            if terminated or truncated:
                break
        # Ending by time limit (env truncation or the max_steps cap here)
        # is not a terminal state: ship the post-step obs so value targets
        # bootstrap through it. A genuine terminal takes precedence even if
        # it coincides with the time limit (Gymnasium allows both True).
        time_limited = not terminated
        self.actor.flag_last_action(
            reward, truncated=time_limited,
            final_obs=obs if time_limited else None)

        # Hand the wire bytes to the learner exactly as the server would.
        for buf in self._episode_bytes:
            actions = deserialize_actions(buf)
            if self.algorithm.receive_trajectory(actions):
                self.updates += 1
                self.actor.maybe_swap(self.algorithm.bundle())
        self._episode_bytes.clear()
        return ep_ret, ep_len

    def train(self, epochs: int = 10, max_steps: int = 1000) -> dict[str, Any]:
        """Run until ``epochs`` learner updates have happened."""
        returns: list[float] = []
        target_updates = self.updates + epochs
        while self.updates < target_updates:
            ep_ret, _ = self.run_episode(max_steps)
            returns.append(ep_ret)
            self._recent_returns.append(ep_ret)
        return {
            "episodes": len(returns),
            "updates": self.updates,
            # Mean over the PERSISTENT 50-episode window, not just this
            # call's episodes — a train(epochs=5) chunk may contain only
            # ~5 episodes for off-policy families, and early-stop
            # targets read this value (a 5-episode window stops on luck;
            # the committed SAC golden's first run did exactly that).
            "avg_return_last_window": float(np.mean(self._recent_returns)),
            "returns": returns,
        }

    def evaluate(self, episodes: int = 10, max_steps: int = 1000) -> dict:
        """Greedy evaluation between training episodes: probes the CURRENT
        policy deterministically without recording anything to the
        trajectory (nothing reaches the learner buffer). Refuses to run
        mid-episode (run_episode always closes its episode, so calling
        between episodes is always safe)."""
        from relayrl_tpu.runtime.agent import greedy_episodes

        returns = greedy_episodes(self.actor, self.env, episodes, max_steps)
        return {
            "episodes": episodes,
            "avg_return": float(np.mean(returns)),
            "returns": returns,
        }

    def _to_env_action(self, act: np.ndarray):
        from relayrl_tpu.runtime.agent import coerce_env_action

        return coerce_env_action(act)


def reward_threshold_reached(result: Mapping[str, Any], threshold: float) -> bool:
    return result["avg_return_last_window"] >= threshold
