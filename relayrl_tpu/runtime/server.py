"""The training server process: trajectory ingest → jitted learner → model
publish.

Capability parity with the reference's server stack
(reference: relayrl_framework/src/network/server/training_server_wrapper.rs:
199-443 facade + lifecycle; training_zmq.rs / training_grpc.rs loops), with
the central re-design from SURVEY.md §7.4 item 1: the reference funnels every
trajectory through a lock-step JSON-over-stdin subprocess
(python_algorithm_request.rs:199-267); here the learner is **in-process** —
ingest happens on transport threads into a queue, a staging thread decodes
(natively, off-GIL, via native/codec.cc when the library is built — the
reference keeps its decode native too, training_zmq.rs:994-1011), and a
single learner thread drains ready batches into the jitted XLA update while
the next trajectories decode in parallel. The native transport goes one
step further and delivers pre-decoded columnar batches straight to the
decoded queue (rl_server_poll_batch). No subprocess, no stdio bottleneck,
no 50 ms polls, no per-step Python on the ingest path.

Ctor parity with the PyO3 surface (src/bindings/python/network/server/
o3_training_server.rs:78-151): ``TrainingServer(algorithm_name, obs_dim,
act_dim, buf_size, tensorboard=False, multiactor=False, env_dir,
algorithm_dir, config_path, hyperparams, server_type, ...)`` plus
``restart_server/enable_server/disable_server``.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Mapping

from relayrl_tpu.algorithms import build_algorithm, registered_algorithms
from relayrl_tpu.config import ConfigLoader
from relayrl_tpu.telemetry.aggregate import is_snapshot_frame
from relayrl_tpu.transport import make_server_transport
from relayrl_tpu.telemetry.trace import split_ctx as _split_trace_ctx
from relayrl_tpu.transport.base import (
    BATCH_KIND_ENVELOPES,
    batch_kind,
    split_agent_seq,
    split_batch,
    swallow_decode_error,
    unpack_trajectory_envelope,
)
from relayrl_tpu.types.columnar import DecodedTrajectory
from relayrl_tpu.types.trajectory import deserialize_actions


class _EventCoalescer:
    """≤1 journal event per ``min_interval_s`` for burst-prone counters
    (ingest drops, duplicate replays): the metric counter is the ledger,
    the journal event is the greppable breadcrumb — one instance per
    event type, mutated under the owner's lock, with ``flush`` covering
    the tail of a burst on quiesce paths."""

    def __init__(self, min_interval_s: float = 1.0):
        self.pending = 0
        self._last = 0.0
        self._min = min_interval_s

    def add(self, n: int) -> int | None:
        """Accumulate; returns the count to emit now, or None while
        still coalescing. Caller holds the owning lock."""
        self.pending += n
        if time.monotonic() - self._last >= self._min:
            due, self.pending = self.pending, 0
            self._last = time.monotonic()
            return due
        return None

    def flush(self) -> int:
        """Drain whatever is still coalescing (caller holds the lock)."""
        due, self.pending = self.pending, 0
        if due:
            self._last = time.monotonic()
        return due


class _TracedRecords(list):
    """A ``list[ActionRecord]`` that can carry a trace context attribute
    (plain lists can't) — behaves identically through accumulate."""

    trace_ctx = None


def _attach_trace_ctx(item, ctx):
    """Hang a sampled trajectory's trace context on the decoded item so
    the learner thread can attribute the consuming update dispatch."""
    if isinstance(item, DecodedTrajectory):
        item.trace_ctx = ctx
        return item
    if isinstance(item, list):
        if item and isinstance(item[0], DecodedTrajectory):
            item[0].trace_ctx = ctx  # coalesced frames: one ctx, one seq
            return item
        wrapped = _TracedRecords(item)
        wrapped.trace_ctx = ctx
        return wrapped
    return item


class TrainingServer:
    def __init__(
        self,
        algorithm_name: str = "REINFORCE",
        obs_dim: int = 4,
        act_dim: int = 2,
        buf_size: int | None = None,
        tensorboard: bool = False,
        multiactor: bool = True,
        env_dir: str | None = None,
        algorithm_dir: str | None = None,
        config_path: str | None = None,
        hyperparams: Mapping[str, Any] | None = None,
        server_type: str = "zmq",
        start: bool = True,
        resume: bool = False,
        handle_signals: bool = False,
        serving: bool | None = None,
        **addr_overrides,
    ):
        self.config = ConfigLoader(algorithm_name, config_path)
        self.server_type = server_type
        self._addr_overrides = addr_overrides

        # Observability first: the registry must be live before any
        # component (algorithm logger, transports, pipeline) grabs its
        # metric handles; disabled mode installs null metrics everywhere
        # (telemetry.* knobs, docs/observability.md).
        from relayrl_tpu import telemetry

        self._telemetry = telemetry.configure_from_config(self.config)
        self._exporter = telemetry.maybe_serve()
        reg = self._telemetry
        self._m_trajectories = reg.counter(
            "relayrl_server_trajectories_total",
            "trajectories handed to the learner plane")
        self._m_updates = reg.counter(
            "relayrl_server_updates_total", "learner updates dispatched")
        self._m_dropped = reg.counter(
            "relayrl_server_dropped_total",
            "payloads lost at ingest (full queue / decode failure)")
        self._m_nonfinite = reg.gauge(
            "relayrl_server_dropped_nonfinite",
            "trajectories rejected by the finite-value guard")
        self._m_decode = reg.histogram(
            "relayrl_server_decode_seconds",
            "one payload decode on a staging worker")
        self._m_columnar_frames = reg.counter(
            "relayrl_server_columnar_frames_total",
            "columnar trajectory frames decoded straight into "
            "DecodedTrajectory (the wire fast path)")
        self._m_columnar_bytes = reg.counter(
            "relayrl_server_columnar_bytes_total",
            "columnar trajectory frame bytes decoded")
        self._m_columnar_rejects = reg.counter(
            "relayrl_server_columnar_rejects_total",
            "columnar frames refused at decode (CRC mismatch / "
            "malformed layout) — also counted in dropped_total")
        self._m_dispatch = reg.histogram(
            "relayrl_server_dispatch_seconds",
            "learner-thread host work per trajectory: accumulate + "
            "assemble + async update dispatch")
        self._m_duplicates = reg.counter(
            "relayrl_server_duplicate_trajectories_total",
            "sequence-tagged trajectories dropped by idempotent ingest "
            "(replays, retry storms, duplicate-injection faults)")
        # Same bucket grid as the scheduler's emit-side lag histogram —
        # bench_rlhf compares the two distributions side by side, so the
        # grids must never drift apart.
        from relayrl_tpu.rlhf.scheduler import LAG_BUCKETS

        self._m_rlhf_train_lag = reg.histogram(
            "relayrl_rlhf_train_lag_versions",
            "behavior version (data['bver'], stamped at generation) vs "
            "the learner's dispatched version when the trajectory "
            "trains — the off-policy distance V-trace corrects; "
            "observed for trajectories that carry bver, or a sampled "
            "trace context's born_version (same evidence)",
            buckets=LAG_BUCKETS)
        self._m_ckpt_failures = reg.counter(
            "relayrl_server_checkpoint_failures_total",
            "periodic/final checkpoint saves that raised")
        self._m_ckpt_consecutive = reg.gauge(
            "relayrl_server_checkpoint_consecutive_failures",
            "checkpoint failures since the last successful save "
            "(alarm when this grows — resume would lose that window)")
        self._ckpt_consecutive_failures = 0
        self._drop_events = _EventCoalescer()
        self._dup_events = _EventCoalescer()

        # Fleet telemetry aggregation (ISSUE 15, telemetry/aggregate.py):
        # the root holds the fleet table — every process's snapshot
        # frames land here through the ordinary ingest funnel (sniffed by
        # RLS1 magic in _ingest_one, O(relays) frames under a relay
        # tree), the fleet tick folds this server's own registry in,
        # evicts stale procs, and runs the SLO alert rules over the
        # merged snapshot. Gated like tracing: registry live AND
        # telemetry.fleet_interval_s > 0.
        tel_params = self.config.get_telemetry_params()
        self._fleet = None
        self._alerts = None
        self._fleet_interval_s = float(tel_params.get("fleet_interval_s")
                                       or 0.0)
        self._fleet_stop = threading.Event()
        self._fleet_thread: threading.Thread | None = None
        self._fleet_proc = f"server-{os.getpid()}"
        if reg.enabled and self._fleet_interval_s > 0:
            from relayrl_tpu.telemetry.aggregate import (
                AlertEngine,
                FleetTable,
                rules_from_config,
            )

            self._fleet = FleetTable(
                stale_s=tel_params.get("fleet_stale_s", 15.0), registry=reg)
            self._alerts = AlertEngine(rules_from_config(tel_params),
                                       registry=reg)
            if self._exporter is not None:
                self._exporter.set_fleet(self._fleet, self._alerts)

        # Fault-injection plane: the env-driven plan (RELAYRL_FAULT_PLAN)
        # installs before any hook site resolves; production processes
        # without the env var get None sites and pay one identity check.
        from relayrl_tpu import faults

        faults.maybe_install_from_env()
        self._fault_ingest = faults.site("server.ingest")
        self._fault_publish = faults.site("server.publish")

        # Training-health guardrails (relayrl_tpu/guardrails/): ingest
        # validation + quarantine, divergence watchdog, last-known-good
        # rollback, and ingest backpressure. None when guardrails.enabled
        # is false — every hook site below then costs one identity check.
        from relayrl_tpu.guardrails import build_guardrails

        self.guardrails = build_guardrails(self.config)
        # Rollback bookkeeping (learner thread only): timestamps of
        # executed rollbacks inside the budget window, and the degraded
        # halt-and-alarm latch (halted = ingest sheds, training stops,
        # the process survives for operator forensics).
        self._rollback_times: list[float] = []
        self._rollbacks_total = 0
        self._halted = False

        # Multi-host bring-up must precede any other JAX use (no-op for the
        # default single-host config; RELAYRL_COORDINATOR etc. override).
        from relayrl_tpu.parallel.distributed import initialize_distributed

        self.distributed_info = initialize_distributed(
            config=self.config.get_learner_params())
        if self.distributed_info["multi_host"]:
            print(f"[TrainingServer] multi-host learner: process "
                  f"{self.distributed_info['process_id']}/"
                  f"{self.distributed_info['num_processes']}", flush=True)

        if algorithm_dir:
            _load_plugin_algorithms(algorithm_dir)
        # Reference parity: hyperparams may arrive as a dict or as
        # ["k=v", ...] (training_server_wrapper.rs:118-154).
        if isinstance(hyperparams, (list, tuple)):
            hp = {k: _coerce(v) for k, v in
                  (kv.split("=", 1) for kv in hyperparams)}
        else:
            hp = dict(hyperparams or {})
        if self.distributed_info["multi_host"]:
            # SPMD demands bit-identical initial state on every process;
            # the default seed_salt (the pid) would fork the inits.
            hp.setdefault("seed_salt", 0)

        self.algorithm = build_algorithm(
            algorithm_name,
            env_dir=env_dir,
            config_path=str(self.config.config_path) if self.config.config_path else None,
            obs_dim=obs_dim,
            act_dim=act_dim,
            buf_size=buf_size,
            **hp,
        )
        if self.guardrails is not None:
            # Installs the device-side health probes (observers — params
            # stay bit-identical to guardrails-off) and aligns the
            # per-algorithm finite guard with the validation mode.
            self.guardrails.attach_algorithm(self.algorithm)

        learner_cfg = self.config.get_learner_params()
        # One resolution for save AND resume — a falsy configured value
        # disables checkpointing entirely, anything else is used by both
        # paths (a split default here would resume from a dir never written).
        # Relative dirs anchor under env_dir (see anchor_path) so example
        # runs don't leave `checkpoints/` in the caller's cwd.
        from relayrl_tpu.algorithms.base import anchor_path

        self._checkpoint_dir = learner_cfg.get("checkpoint_dir", "checkpoints")
        if self._checkpoint_dir:
            self._checkpoint_dir = anchor_path(self._checkpoint_dir, env_dir)
        self._checkpoint_every = max(
            1, int(learner_cfg.get("checkpoint_every_epochs", 10)))
        # Replay-buffer (aux) cadence: snapshotting the ring is a
        # synchronous host copy on the learner thread, so large buffers
        # can throttle it to every Nth periodic save. Final/signal saves
        # always include aux regardless. Retention grows with the
        # cadence (max_to_keep >= cadence) so a crash-resume always finds
        # at least one retained aux-carrying step — the aux-less step
        # dirs are cheap (params + opt state) next to the ring itself.
        from relayrl_tpu.checkpoint import CheckpointManager

        self._aux_every = max(
            1, int(learner_cfg.get("checkpoint_aux_every", 1)))
        self._ckpt_keep = max(CheckpointManager.DEFAULT_MAX_TO_KEEP,
                              self._aux_every)
        if self.guardrails is not None and self.guardrails.params["rollback"]:
            # The last-known-good ring: retain at least checkpoint_ring
            # steps so the rollback search has healthy-tagged candidates
            # even when the newest saves straddled the divergence.
            self._ckpt_keep = max(self._ckpt_keep,
                                  self.guardrails.params["checkpoint_ring"])
        self._ckpt_saves = 0

        # Idempotent ingest (runtime/spool.SequenceLedger): sequence-
        # tagged trajectories are accepted at most once per agent, so
        # actor replay-on-reconnect can never double-train. The ledger
        # snapshots to a per-version JSON sidecar next to each
        # checkpoint and is restored WITH the matching resume, keeping
        # dedup state consistent with the params line of history.
        from relayrl_tpu.runtime.spool import SequenceLedger

        try:
            dedup_window = int(learner_cfg.get("ingest_dedup_window", 4096))
        except (TypeError, ValueError):
            dedup_window = 4096
        self._ingest_ledger = (SequenceLedger(dedup_window)
                               if dedup_window > 0 else None)

        if resume and self._checkpoint_dir:
            # Multi-host: EVERY rank restores the same full state from the
            # shared checkpoint dir BEFORE enable_multihost places it on
            # the global mesh — identical state everywhere, exactly like a
            # fresh seed_salt=0 init. (Saves are already collective; see
            # the broadcast loop.)
            from relayrl_tpu.checkpoint import restore_algorithm

            try:
                restore_algorithm(self.algorithm, self._checkpoint_dir)
                print(f"[TrainingServer] resumed at version "
                      f"{self.algorithm.version}", flush=True)
                self._load_ledger_sidecar(self.algorithm.version)
            except FileNotFoundError:
                print("[TrainingServer] no checkpoint to resume; fresh start",
                      flush=True)

        if self.distributed_info["multi_host"]:
            # The learner step becomes SPMD over the global (all-host)
            # mesh: coordinator-side socket ingest assembles batches, the
            # broadcast loop ships them, every process steps in lockstep
            # (SURVEY.md §7.4 item 5's asymmetric-ingest design).
            if not hasattr(self.algorithm, "enable_multihost"):
                raise NotImplementedError(
                    f"{algorithm_name} has no multi-host support "
                    "(enable_multihost)")
            from relayrl_tpu.parallel import make_mesh

            self._mh_mesh = make_mesh(learner_cfg.get("mesh") or {"dp": -1})
            self.algorithm.enable_multihost(self._mh_mesh)
            print(f"[TrainingServer] multi-host mesh "
                  f"{dict(self._mh_mesh.shape)} over "
                  f"{len(self._mh_mesh.devices.flat)} devices", flush=True)

        # Multi-actor registry (ref: MultiactorParams,
        # training_server_wrapper.rs:159-163). Always multi-capable; the
        # flag only gates the registered-agents log.
        self.multiactor = bool(multiactor)
        self.agent_ids: list[str] = []
        self._registry_lock = threading.Lock()

        # Raw payloads from transport threads; a staging thread decodes
        # them (native codec when built) into _decoded, which the learner
        # thread drains — decode overlaps the device step.
        self._ingest: queue.Queue[tuple[str, bytes]] = queue.Queue(maxsize=100_000)
        self._decoded: queue.Queue = queue.Queue(maxsize=100_000)
        # Pull-gauges: depth is read from the live queues only when an
        # export actually renders — zero hot-path cost. Sources hold a
        # WEAK reference to this server: the registry is process-global,
        # and a strong closure would pin a shut-down server's whole
        # object graph (100k-slot queues, algorithm state) for the
        # process lifetime. A dead source reads None → omitted from
        # snapshots.
        import weakref

        wref = weakref.ref(self)

        def _queue_depth(attr):
            def read():
                server = wref()
                return (None if server is None
                        else getattr(server, attr).qsize())
            return read

        def _registered():
            server = wref()
            return None if server is None else len(server.agent_ids)

        reg.gauge_fn("relayrl_server_ingest_queue_depth",
                     _queue_depth("_ingest"),
                     "raw payloads awaiting a decode worker")
        reg.gauge_fn("relayrl_server_decoded_queue_depth",
                     _queue_depth("_decoded"),
                     "decoded trajectories awaiting the learner thread")
        reg.gauge_fn("relayrl_server_registered_agents", _registered,
                     "logical agents currently in the registry")
        self._bundle_lock = threading.Lock()
        self._bundle_bytes: bytes = self.algorithm.bundle().to_bytes()
        self._bundle_version: int = self.algorithm.version
        # Latest published model as a HOST tree (version, arch, params):
        # the v1 bundle bytes for handshakes/artifacts serialize lazily
        # from it in _get_model, so the wire-v2 publish path never pays a
        # full flax serialize per publish (only per handshake-or-artifact
        # that actually needs one).
        self._bundle_host: tuple[int, dict, object] | None = None
        # Model-wire v2 (transport/modelwire.py): per-leaf delta frames
        # with periodic keyframes replace the full-bundle blob on the
        # broadcast plane. transport.wire_version=1 is the rolling-compat
        # escape hatch (v1 fleets; v2 actors decode either).
        transport_cfg = self.config.get_transport_params()
        self._wire_encoder = None
        if int(transport_cfg.get("wire_version", 2)) >= 2:
            from relayrl_tpu.transport.modelwire import ModelWireEncoder

            self._wire_encoder = ModelWireEncoder(
                keyframe_interval=transport_cfg["keyframe_interval"],
                compress=transport_cfg["compress"],
                small_model_bytes=transport_cfg.get("small_model_bytes"))
        # Broadcast-plane resync requests (CMD_RESYNC — ISSUE 11): a
        # diverged subscriber asks for a keyframe instead of waiting out
        # the interval. Coalesced by nature (force_keyframe is a flag
        # the next publish consumes) and rate-limited so a subtree-wide
        # divergence storm grants ONE forced keyframe per window.
        self._resync_lock = threading.Lock()
        self._last_resync_grant = -1e9
        self._resync_min_interval_s = float(
            transport_cfg.get("resync_min_interval_s", 0.25))
        self._m_resync_requests = reg.counter(
            "relayrl_server_resync_requests_total",
            "CMD_RESYNC keyframe requests received from the broadcast "
            "plane (actors or relays with a diverged delta base)")
        self._m_resync_granted = reg.counter(
            "relayrl_server_resync_keyframes_total",
            "resync requests that forced the next publish to keyframe "
            "(the rest coalesced into an already-granted window)")

        # Non-coordinator processes run learner steps only — the actor
        # plane (sockets) binds on the coordinator host alone.
        from relayrl_tpu.parallel.distributed import is_coordinator

        self.transport = None
        if is_coordinator():
            self.transport = make_server_transport(server_type, self.config,
                                                   **addr_overrides)
            self.transport.on_trajectory = self._on_trajectory
            self.transport.on_trajectory_decoded = self._on_trajectory_decoded
            self.transport.get_model = self._get_model
            self.transport.on_register = self._on_register
            self.transport.on_unregister = self._on_unregister
            self.transport.on_resync = self._on_resync_request
            if self.guardrails is not None:
                # Ack-capable transports (gRPC) answer a refused send
                # with a typed nack (quarantine / overload) instead of a
                # silent server-side shed — see _check_ingest.
                self.transport.check_ingest = self._check_ingest
            if getattr(self.transport, "serves_full_bundles_only", False):
                # This plane (native C++ gRPC long-polls) ships the
                # stored full bundle to every subscriber regardless —
                # encoding delta frames would burn publisher CPU and
                # record wire counters for bytes that never leave.
                self._wire_encoder = None
            if self._wire_encoder is not None:
                # Pull transports (gRPC long-polls) choose delta-vs-full
                # per subscriber through this surface; the version probe
                # keeps their wakeup checks from forcing lazy serializes.
                self.transport.get_model_update = self._get_model_update
                self.transport.get_model_version = (
                    lambda: self.latest_model_version)

        # Disaggregated batched-inference serving plane (ROADMAP item 2,
        # runtime/inference.py): colocated with this learner, fed
        # in-process from the publish path — thin clients
        # (actor.host_mode: "remote") get batched actions with zero
        # model-distribution wire hops. grpc fleets ride the in-band
        # GetActions RPC; zmq/native fleets the dedicated ROUTER plane.
        self.inference = None
        serving_cfg = self.config.get_serving_params()
        if serving is not None:
            # Ctor override for drivers/benches that decide the topology
            # programmatically (examples/train_distributed.py
            # --host-mode remote); config holds every other knob.
            serving_cfg["enabled"] = bool(serving)
        if serving_cfg["enabled"] and self.transport is not None:
            from relayrl_tpu.runtime.inference import InferenceService

            try:
                self.inference = InferenceService.from_config(
                    self.algorithm.bundle(), self.config, validate=False)
            except ValueError as e:
                # Sequence policies are not servable yet — the server
                # must still come up for the local actor tiers.
                print(f"[TrainingServer] serving disabled: {e}",
                      flush=True)
            if self.inference is not None:
                self._wire_serving_plane(addr_overrides)

        self._stop = threading.Event()
        self._learner_thread: threading.Thread | None = None
        self._staging_threads: list[threading.Thread] = []
        self._mh_ready: list = []   # assembled-but-untrained epoch batches
        self._mh_busy = False       # a broadcast step is in flight
        self.active = False
        # Pipelined learner hot path (single-host): the learner thread is
        # dispatch-only — updates enter the algorithm's bounded in-flight
        # window unfenced, the publish runs on a dedicated latest-wins
        # thread, assembled batches prefetch to the device, and epoch
        # logs defer until their update's fence. Knobs (docs/operations):
        #   learner.max_inflight_updates  (algorithm-side; 0 = sync)
        #   learner.async_publish         false = publish on learner thread
        #   learner.device_prefetch       false = H2D inside the dispatch
        #   learner.ingest_staging_threads  decode workers (default 1)
        self._async_publish = bool(learner_cfg.get("async_publish", True))
        self._prefetch = bool(learner_cfg.get("device_prefetch", True))
        self._staging_count = max(
            1, int(learner_cfg.get("ingest_staging_threads", 1)))
        self._publisher = None
        # Distance-gate anchors for the model artifact and the periodic
        # checkpoint — seeded from the (possibly resumed) version so a
        # resume doesn't immediately re-save what it just restored.
        self._artifact_version = int(self.algorithm.version)
        self._ckpt_version = int(self.algorithm.version)
        from collections import deque

        self._pending_logs: deque = deque()
        # Sampled trajectory contexts staged-but-not-yet-consumed: the
        # next update dispatch closes them out with an "update" span +
        # the data-age observation (learner thread only). Bounded as a
        # belt — contexts only enter while the tracer is live, but a
        # plugin algorithm that never updates must not hoard them.
        self._trace_pending: deque = deque(maxlen=8192)
        self._timings_lock = threading.Lock()
        # "dropped" counts transport/queue-level losses; the ingest
        # finite-value guard's count is mirrored from the algorithm after
        # each trajectory so operators see poisoning without reaching
        # into algorithm internals.
        self.stats = {"trajectories": 0, "updates": 0, "dropped": 0,
                      "dropped_nonfinite": 0}
        # Per-thread time ledger (seconds): where the ingest pipeline
        # actually spends its time — the profile evidence that the learner
        # thread waits on the device, not on msgpack (SURVEY §7.4-1).
        #   decode_s      staging thread(s) inside decode
        #   dispatch_s    learner thread enqueueing host work (assemble +
        #                 async update dispatch + publish handoff)
        #   device_wait_s learner thread fenced on the device (in-flight
        #                 window + idle drains) — split from dispatch_s
        #                 because async dispatch makes a single "learn"
        #                 bucket meaningless (jaxlint JAX06)
        #   publish_s     publisher thread inside gather/serialize/send
        #   learn_s       legacy total: learner thread inside trajectory
        #                 processing (dispatch + deferred logs + fences
        #                 that land there); superseded by the split above
        #   learner_idle_s learner thread blocked on an empty queue
        #   warmup_s      learner thread pre-compiling update shapes
        self.timings = {"decode_s": 0.0, "learn_s": 0.0, "dispatch_s": 0.0,
                        "device_wait_s": 0.0, "publish_s": 0.0,
                        "learner_idle_s": 0.0, "warmup_s": 0.0}
        self._warmup_done = threading.Event()

        self._tb = None
        if tensorboard:
            from relayrl_tpu.utils.tb_writer import TensorboardWriter

            self._tb = TensorboardWriter.from_logger(
                self.algorithm.logger, self.config.get_tb_params())

        if handle_signals:
            self._install_signal_handlers()
        if start:
            self.enable_server()

    def _install_signal_handlers(self) -> None:
        """Opt-in SIGTERM/SIGINT handling for long-lived deployments
        (systemd stop, k8s pod eviction, ^C): write a final full-state
        checkpoint, shut the planes down cleanly, then die by the SAME
        signal so supervisors see an honest exit status. The reference
        has no shutdown path at all beyond process death (SURVEY §5.3);
        pairing this with ``resume=True`` on the next start makes a
        restart lose nothing. Only possible on the main thread
        (CPython restriction) — elsewhere this is a no-op with a note."""
        import signal

        def _handler(signum, frame):
            # First thing: restore default disposition on BOTH signals, so
            # a second ^C / a supervisor's follow-up SIGTERM kills
            # immediately instead of re-entering a save in flight.
            for sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(sig, signal.SIG_DFL)
            name = signal.Signals(signum).name
            print(f"[TrainingServer] {name}: final checkpoint + clean "
                  f"shutdown", flush=True)
            try:
                # Quiesce BEFORE snapshotting: joins the learner/staging
                # threads so state/version/replay ring aren't mid-mutation
                # under the save. Undelivered queue items are dropped —
                # nothing the learner had trained on is lost.
                # Multi-host: peers may be mid-collective and only THIS
                # rank got the signal — an unbounded join can outlive the
                # supervisor's grace period so the re-raise below never
                # runs and the pod is SIGKILLed with sockets still open.
                # Bound the quiesce; a timed-out thread dies with the
                # process (the final save is skipped on multi-host anyway).
                grace = (10.0 if self.distributed_info["multi_host"]
                         else None)
                self.disable_server(join_timeout=grace)
                if (self._checkpoint_dir and self.algorithm.version > 0
                        and not self.distributed_info["multi_host"]):
                    # Multi-host saves are collective and version-gated
                    # (every rank must enter together); an eviction-time
                    # solo save would deadlock the mesh — rely on the
                    # periodic collective checkpoints there.
                    from relayrl_tpu.checkpoint import checkpoint_algorithm

                    try:
                        # overwrite: a periodic save may already sit at
                        # this version WITHOUT the replay snapshot (aux
                        # cadence) — the final save must land with it, so
                        # a same-step collision bumps to a fresh step
                        # instead of being skipped (never deletes).
                        checkpoint_algorithm(self.algorithm,
                                             self._checkpoint_dir, wait=True,
                                             overwrite=True,
                                             extra_meta=self._health_tag())
                        self._save_ledger_sidecar(self.algorithm.version)
                    except Exception as e:
                        self._m_ckpt_failures.inc()
                        from relayrl_tpu import telemetry

                        telemetry.emit("checkpoint_failed",
                                       version=self.algorithm.version,
                                       error=repr(e), consecutive=1,
                                       dir=str(self._checkpoint_dir))
                        print(f"[TrainingServer] final checkpoint skipped: "
                              f"{e!r}", flush=True)
            finally:
                signal.raise_signal(signum)

        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                signal.signal(sig, _handler)
        except ValueError:  # not the main thread
            print("[TrainingServer] handle_signals requested off the main "
                  "thread — skipped (install handlers in your main thread "
                  "and call disable_server there instead)", flush=True)

    def _wire_serving_plane(self, addr_overrides: dict) -> None:
        """Attach the InferenceService's action channel to the fleet's
        transport kind: in-band ``GetActions`` where the backend carries
        request/response RPCs (pure-grpcio), else the dedicated zmq
        ROUTER plane at ``server.inference_server`` (zmq fleets natively;
        native framed-TCP fleets as the documented passthrough — the C++
        core has no action RPC)."""
        if getattr(self.transport, "supports_inband_infer", False):
            self.transport.on_infer = self.inference.handle_request_blocking
            # Bidi StreamActions (serving v2): one parked RPC thread per
            # stream regardless of in-flight depth — frames go through
            # the non-blocking enqueue, replies ride the batch worker's
            # callbacks.
            self.transport.on_infer_submit = self.inference.handle_request
        else:
            self.inference.bind_zmq(addr_overrides.get(
                "serving_addr",
                self.config.get_inference_server().address))

    @staticmethod
    def _get_tracer():
        from relayrl_tpu.telemetry import trace as trace_mod

        return trace_mod.get_tracer()

    # -- transport callbacks (transport threads!) --
    def _count_dropped(self, n: int = 1) -> None:
        """stats['dropped'] is written from transport threads AND the N
        decode workers — an unlocked += loses increments exactly when
        the operator most needs the counter (docs/operations.md says to
        watch it to size ingest_staging_threads)."""
        with self._timings_lock:
            self.stats["dropped"] += n
            total = self.stats["dropped"]
            due = self._drop_events.add(n)
        self._m_dropped.inc(n)
        if due:
            from relayrl_tpu import telemetry

            telemetry.emit("drop", n=due, total=total)

    def _flush_drop_event(self) -> None:
        """Emit any drop/duplicate count still coalescing (quiesce paths:
        drain success, disable_server) — without this, counts accumulated
        in the 1-s window after the last emitted event would never reach
        the journal."""
        with self._timings_lock:
            pending = self._drop_events.flush()
            total = self.stats["dropped"]
            dup_pending = self._dup_events.flush()
        if pending or dup_pending:
            from relayrl_tpu import telemetry

            if pending:
                telemetry.emit("drop", n=pending, total=total)
            if dup_pending:
                telemetry.emit("duplicate_drop", n=dup_pending)

    def _count_duplicate(self, n: int = 1) -> None:
        """Duplicate-drop accounting, coalesced to <=1 journal event/s
        (a replay burst after a reconnect is hundreds of lines
        otherwise)."""
        self._m_duplicates.inc(n)
        with self._timings_lock:
            due = self._dup_events.add(n)
        if due:
            from relayrl_tpu import telemetry

            telemetry.emit("duplicate_drop", n=due)

    def _admit_seq(self, agent_id: str):
        """Split the sequence AND trace tags off an envelope id and
        consult the dedup ledger: ``(clean_agent_id, seq, ctx, admit)``.
        Both tags strip unconditionally — like the seq tag, a trace
        context must never leak into attribution/quarantine keys even
        when this process records no spans. Untagged ids (raw transport
        users, pre-spool fleets) admit with seq/ctx None."""
        clean_id, seq = split_agent_seq(agent_id)
        clean_id, ctx = _split_trace_ctx(clean_id)
        if seq is None or self._ingest_ledger is None:
            return clean_id, seq, ctx, True
        if not self._ingest_ledger.accept(clean_id, seq):
            self._count_duplicate()
            return clean_id, seq, ctx, False
        return clean_id, seq, ctx, True

    def _on_trajectory(self, agent_id: str, payload: bytes) -> None:
        if self._fault_ingest is not None:
            # chaos plane: drop/delay/duplicate/corrupt AFTER the wire —
            # the frame arrived but the server mishandles it (actor
            # replay + dedup must make the loop whole again).
            for delay_s, part in self._fault_ingest.inject(payload):
                if delay_s > 0:
                    time.sleep(delay_s)
                self._ingest_one(agent_id, part)
            return
        self._ingest_one(agent_id, payload)

    def _check_ingest(self, tagged_id: str):
        """Guardrail admission verdict for ack-capable transports (the
        pure-grpcio servicer calls this BEFORE on_trajectory): ``None``
        admits; ``(nack_code, reason, retry_after_s)`` is returned to
        the sender as a typed nack the actor's spool understands
        (quarantine → discard the entry; overload → keep it, replay
        later). Broadcast planes — and the native C++ gRPC server,
        which acks in C++ before Python sees the send — never call
        this; the same verdicts are enforced server-side in _ingest_one.
        Runs on transport threads."""
        g = self.guardrails
        if g is None:
            return None
        from relayrl_tpu.transport.base import (
            NACK_OVERLOADED,
            NACK_QUARANTINED,
            split_agent_seq,
        )

        from relayrl_tpu.transport.base import split_agent_trace

        agent_id, _ = split_agent_seq(tagged_id)
        agent_id, _ = split_agent_trace(agent_id)
        if self._halted:
            # NOT counted as a halted drop: an overload nack is retained
            # by the sender's spool and replayed — counting each replay
            # would read as unbounded data loss that never happened (the
            # genuine-shed sites in _ingest_one/_on_trajectory_decoded
            # own that counter).
            return (NACK_OVERLOADED, "guardrails halted", 30.0)
        if g.quarantine.is_quarantined(agent_id):
            g.quarantine.count_rejected_send()
            return (NACK_QUARANTINED, "agent quarantined",
                    g.quarantine.retry_after(agent_id))
        adm = g.admission
        if adm is not None and adm.policy == "nack":
            # Under the nack shed policy the back-channel IS the shed:
            # decide here so the sender's spool keeps the entry and
            # retries after the hint. (admit() only mutates shed
            # counters, so an "admit" verdict here followed by the
            # _ingest_one re-check is harmless.)
            verdict = adm.admit(agent_id)
            if verdict in ("nack", "shed_agent"):
                reason = ("agent over fair share"
                          if verdict == "shed_agent" else "ingest overloaded")
                return (NACK_OVERLOADED, reason, adm.retry_after_s)
        return None

    def _ingest_one(self, agent_id: str, payload: bytes,
                    depth: int = 0) -> None:
        if is_snapshot_frame(payload):
            # Fleet telemetry frame (ISSUE 15): route to the fleet table
            # BEFORE dedup/guardrails — telemetry carries no seqs, must
            # never strike a quarantine book, and a fleet-less server
            # treats it as inert noise rather than a decode failure
            # (which would count drops and could fire the drops alert
            # the frames exist to deliver).
            fleet = self._fleet
            if fleet is not None:
                try:
                    fleet.ingest_frame(payload)
                except ValueError as e:
                    swallow_decode_error(self.server_type, "fleet_frame", e)
            return
        if batch_kind(payload) == BATCH_KIND_ENVELOPES and depth < 8:
            # Relay upstream forward (ISSUE 11): one wire send carrying N
            # whole subtree envelopes, each with its leaf agent's id +
            # seq tag verbatim — split and run every inner envelope
            # through the normal per-agent funnel, so dedup/guardrails
            # see exactly what a flat fleet would have sent. Recursion
            # covers relay-behind-relay nesting; the depth cap is the
            # hostile-frame guard.
            try:
                parts = split_batch(payload)
            except ValueError as e:
                swallow_decode_error(self.server_type, "envelope_batch", e)
                self._count_dropped()
                return
            for part in parts:
                try:
                    inner_id, inner_payload = unpack_trajectory_envelope(part)
                except Exception as e:
                    swallow_decode_error(self.server_type,
                                         "envelope_batch", e)
                    self._count_dropped()
                    continue
                self._ingest_one(inner_id, inner_payload, depth=depth + 1)
            return
        # Trace hops (telemetry/trace.py): clock reads gate on a live
        # tracer, span recording on the envelope actually carrying a
        # sampled context — the untraced hot path pays one attribute
        # check plus (tracer live) one monotonic_ns.
        tracer = self._get_tracer()
        t_arr = time.monotonic_ns() if tracer.enabled else 0
        agent_id, seq, ctx, admit = self._admit_seq(agent_id)
        if not tracer.enabled:
            # The tag is stripped regardless; the context only FLOWS when
            # this process traces (a mixed fleet — traced actors, trace-
            # off server — must not accumulate contexts it never drains).
            ctx = None
        elif ctx is not None:
            t_ded = time.monotonic_ns()
            tracer.span("traj", ctx.trace_id, "ingest", t_arr, t_arr,
                        agent=agent_id, seq=seq)
            tracer.span("traj", ctx.trace_id, "dedup", t_arr, t_ded,
                        admitted=bool(admit))
        if not admit:
            return

        def retract():
            # un-see the seq: the actor's replay must be able to land
            # this trajectory later — a shed is backpressure, not dedup.
            if seq is not None and self._ingest_ledger is not None:
                self._ingest_ledger.retract(agent_id, seq)

        g = self.guardrails
        if g is not None:
            if self._halted:
                g._m_halted_drops.inc()
                retract()
                return
            if g.quarantine.is_quarantined(agent_id):
                # Broadcast planes (zmq PUSH, native) have no per-send
                # back-channel: the quarantine sheds here, silently to
                # the sender, loudly to telemetry.
                g.quarantine.count_rejected_send()
                retract()
                return
            if g.admission is not None:
                verdict = g.admission.admit(agent_id)
                if verdict in ("shed_agent", "nack"):
                    retract()
                    return
                if verdict == "evict":
                    self._evict_oldest_raw()
        try:
            self._ingest.put_nowait((agent_id, seq, ctx, payload))
            if g is not None and g.admission is not None:
                g.admission.note_enqueued(agent_id)
        except queue.Full:
            retract()
            self._count_dropped()

    def _evict_oldest_raw(self) -> None:
        """drop_oldest shed: evict the globally oldest queued raw payload
        to admit a fresh one (freshest-data-wins). The victim's seq is
        retracted from the dedup ledger so the owning actor's spool can
        redeliver it when pressure clears."""
        try:
            victim_id, victim_seq, _ctx, _ = self._ingest.get_nowait()
        except queue.Empty:
            return
        self._ingest.task_done()
        if victim_seq is not None and self._ingest_ledger is not None:
            self._ingest_ledger.retract(victim_id, victim_seq)
        adm = self.guardrails.admission if self.guardrails else None
        if adm is not None:
            adm.note_dequeued(victim_id)

    def _on_trajectory_decoded(self, batch) -> None:
        """Pre-decoded columnar trajectory batch from the native drain —
        skips the staging thread entirely (one queue entry per drain).
        Sequence tags ride the decoded items' agent ids through the C++
        core; they are split + deduped here, and the clean id is written
        back so per-agent attribution stays tag-free downstream."""
        g = self.guardrails
        tracer = self._get_tracer()
        t_arr = time.monotonic_ns() if tracer.enabled else 0
        admitted = []
        for item in batch:
            clean_id, seq, ctx, admit = self._admit_seq(item.agent_id)
            if ctx is not None and not tracer.enabled:
                ctx = None  # see _ingest_one: never flow undrained ctxs
            if ctx is not None:
                # The native C++ core already decoded this payload; the
                # ingest/dedup hops collapse to the drain's arrival.
                tracer.span("traj", ctx.trace_id, "ingest", t_arr, t_arr,
                            agent=clean_id, seq=seq)
                tracer.span("traj", ctx.trace_id, "dedup", t_arr,
                            time.monotonic_ns(), admitted=bool(admit))
                if admit:
                    item.trace_ctx = ctx
            if not admit:
                continue
            if clean_id != item.agent_id:
                item.agent_id = clean_id
            if g is not None:
                # Same guardrail funnel as the staged path: halted shed,
                # quarantine shed, then validation + strike accounting.
                # (Admission backpressure governs the raw ingest queue;
                # this plane delivers pre-decoded batches whose depth the
                # native core already bounds.)
                if self._halted:
                    g._m_halted_drops.inc()
                    continue
                if g.quarantine.is_quarantined(clean_id):
                    g.quarantine.count_rejected_send()
                    if seq is not None and self._ingest_ledger is not None:
                        self._ingest_ledger.retract(clean_id, seq)
                    continue
                if g.validate(clean_id, item) is None:
                    continue
            admitted.append((item, seq))
        if not admitted:
            return
        try:
            self._decoded.put_nowait([item for item, _ in admitted])
        except queue.Full:
            if self._ingest_ledger is not None:
                for item, seq in admitted:
                    if seq is not None:
                        self._ingest_ledger.retract(item.agent_id, seq)
            self._count_dropped(len(admitted))

    def _get_model(self) -> tuple[int, bytes]:
        """Current full model as v1 bundle bytes (handshakes, artifact
        writes, gRPC resyncs). Serialized lazily from the latest
        published host tree — at most once per version (barring a benign
        handshake race), and not at all for versions nobody handshakes
        during (the wire-v2 serialize saving; v1 publishes still store
        their bytes eagerly). The serialize itself runs OUTSIDE
        ``_bundle_lock``: a multi-second flax serialize of a large model
        under the lock would stall every version probe and the
        publisher's host-snapshot store."""
        with self._bundle_lock:
            host = self._bundle_host
            if host is None or host[0] == self._bundle_version:
                return self._bundle_version, self._bundle_bytes
        ver, arch, params = host
        from relayrl_tpu.types.model_bundle import ModelBundle

        raw = ModelBundle(version=ver, arch=dict(arch),
                          params=params).to_bytes()
        with self._bundle_lock:
            if ver > self._bundle_version:
                self._bundle_bytes = raw
                self._bundle_version = ver
            # A racing caller may have installed a newer version; the
            # cached pair is always internally consistent either way.
            return self._bundle_version, self._bundle_bytes

    def _get_model_update(self, known_version: int) -> tuple[int, bytes]:
        """Freshest blob a subscriber at ``known_version`` can decode:
        the latest wire frame when its base matches (or it is a
        keyframe), else the full v1 bundle (the server-side resync —
        cheaper than bouncing the subscriber through an extra RTT)."""
        enc = self._wire_encoder
        if enc is not None:
            got = enc.frame_for(known_version)
            if got is not None:
                return got
        return self._get_model()

    def _on_resync_request(self, held_version: int = -1) -> None:
        """CMD_RESYNC from the broadcast plane (zmq ROUTER thread): a
        subscriber's delta base diverged mid-stream — force the next
        publish to keyframe so it heals in <= 1 publish instead of <=
        keyframe_interval. ``held_version`` (the requester's, -1 when
        unknown) is only consulted by RELAYS; the root's forced keyframe
        heals any held version. Coalesced (force_keyframe is one flag
        per publish) and rate-limited
        (``transport.resync_min_interval_s``) so a storm of diverged
        subscribers grants one keyframe per window. A v1 server ignores
        it: every publish is already a full model."""
        self._m_resync_requests.inc()
        enc = self._wire_encoder
        if enc is None:
            return
        now = time.monotonic()
        with self._resync_lock:
            if now - self._last_resync_grant < self._resync_min_interval_s:
                return
            self._last_resync_grant = now
        enc.force_keyframe()
        self._m_resync_granted.inc()
        from relayrl_tpu import telemetry

        telemetry.emit("resync_keyframe_forced",
                       version=self.latest_model_version)

    @property
    def latest_model_version(self) -> int:
        """Version of the most recently published model — what an
        agent's hot-swap should converge to (embedder/eval surface).
        Reads the published host snapshot, not the lazily-serialized v1
        byte cache, which may trail it under wire v2."""
        with self._bundle_lock:
            if self._bundle_host is not None:
                return max(self._bundle_version, self._bundle_host[0])
            return self._bundle_version

    def _on_register(self, agent_id: str) -> None:
        with self._registry_lock:
            if agent_id not in self.agent_ids:
                self.agent_ids.append(agent_id)
                fresh = True
            else:
                fresh = False
        if fresh:
            from relayrl_tpu import telemetry

            telemetry.emit("agent_register", agent_id=agent_id,
                           registered=len(self.agent_ids))

    def _on_unregister(self, agent_id: str) -> None:
        """Elastic-fleet reaping (the reference's registry is append-only,
        training_server_wrapper.rs:159-163): a dead agent's id leaves the
        registry so long-lived fleets under churn don't accumulate
        ghosts."""
        with self._registry_lock:
            try:
                self.agent_ids.remove(agent_id)
            except ValueError:
                return
        from relayrl_tpu import telemetry

        telemetry.emit("agent_unregister", agent_id=agent_id,
                       registered=len(self.agent_ids))

    # -- staging: raw payload -> decoded trajectory (overlaps learner) --
    def _staging_loop(self) -> None:
        from relayrl_tpu.transport.base import BATCH_KIND_FRAMES
        from relayrl_tpu.types.columnar import (
            RawTrajectory,
            is_columnar_frame,
            parse_frame,
        )

        decoder = None
        try:
            from relayrl_tpu.types.columnar import NativeDecoder

            decoder = NativeDecoder()
        except Exception:
            pass  # native codec unavailable: pure-Python decode
        guard = self.guardrails
        while not self._stop.is_set():
            try:
                agent_id, seq, ctx, payload = self._ingest.get(timeout=0.1)
            except queue.Empty:
                continue
            if guard is not None and guard.admission is not None:
                guard.admission.note_dequeued(agent_id)
            item = None
            columnar = False
            t0_ns = time.monotonic_ns() if ctx is not None else 0
            t0 = time.monotonic()
            try:
                if is_columnar_frame(payload):
                    # Columnar wire fast path (anakin actors): the frame
                    # IS the folded column layout — a CRC check plus a
                    # handful of np.frombuffer views, no msgpack, no
                    # per-step objects, on every transport.
                    columnar = True
                    item = parse_frame(payload, agent_id=agent_id)
                    self._m_columnar_frames.inc()
                    self._m_columnar_bytes.inc(len(payload))
                elif batch_kind(payload) == BATCH_KIND_FRAMES:
                    # Coalesced columnar segments (actor.emit_coalesce_
                    # frames / relay batch-forward): one spooled send —
                    # one seq, one envelope — carrying N frames of ONE
                    # logical lane; decode each and hand the learner the
                    # list (the native drain's batch shape).
                    columnar = True
                    parts = split_batch(payload)
                    item = [parse_frame(p, agent_id=agent_id)
                            for p in parts]
                    self._m_columnar_frames.inc(len(parts))
                    self._m_columnar_bytes.inc(len(payload))
                elif decoder is not None:
                    # off-GIL msgpack -> columns; falls back to the Python
                    # decoder only for payloads the columnar schema can't
                    # represent
                    item = decoder.decode(payload, agent_id=agent_id)
                    if isinstance(item, RawTrajectory):
                        raw = item.payload
                        if item.is_envelope:
                            from relayrl_tpu.transport.base import (
                                unpack_trajectory_envelope,
                            )

                            _, raw = unpack_trajectory_envelope(raw)
                        item = deserialize_actions(raw)
                else:
                    item = deserialize_actions(payload)
            except Exception:
                if columnar:
                    self._m_columnar_rejects.inc()
                # Un-see the seq: the payload never reached the learner
                # (CRC/parse failure), so the actor's spool replay must be
                # able to land its retained clean copy later.
                if seq is not None and self._ingest_ledger is not None:
                    self._ingest_ledger.retract(agent_id, seq)
                self._count_dropped()
            if item is not None and guard is not None:
                # Ingest validation + per-agent strike accounting: the
                # semantic trust boundary, BEFORE the decoded item can
                # reach the staging slabs. None = rejected (counted,
                # struck; the poison never reaches the learner plane).
                # Coalesced batches validate per contained trajectory —
                # one poisoned segment must not veto its clean siblings.
                if (isinstance(item, list) and item
                        and isinstance(item[0], DecodedTrajectory)):
                    item = [one for one in item
                            if guard.validate(agent_id, one) is not None]
                    if not item:
                        item = None
                else:
                    item = guard.validate(agent_id, item)
            dt = time.monotonic() - t0
            self._m_decode.observe(dt)  # per-thread shard: no lock needed
            with self._timings_lock:  # N decode workers share the ledger
                self.timings["decode_s"] += dt
            if ctx is not None and item is not None:
                # staging hop (decode + validate) + context handoff: the
                # learner attributes the consuming update at dispatch.
                self._get_tracer().span(
                    "traj", ctx.trace_id, "staging", t0_ns,
                    time.monotonic_ns(), agent=agent_id)
                item = _attach_trace_ctx(item, ctx)
            if item is not None:
                try:
                    self._decoded.put_nowait(item)
                except queue.Full:
                    # Same contract as every other shed path: un-see the
                    # seq so the sender's spool replay can land this
                    # trajectory once pressure clears (a shed is
                    # backpressure, not loss).
                    if seq is not None and self._ingest_ledger is not None:
                        self._ingest_ledger.retract(agent_id, seq)
                    self._count_dropped()
            # task_done only after the decoded item is enqueued, so
            # drain()'s two-queue emptiness check never races the handoff
            self._ingest.task_done()

    # -- multi-host learner loop (SPMD broadcast protocol) --
    # Every process loops in lockstep on a fixed-shape control broadcast:
    # IDLE ticks keep non-coordinators synchronized while the coordinator
    # accumulates trajectories; STEP carries the batch shape, then the
    # batch itself, then all processes run the sharded update + the
    # collective bundle all-gather; STOP tears everyone down together.
    _MH_IDLE, _MH_STEP, _MH_STOP = 0, 1, 2

    def _mh_accumulate(self, item) -> dict | None:
        """Coordinator: feed one decoded queue entry into the algorithm
        buffer; returns a ready training batch dict (at most one per call
        — extras queue in _mh_ready). On-policy accumulate yields one
        epoch batch; off-policy yields a LIST of sampled transition
        batches (the update-to-data ratio's worth)."""
        items = (item if (isinstance(item, list) and item
                          and isinstance(item[0], DecodedTrajectory))
                 else [item])
        for one in items:
            self.stats["trajectories"] += 1
            self._m_trajectories.inc()
            try:
                got = self.algorithm.accumulate(one)
            except Exception as e:
                print(f"[TrainingServer] accumulate error: {e!r}", flush=True)
                continue
            finally:
                self._sync_drop_stats()
            if isinstance(got, list):
                self._mh_ready.extend(got)
            elif got is not None:
                self._mh_ready.append(got)
        return self._mh_ready.pop(0) if self._mh_ready else None

    def _learner_loop_multihost(self) -> None:
        import numpy as np

        from relayrl_tpu.parallel.distributed import (
            broadcast_from_coordinator,
            is_coordinator,
        )

        coord = is_coordinator()
        while True:
            batch = None
            if coord:
                # STOP preempts any ingest backlog: disable_server must
                # terminate the fleet within one in-flight step, not
                # after draining hundreds of queued trajectories.
                if not self._stop.is_set():
                    if self._mh_ready:
                        # _mh_busy flips BEFORE the batch leaves the
                        # queues (here and below, ahead of task_done):
                        # drain() checks queues-empty AND ready-empty AND
                        # not-busy, so a gap between "popped" and "busy"
                        # would let it report drained with a step pending.
                        self._mh_busy = True
                        batch = self._mh_ready.pop(0)
                    tick_deadline = time.monotonic() + 0.2
                    while batch is None and time.monotonic() < tick_deadline:
                        try:
                            item = self._decoded.get(timeout=0.05)
                        except queue.Empty:
                            continue
                        try:
                            batch = self._mh_accumulate(item)
                            if batch is not None:
                                self._mh_busy = True
                        finally:
                            self._decoded.task_done()
                code = (self._MH_STOP if self._stop.is_set()
                        else self._MH_STEP if batch is not None
                        else self._MH_IDLE)
                desc = np.array(
                    [code,
                     batch["obs"].shape[0] if batch is not None else 0,
                     batch["obs"].shape[1] if batch is not None else 0],
                    np.int64)
            else:
                desc = np.zeros(3, np.int64)
            desc = broadcast_from_coordinator(desc)
            code = int(desc[0])
            if code == self._MH_STOP:
                self._mh_busy = False  # a preempted batch is dropped
                # Fence what was dispatched and flush its deferred logs
                # (every rank drains its own window — the programs were
                # dispatched symmetrically, so they all complete), then
                # resolve the fenced probes before shutdown.
                self._pipeline_quiesce()
                if coord:
                    self._guard_poll()
                break
            if code == self._MH_IDLE:
                # Idle is fence-for-free, as in the single-host loop: the
                # device has nothing queued behind the in-flight sharded
                # updates, so resolving them costs no overlap — and it is
                # what lets drain() observe pending -> 0 on every rank.
                self._pipeline_quiesce()
                if coord:
                    self._guard_poll()
                continue
            if not coord:
                batch = self.algorithm.mh_zero_batch(int(desc[1]),
                                                     int(desc[2]))
            self._mh_busy = True
            batch = broadcast_from_coordinator(batch)
            algo = self.algorithm
            t0 = time.monotonic()
            try:
                if self._prefetch:
                    # Eager sharded H2D (device_put with NamedSharding
                    # via the mesh-aware _place): the transfer enqueues
                    # now and overlaps the in-flight updates instead of
                    # running inside the dispatch below.
                    batch = algo.stage_batch(batch)
                # Dispatch-only: the sharded update enters the in-flight
                # window unfenced (its collectives live inside the XLA
                # program, so nothing here blocks the host).
                algo.train_on_batch(batch)
            except Exception as e:
                print(f"[TrainingServer] multi-host update error: {e!r}",
                      flush=True)
                self._mh_busy = False
                continue  # symmetric on all ranks: same data, same failure
            if (coord and self.guardrails is not None
                    and self.guardrails.watchdog is not None
                    and self.distributed_info["num_processes"] == 1):
                # Health probes ride LazyMetrics through the window on
                # every rank (they are jitted over the same sharded
                # state). The watchdog DETECTOR stays single-process:
                # its rollback path restores a checkpoint, which is a
                # collective a coordinator-solo trip would hang on.
                self.guardrails.watchdog.observe_dispatch(
                    algo.inflight.dispatch_count, algo._last_metrics)
            if coord:
                self.stats["updates"] += 1
                self._m_updates.inc()
                # Epoch log: captured now (on-policy: one per update;
                # off-policy: the trajectory cadence), dumped once the
                # update it describes is fenced.
                payload = algo.capture_epoch_stats(True)
                if payload is not None:
                    self._pending_logs.append(
                        (algo.inflight.dispatch_count, payload,
                         algo._last_metrics))
            dispatch_dt = time.monotonic() - t0
            self.timings["dispatch_s"] += dispatch_dt
            self._m_dispatch.observe(dispatch_dt)
            try:
                if self._async_publish:
                    # The publish gather (jitted re-shard to replicated)
                    # is a collective DISPATCH on every rank — symmetric
                    # by construction since async_publish comes from the
                    # shared config; only the coordinator owns a
                    # transport, so only it hands the snapshot to the
                    # publisher thread (D2H + encode off this thread).
                    snapshot = algo.snapshot_for_publish()
                    if coord and self._publisher is not None:
                        self._publisher.submit(snapshot)
                    ckpt_version = algo.dispatched_version
                else:
                    bundle = algo.bundle()  # collective + fences (escape
                    if coord:               # hatch: async_publish false)
                        import jax

                        self._publish_params(bundle.version, bundle.arch,
                                             jax.device_get(bundle.params))
                    ckpt_version = bundle.version
            except Exception as e:
                print(f"[TrainingServer] publish error: {e!r}", flush=True)
                ckpt_version = algo.dispatched_version
            # Full-state checkpoint is COLLECTIVE on a multi-host mesh
            # (orbax needs every process to contribute its shards to the
            # shared checkpoint_dir); the due-check derives from the
            # host-side version mirror, which advances identically on
            # every rank, so all agree without extra coordination — and
            # the checkpoint path quiesces the window first, extending
            # the quiesce contract to in-flight sharded updates.
            self._maybe_periodic_checkpoint(ckpt_version)
            if coord:
                self._flush_ready_logs()
                self._guard_poll()
            self._mh_busy = False

    # -- learner loop --
    def _learner_loop(self) -> None:
        if not self._warmup_done.is_set():
            # Pre-compile the update for every shape the first epochs can
            # hit, while the fleet is still handshaking/playing its first
            # episodes. Without this, the first compile lands under ingest
            # load — and in a one-process deployment (notebook kernel
            # hosting server + busy actor loop on a small host) a ~2 s
            # compile competing with the actor loop for CPU can stretch
            # past the whole example run, so no update ever happens live.
            t0 = time.monotonic()
            try:
                n = self.algorithm.warmup(
                    should_continue=lambda: (self._decoded.empty()
                                             and self._ingest.empty()
                                             and not self._stop.is_set()))
                if n:
                    print(f"[TrainingServer] warmup: {n} update shape(s) "
                          f"compiled in {time.monotonic() - t0:.1f}s",
                          flush=True)
            except Exception as e:  # best-effort: first batch compiles then
                print(f"[TrainingServer] warmup failed (non-fatal): {e!r}",
                      flush=True)
            finally:
                self.timings["warmup_s"] += time.monotonic() - t0
                self._warmup_done.set()
        while not self._stop.is_set():
            t_wait = time.monotonic()
            try:
                item = self._decoded.get(timeout=0.1)
            except queue.Empty:
                self.timings["learner_idle_s"] += time.monotonic() - t_wait
                # Idle is fence-for-free: the device has nothing queued
                # behind the in-flight updates, so resolving them (and
                # flushing their deferred epoch logs) costs no overlap —
                # and it is what lets drain() observe pending -> 0.
                self._pipeline_quiesce()
                # Everything dispatched is now fenced: resolve every
                # pending health probe (free post-fence) and act on trips.
                self._guard_poll()
                continue
            self.timings["learner_idle_s"] += time.monotonic() - t_wait
            if self._halted:
                # Degraded halt-and-alarm: training is stopped (rollback
                # budget spent / no healthy checkpoint); drain and drop
                # so the queues don't balloon while the operator digs.
                if self.guardrails is not None:
                    self.guardrails._m_halted_drops.inc(
                        len(item) if isinstance(item, list) else 1)
                self._decoded.task_done()
                continue
            t0 = time.monotonic()
            try:
                # A native drain batch is a list of DecodedTrajectory; a
                # Python-decoded single trajectory is a list of
                # ActionRecord (and a staged columnar one is a bare
                # DecodedTrajectory) — disambiguate on the element type.
                if (isinstance(item, list) and item
                        and isinstance(item[0], DecodedTrajectory)):
                    for one in item:
                        self._process_one(one)
                else:
                    self._process_one(item)
            finally:
                self.timings["learn_s"] += time.monotonic() - t0
                self._decoded.task_done()
        # Shutdown: fence what was dispatched and flush its logs so
        # disable_server leaves state/progress.txt consistent — then
        # resolve the fenced probes, so the signal-path final save's
        # healthy-at-save tag covers every update baked into it (a
        # poisoned last update must trip here, not get tagged healthy).
        self._pipeline_quiesce()
        self._guard_poll()

    def _observe_behavior_lag(self, item, algo, ctx=None) -> None:
        """RLHF-plane off-policy evidence: trajectories whose records
        carry ``bver`` (the params version the generation sampled
        under — rlhf/scheduler.py stamps it per token) observe
        ``dispatched_version - bver`` into the train-lag histogram, one
        sample per trajectory. A sampled trace context's born_version
        (stamped at emission, telemetry/trace.py) is the same kind of
        behavior-version evidence, so bver-less traced trajectories
        feed the histogram too — the analyzer's version-lag
        distribution and this histogram then describe the same data.
        Non-RLHF untraced traffic pays one dict lookup."""
        try:
            if isinstance(item, DecodedTrajectory):
                arr = (item.aux or {}).get("bver")
                if arr is None or len(arr) == 0:
                    if ctx is not None and ctx.born_version >= 0:
                        self._m_rlhf_train_lag.observe(
                            max(0, algo.dispatched_version
                                - ctx.born_version))
                    return
                bver = int(arr.reshape(-1)[0])
            else:
                data = item[0].data if item else None
                if not data or "bver" not in data:
                    if ctx is not None and ctx.born_version >= 0:
                        self._m_rlhf_train_lag.observe(
                            max(0, algo.dispatched_version
                                - ctx.born_version))
                    return
                bver = int(data["bver"])
            self._m_rlhf_train_lag.observe(
                max(0, algo.dispatched_version - bver))
        except Exception:
            # Lag evidence is diagnostics; malformed aux must never
            # touch the ingest path's health.
            pass

    def _trace_dispatch(self, tracer, algo, t0_ns: int,
                        consume_ver: int) -> None:
        """Close out the tracing bookkeeping of one update dispatch
        (learner thread): the downstream ``dispatch`` hop for sampled
        versions, and for every sampled trajectory context consumed
        since the previous dispatch, the upstream ``update`` hop plus
        the end-to-end data-age / version-lag observations (same-host
        skew-guarded — a cross-host born stamp is dropped, not
        observed)."""
        from relayrl_tpu.telemetry.trace import SKEW_GUARD_NS, model_trace_id

        t1_ns = time.monotonic_ns()
        ver = algo.dispatched_version
        if tracer.sample_version(ver):
            tracer.span("model", model_trace_id(ver), "dispatch",
                        t0_ns, t1_ns, version=int(ver))
        while self._trace_pending:
            ctx = self._trace_pending.popleft()
            # version = the version the batch trained FROM (matching the
            # train_version_lag convention), not the freshly-minted one.
            tracer.span("traj", ctx.trace_id, "update", t0_ns, t1_ns,
                        version=int(consume_ver))
            age_ns = t1_ns - ctx.born_ns
            if 0 <= age_ns < SKEW_GUARD_NS:
                lag = (int(consume_ver) - ctx.born_version
                       if ctx.born_version >= 0 else None)
                tracer.observe_data_age(age_ns / 1e9, lag)

    def _sync_drop_stats(self) -> None:
        """Mirror the algorithm's finite-guard counter into stats — the
        single owner, so every ingest path (single-host, multi-host, any
        future drain) keeps the operator-visible counter fresh."""
        self.stats["dropped_nonfinite"] = getattr(
            self.algorithm, "dropped_nonfinite", 0)
        self._m_nonfinite.set(self.stats["dropped_nonfinite"])

    def _process_one(self, item) -> None:
        """``item``: DecodedTrajectory (columnar fast path) or
        list[ActionRecord] (Python decode). Dispatch-only: the update
        enters the algorithm's in-flight window unfenced, the publish is
        handed to the latest-wins publisher thread, and the epoch log
        defers until the update's fence."""
        algo = self.algorithm
        if not hasattr(algo, "accumulate"):
            # Plugin algorithms implementing only the reference contract
            # (receive_trajectory/train_model/save/log_epoch) keep the
            # original synchronous path — pipelining needs the family
            # accumulate/capture split.
            self._process_one_legacy(item)
            return
        self.stats["trajectories"] += 1
        self._m_trajectories.inc()
        ctx = getattr(item, "trace_ctx", None)
        if ctx is not None:
            self._trace_pending.append(ctx)
        self._observe_behavior_lag(item, algo, ctx)
        tracer = self._get_tracer()
        t0_ns = time.monotonic_ns() if tracer.enabled else 0
        # The version this batch trains FROM (pre-dispatch) — the
        # convention _observe_behavior_lag's histogram uses, so the
        # trace-side version-lag distribution matches it exactly.
        consume_ver = algo.dispatched_version if tracer.enabled else 0
        t0 = time.monotonic()
        try:
            got = algo.accumulate(item)
            updated = got is not None
            if updated:
                batches = got if isinstance(got, list) else [got]
                if self._prefetch:
                    # Eager H2D: enqueued now, the transfer overlaps the
                    # in-flight updates instead of running after the
                    # window fence below.
                    batches = [algo.stage_batch(b) for b in batches]
                if isinstance(got, list):
                    algo.train_on_batches(batches)
                else:
                    algo.train_on_batch(batches[0])
        except Exception as e:  # never kill the loop on one bad batch
            print(f"[TrainingServer] learner error: {e!r}", flush=True)
            return
        finally:
            self._sync_drop_stats()
        if (updated and self.guardrails is not None
                and self.guardrails.watchdog is not None):
            # Queue the dispatched update's (lazy) metrics — probe
            # scalars included — for the watchdog; they resolve at the
            # in-flight fence, never here (the LazyMetrics deferral).
            self.guardrails.watchdog.observe_dispatch(
                algo.inflight.dispatch_count, algo._last_metrics)
        # Epoch log: captured now (episode counters must not leak across
        # epochs), dumped once the update it describes is fenced.
        payload = algo.capture_epoch_stats(updated)
        if payload is not None:
            self._pending_logs.append(
                (algo.inflight.dispatch_count, payload, algo._last_metrics))
        # dispatch_s ends here: the publish handoff below is a lock'd
        # slot swap, but a due checkpoint quiesces + saves — seconds of
        # fence/IO that must not masquerade as host-side enqueue (the
        # window fence is already accounted in device_wait_s).
        dispatch_dt = time.monotonic() - t0
        self.timings["dispatch_s"] += dispatch_dt
        self._m_dispatch.observe(dispatch_dt)
        if tracer.enabled and updated:
            self._trace_dispatch(tracer, algo, t0_ns, consume_ver)
        if updated:
            self.stats["updates"] += 1
            self._m_updates.inc()
            try:
                if self._publisher is not None:
                    self._publisher.submit(algo.snapshot_for_publish())
                    # Full-state checkpointing stays on the learner
                    # thread (orbax save is not publisher-safe); gate on
                    # the host version mirror — int(state.step) would
                    # fence the window.
                    self._maybe_periodic_checkpoint(algo.dispatched_version)
                else:
                    self._publish()  # sync escape hatch (async_publish off)
            except Exception as e:  # transient socket/fs errors must not
                print(f"[TrainingServer] publish error: {e!r}", flush=True)
        self._flush_ready_logs()
        self._guard_poll()

    def _process_one_legacy(self, item) -> None:
        """Pre-pipeline path for plugin algorithms: train + log inside
        receive_trajectory, synchronous publish."""
        self.stats["trajectories"] += 1
        self._m_trajectories.inc()
        try:
            updated = self.algorithm.receive_trajectory(item)
        except Exception as e:  # never kill the loop on one bad batch
            print(f"[TrainingServer] learner error: {e!r}", flush=True)
            return
        finally:
            self._sync_drop_stats()
        if updated:
            self.stats["updates"] += 1
            self._m_updates.inc()
            try:
                self._publish()
            except Exception as e:  # transient socket/fs errors must not
                print(f"[TrainingServer] publish error: {e!r}", flush=True)
            if self._tb is not None:
                try:
                    self._tb.poll()
                except Exception as e:
                    print(f"[TrainingServer] tensorboard error: {e!r}",
                          flush=True)

    def _flush_ready_logs(self, force: bool = False) -> None:
        """Dump deferred epoch logs whose update has been fenced by the
        in-flight window (FIFO — rows land in dispatch order). Runs on
        the learner thread only."""
        win = self.algorithm.inflight
        dumped = False
        while self._pending_logs:
            after_dispatch, payload, metrics = self._pending_logs[0]
            if not force and after_dispatch > win.fenced_count:
                break
            self._pending_logs.popleft()
            try:
                self.algorithm.log_epoch(stats=payload, metrics=metrics)
                dumped = True
            except Exception as e:
                print(f"[TrainingServer] log error: {e!r}", flush=True)
        if dumped and self._tb is not None:
            try:
                self._tb.poll()
            except Exception as e:
                print(f"[TrainingServer] tensorboard error: {e!r}",
                      flush=True)
        self.timings["device_wait_s"] = win.device_wait_s
        if self._publisher is not None:
            self.timings["publish_s"] = self._publisher.publish_s

    def _pipeline_quiesce(self) -> None:
        """Fence every in-flight update and flush the deferred logs —
        called when the learner is idle or exiting (learner thread only)."""
        win = getattr(self.algorithm, "_inflight", None)
        if win is not None and win.pending:
            win.drain()
        if self._pending_logs:
            self._flush_ready_logs(force=True)

    # -- divergence watchdog + last-known-good rollback (learner thread) --
    def _guard_poll(self) -> bool:
        """Resolve fenced health probes and evaluate the watchdog's
        detectors; a Trip executes the rollback path (or the degraded
        halt). True when a trip fired — callers gating a checkpoint on
        health skip the save then. Learner thread only."""
        g = self.guardrails
        if g is None or g.watchdog is None or self._halted:
            return False
        win = getattr(self.algorithm, "_inflight", None)
        fenced = win.fenced_count if win is not None else 0
        trip = g.watchdog.poll(fenced)
        if trip is None:
            return False
        self._execute_rollback(trip)
        return True

    def _execute_rollback(self, trip) -> None:
        """The watchdog tripped: halt dispatch, restore the newest
        healthy-tagged checkpoint AND its dedup-ledger sidecar, fast-
        forward the version past the poisoned line, force a model-wire
        keyframe so actors resync off the poisoned delta chain, publish
        the restored params, and resume. Bounded: more than
        ``max_rollbacks`` inside ``rollback_window_s`` (or no healthy
        checkpoint to restore) degrades to halt-and-alarm. Learner
        thread only — nothing else dispatches while this runs."""
        from relayrl_tpu import telemetry

        g = self.guardrails
        # 1. Halt dispatch: fence everything in flight, drop the deferred
        # logs (they describe the rolled-back line of history), and let
        # the publisher finish so no poisoned-line publish races the
        # restored one.
        win = getattr(self.algorithm, "_inflight", None)
        if win is not None and win.pending:
            win.drain()
        self._pending_logs.clear()
        if self._publisher is not None:
            self._publisher.drain(timeout=30.0)
        if not g.params["rollback"] or not self._checkpoint_dir:
            self._enter_halt(trip, "rollback disabled")
            return
        now = time.monotonic()
        window = g.params["rollback_window_s"]
        self._rollback_times = [t for t in self._rollback_times
                                if now - t < window]
        if len(self._rollback_times) >= g.params["max_rollbacks"]:
            self._enter_halt(trip, "rollback budget spent")
            return
        self._rollback_times.append(now)
        # 2. Restore the newest healthy step (settle any in-flight async
        # save first so the step listing is complete).
        mgr = getattr(self.algorithm, "_ckpt_mgr", None)
        if mgr is not None:
            try:
                mgr.wait()
            except Exception:
                pass
        try:
            from relayrl_tpu.checkpoint import restore_latest_healthy

            step = restore_latest_healthy(self.algorithm,
                                          self._checkpoint_dir)
        except FileNotFoundError:
            self._enter_halt(trip, "no healthy checkpoint retained")
            return
        except Exception as e:
            self._enter_halt(trip, f"restore failed: {e!r}")
            return
        # 3. The dedup ledger must match the restored params' line of
        # history (PR 6's consistency contract): a newer ledger would
        # dedup (lose) trajectories whose updates just rolled back.
        self._load_ledger_sidecar(step)
        # 4. Fast-forward the version PAST anything the poisoned line
        # published, so actor swap gates and checkpoint step numbering
        # stay monotonic (step numbers are labels; the state is the
        # restored tree).
        new_version = max(self.latest_model_version,
                          int(self.algorithm.version)) + 1
        self.algorithm.force_version(new_version)
        # 5. Host-side ingest state part-filled by the poisoned stream
        # belongs to the rolled-back line.
        self.algorithm.reset_ingest_buffers()
        # 6. Re-arm BEFORE the publish below: its checkpoint due-check
        # re-enters _guard_poll, and a watchdog still holding poisoned-
        # line probes would recurse straight back into rollback. The
        # detector windows describe the dead line anyway, and the
        # re-anchored distance gates put the restored line on its own
        # checkpoint cadence.
        g.watchdog.reset_after_rollback()
        self._ckpt_version = new_version
        self._artifact_version = new_version
        # 7. Forced keyframe + immediate publish: every actor resyncs to
        # the restored params regardless of what deltas it held.
        if self._wire_encoder is not None:
            self._wire_encoder.force_keyframe()
        try:
            self._publish()
        except Exception as e:
            print(f"[TrainingServer] rollback publish error: {e!r}",
                  flush=True)
        self._rollbacks_total += 1
        g._m_rollbacks.inc()
        telemetry.emit("rollback", signal=trip.signal, value=trip.value,
                       threshold=trip.threshold, restored_step=int(step),
                       new_version=int(new_version),
                       attempt=len(self._rollback_times))
        print(f"[TrainingServer] ROLLBACK #{self._rollbacks_total}: "
              f"{trip.signal} tripped → restored healthy step {step}, "
              f"resuming as version {new_version}", flush=True)

    def _enter_halt(self, trip, reason: str) -> None:
        """Degrade to halt-and-alarm: training stops, ingest sheds, the
        process survives for operator forensics (docs/operations.md
        runbook). One-way until an operator restarts the server."""
        from relayrl_tpu import telemetry

        self._halted = True
        g = self.guardrails
        g._m_halted.set(1)
        telemetry.emit("guardrails_halt", signal=trip.signal,
                       value=trip.value, reason=reason,
                       rollbacks=self._rollbacks_total)
        print(f"[TrainingServer] GUARDRAILS HALT ({reason}): "
              f"{trip.signal} tripped and recovery is exhausted — "
              f"training stopped, ingest shedding, process alive for "
              f"inspection", flush=True)

    @property
    def guardrails_halted(self) -> bool:
        return self._halted

    def guardrails_accounting(self) -> dict:
        """Guardrail evidence block for drills/benches/status loops:
        validation + quarantine + watchdog + admission accounting plus
        the server-side rollback/halt ledger. Empty when disabled."""
        g = self.guardrails
        if g is None:
            return {}
        out = g.accounting()
        out["rollbacks_total"] = self._rollbacks_total
        out["halted"] = self._halted
        return out

    def _learner_pending(self) -> int:
        """Dispatched-but-unfenced updates + deferred logs + queued or
        in-progress publishes — the single-host half of the drain()
        contract (the multi-host half is _mh_ready/_mh_busy)."""
        win = getattr(self.algorithm, "_inflight", None)
        n = (win.pending if win is not None else 0) + len(self._pending_logs)
        if self._publisher is not None:
            n += self._publisher.pending
        return n

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every trajectory already in the ingest pipeline
        (raw + decoded queues) has been processed (trained + published):
        dispatched updates fenced, deferred epoch logs dumped, and the
        final (latest-wins) model publish landed. True if drained within
        timeout.

        Note this covers trajectories the server has *received*; bytes still
        in transit in socket buffers are invisible here, so to observe an
        exact update count poll ``stats['updates']`` first, then drain."""
        from relayrl_tpu import telemetry

        t0 = time.monotonic()
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            if (self._ingest.unfinished_tasks == 0
                    and self._decoded.unfinished_tasks == 0
                    # single-host pipeline: dispatched-but-unfenced
                    # updates, deferred logs, pending publishes (the
                    # learner thread fences + flushes on its idle tick)
                    and self._learner_pending() == 0
                    # multi-host: assembled-but-untrained epoch batches and
                    # the broadcast step in flight also count as pending
                    and not self._mh_ready
                    and not self._mh_busy):
                self._flush_drop_event()
                telemetry.emit("drain",
                               wait_s=round(time.monotonic() - t0, 3),
                               updates=self.stats["updates"])
                return True
            time.sleep(0.05)
        return False

    # -- idempotent-ingest ledger persistence (crash-recovery plane) --
    def _ledger_sidecar_path(self, version: int) -> str:
        return os.path.join(self._checkpoint_dir,
                            f"ingest_ledger_{int(version)}.json")

    def _save_ledger_sidecar(self, version: int) -> None:
        """Snapshot the dedup ledger next to the checkpoint at
        ``version`` (atomic write; older sidecars pruned to the
        checkpoint retention depth). Keyed BY VERSION so a resume
        restores exactly the dedup state consistent with the restored
        params — a newer ledger would dedup (lose) trajectories whose
        updates rolled back; an older one would double-train."""
        if self._ingest_ledger is None or not self._checkpoint_dir:
            return
        try:
            self._ingest_ledger.save(self._ledger_sidecar_path(version))
            import glob

            sidecars = sorted(
                glob.glob(os.path.join(self._checkpoint_dir,
                                       "ingest_ledger_*.json")),
                key=lambda p: int(p.rsplit("_", 1)[1].split(".")[0]))
            for stale in sidecars[:-max(2, self._ckpt_keep)]:
                os.remove(stale)
        except (OSError, ValueError) as e:
            print(f"[TrainingServer] ingest-ledger sidecar write failed: "
                  f"{e!r}", flush=True)

    def _load_ledger_sidecar(self, version: int) -> None:
        """Restore the ledger matching the resumed version; a missing
        sidecar (pre-recovery checkpoints) starts empty — replays of
        already-trained trajectories then train again, which the runbook
        documents as the bounded cost of a ledgerless resume."""
        if self._ingest_ledger is None or not self._checkpoint_dir:
            return
        path = self._ledger_sidecar_path(version)
        try:
            from relayrl_tpu.runtime.spool import SequenceLedger

            self._ingest_ledger = SequenceLedger.load(path)
            print(f"[TrainingServer] ingest ledger restored "
                  f"({len(self._ingest_ledger.counts())} agent(s), "
                  f"version {version})", flush=True)
        except FileNotFoundError:
            print(f"[TrainingServer] no ingest-ledger sidecar at version "
                  f"{version}; dedup starts empty (replays of "
                  f"already-trained trajectories will re-train)",
                  flush=True)
        except (OSError, ValueError, KeyError) as e:
            print(f"[TrainingServer] ingest-ledger sidecar unreadable: "
                  f"{e!r}; dedup starts empty", flush=True)

    def ingest_accounting(self) -> dict:
        """Sequence accounting for drills/benches: per-agent
        ``{max_seq, accepted, contiguous}`` + duplicate count. Empty when
        dedup is disabled."""
        if self._ingest_ledger is None:
            return {"agents": {}, "duplicates": 0}
        return {"agents": self._ingest_ledger.counts(),
                "duplicates": self._ingest_ledger.total_duplicates()}

    def _write_model_artifact(self, raw: bytes, version: int) -> None:
        """Periodic on-disk model bytes (ref: server reads the .pt file to
        serve agents, training_zmq.rs:905-919; for us handshakes are
        served from memory and the file is a resume/debug aid). Reuses the
        (lazily) serialized v1 bytes, throttled by
        learner.checkpoint_every_epochs. Distance-gated, not
        modulo-gated: latest-wins publish coalescing makes published
        versions an arbitrary subsequence, so waiting for a version
        divisible by the cadence could starve the file forever (with
        every version published the two rules write identically)."""
        if version - self._artifact_version < self._checkpoint_every:
            return
        if raw is None:
            raw = self._get_model()[1]
        try:
            path = self.algorithm.server_model_path
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as f:
                f.write(raw)
            os.replace(tmp, path)
            self._artifact_version = version
        except OSError:
            pass

    def _publish_params(self, version: int, arch: dict, host_params) -> None:
        """The ONE broadcast path (pipelined, synchronous, and multi-host
        publishes all land here with a host params tree). Wire v2: the
        encoder turns the publish into a keyframe or per-leaf delta frame
        off the learner thread; the full v1 bundle serializes lazily only
        when a handshake, artifact write, or native set_model needs it.
        Wire v1: the legacy full-bundle bytes ship on every publish."""
        from relayrl_tpu import telemetry

        from relayrl_tpu.guardrails.validate import params_tree_finite

        g = self.guardrails
        if g is not None and not params_tree_finite(host_params):
            # The publish gate: non-finite params NEVER reach the wire,
            # the handshake cache, or the artifact file — the fleet keeps
            # serving the last good model while the watchdog's rollback
            # replaces the poisoned line (trip_external surfaces on the
            # learner thread's next poll).
            g._m_publish_blocked.inc()
            if g.watchdog is not None:
                g.watchdog.trip_external("publish_nonfinite",
                                         float("nan"), 0.0)
            telemetry.emit("publish_blocked", version=int(version))
            print(f"[TrainingServer] publish BLOCKED: version {version} "
                  f"params are non-finite", flush=True)
            return
        enc = self._wire_encoder
        with self._bundle_lock:
            self._bundle_host = (int(version), dict(arch), host_params)
        tracer = self._get_tracer()
        traced = tracer.enabled and tracer.sample_version(version)
        try:
            if enc is not None:
                t_enc0 = time.monotonic_ns() if traced else 0
                frame, info = enc.encode(version, arch, host_params)
                if traced:
                    from relayrl_tpu.telemetry.trace import model_trace_id

                    t_enc1 = time.monotonic_ns()
                    tracer.span("model", model_trace_id(version), "encode",
                                t_enc0, t_enc1, version=int(version),
                                frame_kind=info["kind"],
                                bytes=info["frame_bytes"])
                if getattr(self.transport, "needs_handshake_bytes", False):
                    # The native core answers handshakes from pushed
                    # bytes; a v2 publish rides with the v1 bundle for
                    # set_model.
                    self._traced_wire_publish(
                        traced, version, frame,
                        handshake_bytes=self._get_model()[1])
                else:
                    self._traced_wire_publish(traced, version, frame)
                telemetry.emit("model_publish", version=version,
                               bytes=info["frame_bytes"], kind=info["kind"],
                               raw_bytes=info["raw_bytes"])
            else:
                from relayrl_tpu.types.model_bundle import ModelBundle

                raw = ModelBundle(version=int(version), arch=dict(arch),
                                  params=host_params).to_bytes()
                with self._bundle_lock:
                    self._bundle_bytes = raw
                    self._bundle_version = int(version)
                self._traced_wire_publish(traced, version, raw)
                telemetry.emit("model_publish", version=version,
                               bytes=len(raw))
        finally:
            # Distance-gated; a transient publish error must not starve
            # the on-disk artifact (the multi-host path always wrote it).
            self._write_model_artifact(None, version)
            # Colocated serving feed: the inference plane sees every
            # published version straight from the host tree — no wire
            # hop, no subscription, same finite-publish gate as the
            # fleet (the non-finite early-return above never reaches
            # here with poisoned params).
            if self.inference is not None:
                try:
                    self.inference.install_params(version, arch,
                                                  host_params)
                except Exception as e:
                    print(f"[TrainingServer] serving install error: "
                          f"{e!r}", flush=True)

    def _traced_wire_publish(self, traced: bool, version: int,
                             frame: bytes, **kwargs) -> None:
        """The ``publish`` hop span (socket broadcast wall time on the
        publisher thread) around the fault-site-wrapped broadcast."""
        if not traced:
            self._faulted_publish(version, frame, **kwargs)
            return
        from relayrl_tpu.telemetry.trace import model_trace_id

        tracer = self._get_tracer()
        t0 = time.monotonic_ns()
        try:
            self._faulted_publish(version, frame, **kwargs)
        finally:
            tracer.span("model", model_trace_id(version), "publish",
                        t0, time.monotonic_ns(), version=int(version),
                        backend=self.server_type)

    def _faulted_publish(self, version: int, frame: bytes,
                         **kwargs) -> None:
        """Model broadcast through the ``server.publish`` fault site:
        drop loses the frame for the whole fleet (keyframe cadence or
        resync recovers), corrupt lands in every actor's CRC check,
        delay stalls the publisher thread. No plan → straight through."""
        if self._fault_publish is None:
            self.transport.publish_model(version, frame, **kwargs)
            return
        for delay_s, part in self._fault_publish.inject(frame):
            if delay_s > 0:
                time.sleep(delay_s)
            self.transport.publish_model(version, part, **kwargs)

    def _publish(self) -> None:
        """Synchronous publish on the learner thread — the multi-host
        loop's path and the ``async_publish: false`` escape hatch (the
        pipelined path hands :meth:`_publish_snapshot` to the publisher
        thread instead)."""
        import jax

        bundle = self.algorithm.bundle()
        self._publish_params(bundle.version, bundle.arch,
                             jax.device_get(bundle.params))
        self._maybe_periodic_checkpoint(bundle.version)

    def _maybe_periodic_checkpoint(self, version: int) -> None:
        """Distance-gated full-state checkpoint (params + optimizer +
        RNG + epoch; async orbax save). Distance, not modulo: off-policy
        versions advance by the whole update-debt between checks, so a
        ``% N == 0`` gate can skip cadences indefinitely (the same
        starvation `_write_model_artifact` guards against). Quiesces the
        pipeline first — the save fences the params anyway, and flushing
        the deferred logs keeps the checkpointed epoch counter in step
        with the checkpointed params (a resume must not repeat Epoch
        rows already logged before the save); a no-op when nothing is
        pending (the synchronous and multi-host paths)."""
        if (not self._checkpoint_dir
                or version - self._ckpt_version < self._checkpoint_every):
            return
        self._pipeline_quiesce()
        # Post-quiesce the in-flight window is empty, so every pending
        # health probe resolves for free here — a trip rolls back (the
        # save is skipped: the state it would capture is the poisoned
        # line) and a clean poll makes the healthy-at-save tag honest.
        if self._guard_poll():
            return
        self._periodic_checkpoint()
        # Advance even on a (caught) failed save — retrying every epoch
        # would hammer a broken checkpoint dir, and multi-host ranks must
        # stay in lockstep on the due-check regardless of local errors.
        self._ckpt_version = version

    def _publish_snapshot(self, snapshot) -> None:
        """Publisher-thread body: the blocking D2H gather, wire encode
        (delta/keyframe under v2, full serialize under v1), socket
        publish, and artifact write all happen here — a slow subscriber
        or disk never stalls the learner thread, and back-to-back epochs
        coalesce latest-wins upstream (runtime/pipeline.ModelPublisher).
        Exceptions are counted and logged by the publisher loop."""
        self._publish_params(snapshot.version, snapshot.arch,
                             snapshot.host_params())

    def _health_tag(self) -> dict:
        """The healthy-at-save tag every checkpoint carries (JSON
        extras): True iff the watchdog's most recently resolved probes
        were clean and guardrails are not halted. The periodic path
        quiesces + polls BEFORE saving, so a True tag means every update
        baked into the step had its probes resolved clean — the
        last-known-good ring's membership test (restore_latest_healthy).
        Guardrails/watchdog off ⇒ True: the ring stays usable as a
        plain resume source."""
        g = self.guardrails
        healthy = not self._halted and (
            g is None or g.watchdog is None or g.watchdog.healthy())
        return {"healthy": healthy}

    def _periodic_checkpoint(self) -> None:
        """One periodic save, with the replay-buffer (aux) snapshot
        throttled to every ``checkpoint_aux_every``-th save — the ring
        copy is synchronous on this (learner) thread, so large buffers
        pay it on a cadence instead of every save."""
        try:
            from relayrl_tpu.checkpoint import checkpoint_algorithm

            include_aux = self._ckpt_saves % self._aux_every == 0
            checkpoint_algorithm(self.algorithm, self._checkpoint_dir,
                                 include_aux=include_aux,
                                 max_to_keep=self._ckpt_keep,
                                 extra_meta=self._health_tag())
            from relayrl_tpu import telemetry

            telemetry.emit("checkpoint", version=self.algorithm.version,
                           include_aux=include_aux,
                           dir=str(self._checkpoint_dir))
            # The dedup ledger rides every checkpoint as a per-version
            # sidecar, so a crash-resume restores dedup state consistent
            # with the restored params (see _save_ledger_sidecar).
            self._save_ledger_sidecar(self.algorithm.version)
            # Count after submit so a SYNCHRONOUS failure (same-step
            # collision, bad tree) doesn't consume the aux slot. Saves
            # are async, so a deferred write failure surfaces at the
            # NEXT call and that slot is still lost — best effort only.
            self._ckpt_saves += 1
            if self._ckpt_consecutive_failures:
                self._ckpt_consecutive_failures = 0
                self._m_ckpt_consecutive.set(0)
        except Exception as e:
            # A step collision happens after a signal-path final save
            # bumped past this version (see manager.save overwrite) —
            # benign, the state is already on disk at the bumped step.
            if type(e).__name__ == "StepAlreadyExistsError":
                print(f"[TrainingServer] checkpoint step exists, skipped "
                      f"(post-resume overlap with a bumped final save)",
                      flush=True)
            else:
                # Satellite (ISSUE 6): a failed save used to leave NO
                # trace beyond this line while _ckpt_version advanced
                # past it — operators could lose a whole resume window
                # silently. Counter + consecutive-failure gauge + journal
                # event make it alarmable.
                self._ckpt_consecutive_failures += 1
                self._m_ckpt_failures.inc()
                self._m_ckpt_consecutive.set(
                    self._ckpt_consecutive_failures)
                from relayrl_tpu import telemetry

                telemetry.emit(
                    "checkpoint_failed", version=self.algorithm.version,
                    error=repr(e),
                    consecutive=self._ckpt_consecutive_failures,
                    dir=str(self._checkpoint_dir))
                print(f"[TrainingServer] checkpoint failed "
                      f"(#{self._ckpt_consecutive_failures} consecutive): "
                      f"{e!r}", flush=True)

    # -- fleet telemetry tick (ISSUE 15) --
    def _fleet_loop(self) -> None:
        while not self._fleet_stop.wait(self._fleet_interval_s):
            self._fleet_tick()

    def _fleet_tick(self) -> None:
        """One aggregation interval at the root: fold this server's own
        registry into the table, evict stale procs, evaluate the SLO
        rules over the merged snapshot. Public-ish so drills/tests can
        tick deterministically; isolated — the pane must never take
        down the plane it watches."""
        from relayrl_tpu import telemetry

        try:
            self._fleet.ingest_registry(self._telemetry, self._fleet_proc,
                                        "server")
            for proc in self._fleet.sweep():
                telemetry.emit("fleet_evict", proc=proc)
            if self._alerts is not None:
                # Membership rides along so increase rules rebaseline
                # across evict/rejoin churn instead of firing on it.
                self._alerts.evaluate(
                    self._fleet.merged(),
                    membership=[p["proc"] for p in self._fleet.procs()])
        except Exception as e:
            print(f"[TrainingServer] fleet tick failed: {e!r}", flush=True)

    # -- lifecycle (ref: training_zmq.rs:322-465 / o3_training_server.rs:153-272) --
    def enable_server(self) -> None:
        if self.active:
            return
        self._stop.clear()
        multi_host = self.distributed_info["multi_host"]
        if self.transport is not None:
            self.transport.start()
            # N decode workers (learner.ingest_staging_threads): once the
            # learner thread is dispatch-only, a single decode thread is
            # the next ingest bottleneck; the native decoder drops the
            # GIL, so extra workers scale on real cores.
            self._staging_threads = [
                threading.Thread(target=self._staging_loop,
                                 name=f"ingest-staging-{i}", daemon=True)
                for i in range(self._staging_count)]
            for t in self._staging_threads:
                t.start()
        if self.inference is not None:
            self.inference.start()
        # The publisher thread exists wherever there is a transport to
        # feed — including the multi-host coordinator (non-coordinators
        # own no actor plane, so they dispatch the publish gather and
        # drop the snapshot). async_publish=false is the sync escape
        # hatch on both loops.
        if (self.transport is not None
                and self._async_publish and self._publisher is None):
            from relayrl_tpu.runtime.pipeline import ModelPublisher

            self._publisher = ModelPublisher(self._publish_snapshot)
        self._mh_ready = []
        self._mh_busy = False
        if multi_host:
            # The multi-host update is collective — a solo pre-compile
            # would hang the other ranks; wait_warmup() must not block.
            self._warmup_done.set()
        self._learner_thread = threading.Thread(
            target=(self._learner_loop_multihost if multi_host
                    else self._learner_loop),
            name="learner", daemon=True)
        self._learner_thread.start()
        if self._fleet is not None:
            self._fleet_stop.clear()
            self._fleet_thread = threading.Thread(
                target=self._fleet_loop, name="fleet-tick", daemon=True)
            self._fleet_thread.start()
        self.active = True

    def wait_warmup(self, timeout: float | None = None) -> bool:
        """Block until the learner thread has pre-compiled its update
        shapes (no-op/immediate on multi-host and after the first enable).
        One-process deployments that run the actor loop on the main thread
        (notebooks) call this right after construction: the main thread
        sleeps on the event, so the compile gets the core to itself.
        Returns False immediately when the server isn't running
        (``start=False`` and no enable yet): no learner thread exists to
        ever set the event, so blocking would hang forever."""
        if not self.active and not self._warmup_done.is_set():
            return False
        return self._warmup_done.wait(timeout)

    def disable_server(self, join_timeout: float | None = None) -> None:
        """``join_timeout`` overrides the per-thread join bounds — the
        signal path passes a short grace on multi-host so a peer stuck
        mid-collective can't hold this rank past its supervisor's
        termination window."""
        if not self.active:
            return
        self._stop.set()
        if self._fleet_thread is not None:
            self._fleet_stop.set()
            self._fleet_thread.join(timeout=5)
            self._fleet_thread = None
            # One closing tick so the table holds this life's final
            # registry state (and alerts get a last look) before the
            # ingest plane stops feeding it.
            self._fleet_tick()
        # Serving plane first: parked thin-client requests answer with a
        # retryable nack instead of hanging out their timeouts against a
        # closing socket (clients ride their breaker until a restart).
        if self.inference is not None:
            self.inference.stop()
        # Join the learner BEFORE stopping the transport: a trajectory being
        # processed right now may still publish, which needs a live socket.
        # (Multi-host: the coordinator's learner thread broadcasts STOP on
        # its way out, releasing every non-coordinator's loop — shut the
        # fleet down together or coordinator-last.)
        # join_timeout is ONE deadline across both joins (the signal path
        # sizes it to the supervisor grace window — two full grants would
        # double it), not a per-thread grant.
        deadline = (None if join_timeout is None
                    else time.monotonic() + join_timeout)
        for t in self._staging_threads:
            t.join(timeout=30 if deadline is None
                   else max(0.0, deadline - time.monotonic()))
        self._staging_threads = []
        if self._learner_thread is not None:
            # Multi-host: the thread may be mid-collective (a step can
            # include a fresh XLA compile) — give it long enough to reach
            # the STOP broadcast; killing the transport under a live
            # publish would be worse than waiting.
            default = 600 if self.distributed_info["multi_host"] else 30
            self._learner_thread.join(
                timeout=default if deadline is None
                else max(0.0, deadline - time.monotonic()))
            self._learner_thread = None
        if self._publisher is not None:
            # After the learner join (no more submits), before the
            # transport stops (the final publish needs a live socket).
            self._publisher.stop(
                timeout=30 if deadline is None
                else max(0.0, deadline - time.monotonic()))
            self._publisher = None
        if self.transport is not None:
            self.transport.stop()
        self._flush_drop_event()
        # Drain any in-flight async orbax save — the most recent checkpoint
        # is exactly the one a subsequent resume needs.
        mgr = getattr(self.algorithm, "_ckpt_mgr", None)
        if mgr is not None and join_timeout is None:
            # Drain in-flight async saves — but NOT on the bounded
            # (signal/emergency) path: a multi-host collective save waits
            # on a cross-process commit barrier un-signaled peers never
            # complete, and an unbounded wait here would defeat the
            # bounded joins above (the process is about to die by signal;
            # single-host final saves use wait=True themselves).
            try:
                mgr.wait()
            except Exception as e:
                print(f"[TrainingServer] checkpoint drain failed: {e!r}",
                      flush=True)
        self.active = False

    def restart_server(self, **addr_overrides) -> None:
        from relayrl_tpu.parallel.distributed import is_coordinator

        self.disable_server()
        if addr_overrides and is_coordinator():
            # Non-coordinators never own a transport (the actor plane
            # binds on the coordinator only) — a symmetric restart call
            # across the fleet must not create one.
            self._addr_overrides.update(addr_overrides)
            self.transport = make_server_transport(
                self.server_type, self.config, **self._addr_overrides)
            self.transport.on_trajectory = self._on_trajectory
            self.transport.on_trajectory_decoded = self._on_trajectory_decoded
            self.transport.get_model = self._get_model
            self.transport.on_register = self._on_register
            self.transport.on_unregister = self._on_unregister
            self.transport.on_resync = self._on_resync_request
            if self.guardrails is not None:
                self.transport.check_ingest = self._check_ingest
            if self.inference is not None:
                self._wire_serving_plane(self._addr_overrides)
        self.enable_server()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disable_server()


def _coerce(v: str):
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v


def _load_plugin_algorithms(algorithm_dir: str) -> None:
    """Import ``<dir>/<ALGO>/<ALGO>.py`` modules so they can
    ``register_algorithm`` themselves (the reference's dynamic
    sys.path+importlib scheme, python_algorithm_reply.py:23-52)."""
    import importlib.util
    import os
    import sys

    if algorithm_dir not in sys.path:
        sys.path.insert(0, algorithm_dir)
    for entry in sorted(os.listdir(algorithm_dir)):
        mod_file = os.path.join(algorithm_dir, entry, f"{entry}.py")
        if os.path.isfile(mod_file):
            name = f"relayrl_plugin_{entry}"
            if name in sys.modules:
                continue
            spec = importlib.util.spec_from_file_location(name, mod_file)
            module = importlib.util.module_from_spec(spec)
            sys.modules[name] = module
            spec.loader.exec_module(module)


__all__ = ["TrainingServer", "registered_algorithms"]
