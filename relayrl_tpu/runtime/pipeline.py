"""Learner hot-path pipelining: async dispatch window + off-thread publish.

The single-host learner thread used to run a fully synchronous chain per
epoch: assemble → H2D → update → host-sync on metrics (``float(v)``) →
D2H params gather → serialize → socket publish → disk write — all before
the next decoded trajectory was dequeued. Podracer's Sebulba split
(arxiv 2104.06272) gets TPU throughput from exactly the overlaps that
chain forbids: host data work and model publishing pipelined against
device compute. This module owns the three host-side pieces of that
split; the server and the algorithm families wire them together:

* :class:`LazyMetrics` — update metrics stay device arrays until
  ``log_epoch``/``stats`` actually read them, so ``train_on_batch``
  returns at dispatch instead of fencing every epoch.
* :class:`InflightWindow` — bounds how many dispatched-but-unfenced
  updates may be outstanding (donation-safe: the train state threads
  through dispatches in program order, so XLA sequences them; the bound
  only stops the host from running unboundedly ahead and anchors the
  staging-buffer reuse proof in ``data/batching.py``).
* :class:`ModelPublisher` — a dedicated thread fed latest-wins: a slow
  socket or artifact write never stalls training, and back-to-back
  epochs coalesce into one publish of the newest params.
* :class:`PublishSnapshot` — the cheap handoff between them: a
  device-to-device params copy taken on the learner thread (dispatched
  async, never a host sync) that the publisher gathers and serializes
  off-thread. The copy is what makes the handoff donation-safe: the
  live state buffers may be consumed by the very next update while the
  publisher is still reading the snapshot.

The multi-host broadcast loop rides the same three pieces: the sharded
update is just as much a non-blocking dispatch as the single-host one
(its collectives live inside the XLA program), so it enters the same
:class:`InflightWindow`; the publish handoff swaps the ``jnp.copy`` for
the algorithm's jitted re-shard-to-replicated gather (a collective every
rank dispatches at the same point — coordinator-side, the publisher
thread then reads one addressable shard of the replicated result); and
``drain()`` counts the window + pending publishes on top of the
``_mh_ready``/``_mh_busy`` broadcast-step flags.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator, Mapping


class LazyMetrics(Mapping):
    """Mapping view over a dict of device scalars that resolves to host
    floats only when read. ``train_on_batch`` returns one of these at
    dispatch time; the fence happens where the value is consumed
    (``log_epoch``'s ``dump_tabular``, a test's ``_last_metrics[k]``),
    not on the learner hot path. Resolution is cached: the first read
    fences, later reads are free."""

    def __init__(self, device_metrics: Mapping[str, Any]):
        self._device = dict(device_metrics)
        self._host: dict[str, float] | None = None

    @property
    def device(self) -> dict[str, Any]:
        """The raw device arrays — what :class:`InflightWindow` fences."""
        return self._device

    def resolve(self) -> dict[str, float]:
        if self._host is None:
            self._host = {k: float(v) for k, v in self._device.items()}
        return self._host

    def __getitem__(self, key: str) -> float:
        return self.resolve()[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._device)

    def __len__(self) -> int:
        return len(self._device)

    def __repr__(self) -> str:
        state = "resolved" if self._host is not None else "in-flight"
        return f"LazyMetrics({sorted(self._device)}, {state})"


class InflightWindow:
    """Bounded window of dispatched-but-unfenced updates.

    Every dispatch pushes the update's output leaves (its metrics — made
    by the same XLA program as the new state, so "metrics ready" ⟺
    "update done"); pushing past ``max_in_flight`` fences the oldest
    first. ``max_in_flight=0`` degenerates to the old synchronous
    behavior (every dispatch fenced immediately) — the equivalence-test
    escape hatch and the operator's kill switch.

    Owned by the learner thread alone: no locks (deliberate — a fence
    under a lock is exactly the CONC01 stall jaxlint exists to catch).
    ``device_wait_s`` accumulates the real blocked time so the server's
    ``timings`` can report the fence separately from dispatch work.
    """

    def __init__(self, max_in_flight: int = 2):
        from relayrl_tpu import telemetry

        self.max_in_flight = max(0, int(max_in_flight))
        self._entries: deque[Any] = deque()
        self.dispatch_count = 0   # total updates ever pushed
        self.fenced_count = 0     # total updates known complete
        self.device_wait_s = 0.0
        reg = telemetry.get_registry()
        self._m_device_wait = reg.histogram(
            "relayrl_learner_device_wait_seconds",
            "learner thread blocked fencing an in-flight update")
        self._m_pending = reg.gauge(
            "relayrl_learner_inflight_pending",
            "dispatched-but-unfenced updates in the async window")

    @property
    def pending(self) -> int:
        """Dispatched-but-unfenced updates (the drain() contract)."""
        return len(self._entries)

    def push(self, fences: Any, version: int | None = None) -> None:
        """Record one dispatched update; blocks only when the window is
        already full (fencing the oldest). ``version`` (the dispatching
        algorithm's host version mirror) labels the eventual fence span
        on the distributed-tracing plane — optional, never read
        otherwise."""
        self._entries.append((fences, version))
        self.dispatch_count += 1
        while len(self._entries) > self.max_in_flight:
            self._fence_oldest()
        self._m_pending.set(len(self._entries))

    def drain(self) -> None:
        """Fence every outstanding update (learner idle / shutdown /
        pre-checkpoint)."""
        while self._entries:
            self._fence_oldest()

    def _fence_oldest(self) -> None:
        import jax

        fences, version = self._entries.popleft()
        t0 = time.monotonic()
        t0_ns = 0
        if version is not None:
            from relayrl_tpu.telemetry import trace as trace_mod

            tracer = trace_mod.get_tracer()
            if tracer.enabled and tracer.sample_version(version):
                t0_ns = time.monotonic_ns()
        jax.block_until_ready(fences)
        dt = time.monotonic() - t0
        if t0_ns:
            from relayrl_tpu.telemetry import trace as trace_mod

            trace_mod.get_tracer().span(
                "model", trace_mod.model_trace_id(version), "fence",
                t0_ns, time.monotonic_ns(), version=int(version))
        self.device_wait_s += dt
        self.fenced_count += 1
        self._m_device_wait.observe(dt)
        self._m_pending.set(len(self._entries))


@dataclasses.dataclass
class PublishSnapshot:
    """Learner-thread handoff to the publisher: ``params`` are
    device-to-device copies (async dispatch, no host sync) so the next
    update's donation cannot invalidate them; ``version`` is the
    host-side dispatch mirror (reading ``state.step`` would fence)."""

    version: int
    arch: dict
    params: Any

    def host_params(self):
        """The blocking D2H gather — runs on the publisher thread, never
        the learner thread. The wire-v2 publish path consumes the host
        tree directly (the encoder keeps it as the next delta's base);
        :meth:`to_bundle` wraps it for the v1 full-bundle path.

        Multi-host snapshots carry the replicated output of the publish
        gather, which is not fully addressable — ``device_get`` refuses
        those, but every process holds a complete local copy, so one
        addressable shard IS the global value."""
        import jax
        import numpy as np

        def read(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                return np.asarray(x.addressable_data(0))
            return jax.device_get(x)

        return jax.tree_util.tree_map(read, self.params)

    def to_bundle(self):
        from relayrl_tpu.types.model_bundle import ModelBundle

        return ModelBundle(version=self.version, arch=self.arch,
                           params=self.host_params())


class ModelPublisher:
    """Dedicated publish thread fed latest-wins.

    ``submit`` replaces any not-yet-started snapshot (the dropped one
    counts as ``coalesced`` — back-to-back epochs fold into one publish
    of the newest params); the publish callable runs outside the lock so
    a slow socket/disk never blocks the submitting learner thread.
    ``pending`` counts the queued slot plus an in-progress publish, which
    is what extends the server ``drain()`` contract to "the final publish
    landed"."""

    def __init__(self, publish_fn: Callable[[PublishSnapshot], None],
                 name: str = "model-publisher"):
        from relayrl_tpu import telemetry

        self._publish_fn = publish_fn
        self._cond = threading.Condition()
        self._slot: PublishSnapshot | None = None
        self._busy = False
        self._stop = False
        self.published = 0
        self.coalesced = 0
        self.errors = 0
        self.publish_s = 0.0
        reg = telemetry.get_registry()
        self._m_published = reg.counter(
            "relayrl_learner_publishes_total",
            "model publishes that landed (gather+serialize+send)")
        self._m_coalesced = reg.counter(
            "relayrl_learner_publish_coalesced_total",
            "queued publishes replaced latest-wins before starting")
        self._m_errors = reg.counter(
            "relayrl_learner_publish_errors_total",
            "publish attempts that raised (transient socket/fs)")
        self._m_publish = reg.histogram(
            "relayrl_learner_publish_seconds",
            "one publish on the publisher thread: D2H gather + serialize "
            "+ socket + artifact write")
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    @property
    def pending(self) -> int:
        with self._cond:
            return int(self._slot is not None) + int(self._busy)

    def submit(self, snapshot: PublishSnapshot) -> None:
        with self._cond:
            if self._stop:
                return
            if self._slot is not None:
                self.coalesced += 1
                self._m_coalesced.inc()
            self._slot = snapshot
            self._cond.notify()

    def drain(self, timeout: float | None = None) -> bool:
        """Block until the queued + in-progress publishes have landed."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._slot is not None or self._busy:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def stop(self, timeout: float | None = 30.0) -> None:
        """Finish the pending publish (if any), then join the thread."""
        with self._cond:
            self._stop = True
            self._cond.notify()
        self._thread.join(timeout)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while self._slot is None and not self._stop:
                    self._cond.wait()
                if self._slot is None and self._stop:
                    return
                snapshot, self._slot = self._slot, None
                self._busy = True
            t0 = time.monotonic()
            try:
                self._publish_fn(snapshot)
                self.published += 1
                self._m_published.inc()
            except Exception as e:  # a transient socket/fs error must not
                self.errors += 1    # kill the publish plane
                self._m_errors.inc()
                print(f"[ModelPublisher] publish error: {e!r}", flush=True)
            finally:
                dt = time.monotonic() - t0
                self.publish_s += dt
                self._m_publish.observe(dt)
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()


__all__ = ["InflightWindow", "LazyMetrics", "ModelPublisher",
           "PublishSnapshot"]
