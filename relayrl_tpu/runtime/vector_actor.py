"""Vectorized actor host: N logical agents, one batched jitted policy step.

The round-5 soak shows every transport collapsing going 32 → 64 actor
*processes* on this host (zmq 734 → 1.7 steps/s,
benches/results/soak_scaling_zmq.json) — process oversubscription, not
transport cost. The fix that transfers from large-scale RL practice is
actor-side batching: Podracer's Anakin steps many environments against a
single jitted policy call (arxiv 2104.06272), and TorchBeast/IMPALA batch
actor inference so env count decouples from process count (arxiv
1910.03552). :class:`VectorActorHost` is that architecture for this
framework: one process steps ``num_envs`` environment lanes through ONE
vmapped, jitted policy dispatch (per-lane PRNG keys split from one seed
key, params broadcast) and presents each lane to the training server as
its own *logical* agent — N trajectory streams with distinct agent ids
multiplexed over one transport connection (see the transport ``base.py``
contract), one shared model-receipt subscription, and a single
:meth:`maybe_swap` that atomically installs new params for every lane (a
batched step reads one params pytree, so no lane can ever act on a mixed
version).

Numerics: the batched step is ``vmap`` of exactly the composition
PolicyActor jits for one agent (``_fuse_rng(policy.step)``), so a
batch-of-1 host is bit-identical to a plain PolicyActor for the same key
(asserted by tests/test_vector_actor.py). Sequence policies run the
vmapped padded-window path with stacked per-lane windows; the KV-cache
incremental path is single-lane-only and intentionally not used here (a
per-lane cache pytree would be donated/rebuilt per swap per lane — the
window recompute is the simpler batched serving story).
"""

from __future__ import annotations

import threading

import jax
import numpy as np

from relayrl_tpu.models import build_policy, validate_policy
from relayrl_tpu.runtime.policy_actor import (
    apply_bundle_swap,
    apply_wire_swap,
    make_batched_step,
    make_batched_window_step,
    normalize_obs,
    push_window,
    resolve_actor_context,
)
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle, exploration_kwargs
from relayrl_tpu.types.trajectory import Trajectory


class VectorActorHost:
    """N env lanes → one batched policy dispatch → N trajectory streams.

    ``on_send(lane, payload)`` receives each lane's serialized episodes;
    the networked facade (:class:`relayrl_tpu.runtime.agent.VectorAgent`)
    stamps lane ``lane``'s payloads with that lane's logical agent id.
    ``rng_keys`` (stacked ``[N, 2]``) overrides the default per-lane key
    derivation (``jax.random.split(PRNGKey(seed), N)``) — parity tests
    hand lane 0 the exact key a single PolicyActor would carry.
    """

    def __init__(
        self,
        bundle: ModelBundle,
        num_envs: int,
        max_traj_length: int = 1000,
        on_send=None,
        seed: int = 0,
        validate: bool = True,
        rng_keys=None,
    ):
        if num_envs < 1:
            raise ValueError(f"num_envs must be >= 1, got {num_envs}")
        self._lock = threading.Lock()
        self.num_envs = int(num_envs)
        self.arch = dict(bundle.arch)
        self.policy = build_policy(self.arch)
        if validate:
            validate_policy(self.policy, bundle.params)
        self.params = bundle.params
        self.version = bundle.version
        self._batched_fn = make_batched_step(self.policy)
        self._windows = None
        self._window_lens = None
        self._batched_window_fn = None
        if self.policy.step_window is not None:
            ctx = resolve_actor_context(self.arch)
            self._windows = np.zeros(
                (self.num_envs, ctx, int(self.arch["obs_dim"])), np.float32)
            self._window_lens = np.zeros(self.num_envs, np.int32)
            self._batched_window_fn = make_batched_window_step(self.policy)
        self._explore_kwargs = exploration_kwargs(self.arch)
        # Wire-v2 decode state, lazily created on the first v2 frame —
        # ONE decoder for all lanes (the whole point: one subscription,
        # one delta apply, one device_put, N lanes served).
        self._wire_decoder = None
        if rng_keys is not None:
            keys = np.asarray(rng_keys)
            if keys.shape[0] != self.num_envs:
                raise ValueError(
                    f"rng_keys has {keys.shape[0]} rows for "
                    f"{self.num_envs} lanes")
            self._keys = jax.numpy.asarray(keys)
        else:
            self._keys = jax.random.split(
                jax.random.PRNGKey(seed), self.num_envs)
        self.trajectories = [
            Trajectory(
                max_length=max_traj_length,
                on_send=(None if on_send is None
                         else (lambda payload, _lane=lane:
                               on_send(_lane, payload))))
            for lane in range(self.num_envs)
        ]
        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_steps = reg.counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")
        self._m_dispatches = reg.counter(
            "relayrl_actor_batched_dispatches_total",
            "batched policy dispatches (each serves num_envs lanes)")
        reg.gauge("relayrl_actor_lanes",
                  "env lanes per batched dispatch on this host").set(
                      self.num_envs)

    # -- batched action API --
    def request_for_actions(self, obs, masks=None,
                            rewards=None) -> list[ActionRecord]:
        """One batched policy dispatch for all lanes; appends one
        ActionRecord per lane to that lane's trajectory.

        ``obs`` is stacked ``[N, ...]``; ``rewards`` (length N, or None)
        carries each lane's env reward earned since its previous request
        and is attached to that lane's PREVIOUS record (same
        credit-assignment semantics as ``PolicyActor.request_for_action``
        — ``ActionRecord.rew`` always means "reward earned BY this
        action"). ``masks`` is None or stacked ``[N, act_dim]``.
        """
        obs = np.asarray(obs)
        if obs.shape[0] != self.num_envs:
            raise ValueError(
                f"obs batch {obs.shape[0]} != num_envs {self.num_envs}")
        # Byte frames stay bytes on the wire, everything else float32 —
        # the shared rule (normalize_obs), including the defensive copy
        # of possibly-reused frame buffers.
        obs = normalize_obs(obs)
        masks_arr = (None if masks is None
                     else np.asarray(masks, dtype=np.float32))
        with self._lock:
            if rewards is not None:
                for lane, r in enumerate(rewards):
                    if r and self.trajectories[lane].get_actions():
                        self.trajectories[lane].get_actions()[-1] \
                            .update_reward(float(r))
            # ONE params read under the lock for the whole batch: every
            # lane acts on the same model version by construction
            # (maybe_swap's atomicity across lanes).
            if self._batched_window_fn is not None:
                self._push_windows(obs)
                # step_window takes the per-lane count of REAL rows (it
                # reads out at t-1 itself) — same convention as
                # PolicyActor passing _window_len, asserted bit-identical
                # by the window parity test.
                acts, aux, self._keys = self._batched_window_fn(
                    self.params, self._keys, self._windows,
                    self._window_lens, masks_arr)
            else:
                acts, aux, self._keys = self._batched_fn(
                    self.params, self._keys, obs, masks_arr,
                    self._explore_kwargs)
            acts_np = np.asarray(acts)
            aux_np = {k: np.asarray(v) for k, v in aux.items()}
            records = []
            for lane in range(self.num_envs):
                record = ActionRecord(
                    obs=obs[lane],
                    act=acts_np[lane],
                    mask=None if masks_arr is None else masks_arr[lane],
                    rew=0.0,  # filled by the lane's NEXT request / terminal
                    # np.asarray: indexing a stacked [N] aux column yields
                    # a numpy SCALAR, which the wire codec would encode as
                    # a float64 — the 0-d ndarray keeps dtype (and bytes)
                    # identical to the single-actor path.
                    data={k: np.asarray(v[lane])
                          for k, v in aux_np.items()},
                    done=False,
                )
                self.trajectories[lane].add_action(record, send_if_done=True)
                records.append(record)
        self._m_steps.inc(self.num_envs)
        self._m_dispatches.inc()
        return records

    def flag_last_action(self, lane: int, reward: float = 0.0,
                         truncated: bool = False, final_obs=None,
                         terminated: bool | None = None,
                         final_mask=None) -> None:
        """Terminal marker for ONE lane (lanes end episodes independently
        under autoreset): appends a done action carrying the final reward,
        which ships that lane's trajectory. Semantics identical to
        ``PolicyActor.flag_last_action`` including terminated-beats-
        truncated precedence and the bootstrap ``final_obs``."""
        if terminated:
            truncated = False
        with self._lock:
            if self._windows is not None:
                # Episode boundary for this lane only: its next episode
                # must not attend this one's observations.
                self._windows[lane, :, :] = 0.0
                self._window_lens[lane] = 0
            record = ActionRecord(
                obs=(None if final_obs is None
                     else np.asarray(final_obs, np.float32)),
                mask=(None if final_mask is None
                      else np.asarray(final_mask, np.float32)),
                rew=float(reward), done=True, truncated=bool(truncated))
            self.trajectories[lane].add_action(record, send_if_done=True)

    # -- model hot-swap (one gate, all lanes) --
    def maybe_swap(self, bundle: ModelBundle) -> bool:
        """Install a newer model for EVERY lane atomically: the params
        swap (shared gate with PolicyActor, ``apply_bundle_swap``)
        happens under the same lock the batched step holds, and the step
        reads params exactly once — there is no interleaving in which
        some lanes act on the old version and some on the new within one
        dispatch."""
        return apply_bundle_swap(self, bundle)

    def swap_from_bytes(self, buf: bytes) -> bool:
        return self.maybe_swap(
            ModelBundle.from_bytes(buf, params_template=ModelBundle.RAW_TREE))

    def swap_from_wire(self, version: int, blob: bytes):
        """Wire-v2-aware swap shared with PolicyActor (same attribute
        contract); one frame updates every lane atomically."""
        return apply_wire_swap(self, version, blob)

    def reset_episode(self, lane: int | None = None) -> None:
        """Reset per-episode serving state (history windows) without
        touching trajectories — one lane, or all lanes when ``lane`` is
        None."""
        with self._lock:
            if self._windows is None:
                return
            if lane is None:
                self._windows[:] = 0.0
                self._window_lens[:] = 0
            else:
                self._windows[lane, :, :] = 0.0
                self._window_lens[lane] = 0

    def _push_windows(self, obs: np.ndarray) -> None:
        """Append one observation per lane to the stacked rolling history
        (lock held). Lanes at capacity roll independently — each goes
        through the shared push_window rule so the byte-parity contract
        can't drift across tiers."""
        for lane in range(self.num_envs):
            self._window_lens[lane], _ = push_window(
                self._windows[lane], int(self._window_lens[lane]),
                obs[lane])


def run_vector_gym_loop(host, venv, steps: int,
                        seed: int | None = None) -> list[list[float]]:
    """Drive a :class:`~relayrl_tpu.envs.vector.SyncVectorEnv` (or any
    stacked gym-like with autoreset) through a vector host/agent for
    ``steps`` batched policy dispatches. Returns per-lane completed
    episode returns. Works with both a raw VectorActorHost and the
    networked VectorAgent (same batched action surface)."""
    from relayrl_tpu.runtime.agent import coerce_env_action

    n = venv.num_envs
    obs, _ = venv.reset(seed=seed)
    rewards = np.zeros(n, np.float32)
    ep_ret = np.zeros(n, np.float64)
    returns: list[list[float]] = [[] for _ in range(n)]
    for _ in range(steps):
        records = host.request_for_actions(obs, rewards=rewards)
        actions = [coerce_env_action(r.act) for r in records]
        obs, rews, terms, truncs, infos = venv.step(actions)
        ep_ret += rews
        for lane in range(n):
            if terms[lane] or truncs[lane]:
                # Autoreset already happened inside venv.step; the
                # pre-reset observation rides the info dict for the
                # time-limit bootstrap.
                time_limited = not terms[lane]
                host.flag_last_action(
                    lane, float(rews[lane]),
                    truncated=bool(time_limited),
                    final_obs=(infos[lane].get("final_observation")
                               if time_limited else None),
                    terminated=bool(terms[lane]))
                returns[lane].append(float(ep_ret[lane]))
                ep_ret[lane] = 0.0
                rewards[lane] = 0.0  # new episode: nothing earned yet
            else:
                rewards[lane] = rews[lane]
    return returns
