"""User-application contract: wire a custom environment to an Agent.

TPU-native counterpart of the reference's ``ApplicationAbstract``
(reference: relayrl_framework/src/native/python/_common/_examples/
BaseApplication.py:4-31), the base class its examples subclass to adapt a
domain application to the actor loop. The reference leaves all three
methods abstract, so every user re-writes the request/step/flag loop by
hand (examples/README.md:125-152 shows the canonical shape); here the
loop ships as a concrete, correct-by-default :meth:`drive_episode` that
``run_application`` implementations can delegate to — the same
hot-swap-aware loop the built-in examples and e2e tests use, including
the truncation/final-obs bookkeeping that 1-step TD learners need.
"""

from __future__ import annotations

import abc


class ApplicationAbstract(abc.ABC):
    """Adapter between a domain application and a RelayRL ``Agent``.

    Subclass and implement the three reference-parity methods; from
    ``run_application``, either write a custom loop against
    ``self.agent`` or call :meth:`drive_episode` per episode with any
    object exposing ``reset() -> raw`` and ``step(act) -> (raw, reward,
    terminated, truncated)``.
    """

    def __init__(self, agent):
        self.agent = agent

    @abc.abstractmethod
    def run_application(self, *args, **kwargs):
        """Run the application's main loop: collect observations, take
        actions, assign rewards."""

    @abc.abstractmethod
    def build_observation(self, raw, *args, **kwargs):
        """Map the application's raw state to the policy observation.

        May return either ``obs`` or ``(obs, mask)`` — ``drive_episode``
        accepts both; a ``(obs, mask)`` tuple routes the mask into
        ``request_for_action`` for masked-action policies.
        """

    @abc.abstractmethod
    def calculate_performance_return(self, *args, **kwargs):
        """Reward for the episode's terminal transition — the value the
        loop passes to ``flag_last_action``. :meth:`drive_episode` calls
        it as ``calculate_performance_return(last_reward, terminated=...,
        truncated=...)``; the identity implementation ``return
        last_reward`` reproduces the canonical unshaped loop."""

    def drive_episode(self, env, max_steps: int | None = None) -> float:
        """One episode of the canonical actor loop; returns the raw
        env-reward sum (terminal shaping from
        ``calculate_performance_return`` is what trains, but the raw sum
        is the comparable metric across shaping choices).

        Rewards ride the NEXT ``request_for_action`` so each record's
        ``rew`` means "reward earned by this action" (see
        policy_actor.py on the deliberate departure from the reference's
        one-step credit shift); the terminal reward goes through
        ``flag_last_action`` with ``terminated``/``truncated`` and the
        final observation forwarded, which off-policy learners need for
        correct bootstrapping at time limits.
        """
        # Lazy: agent.py chains in the transport plane, and this module is
        # imported eagerly by the package __init__ (which keeps Agent lazy).
        from relayrl_tpu.runtime.agent import coerce_env_action

        raw = env.reset()
        pending_reward = 0.0
        total = 0.0
        steps = 0
        while True:
            built = self.build_observation(raw)
            obs, mask = built if isinstance(built, tuple) else (built, None)
            record = self.agent.request_for_action(
                obs, mask=mask, reward=pending_reward)
            raw, reward, terminated, truncated = env.step(
                coerce_env_action(record.act))
            pending_reward = float(reward)
            total += pending_reward
            steps += 1
            if max_steps is not None and steps >= max_steps:
                truncated = True
            if terminated or truncated:
                # Successor state only matters for bootstrapping through a
                # time limit; on a genuine terminal the target is zeroed,
                # and the canonical loops pass None (so applications whose
                # terminal raw state can't build an observation still work).
                if truncated and not terminated:
                    final_built = self.build_observation(raw)
                    final_obs, final_mask = (
                        final_built if isinstance(final_built, tuple)
                        else (final_built, None))
                else:
                    final_obs = final_mask = None
                self.agent.flag_last_action(
                    reward=float(self.calculate_performance_return(
                        pending_reward, terminated=terminated,
                        truncated=truncated)),
                    terminated=terminated,
                    truncated=truncated,
                    final_obs=final_obs,
                    final_mask=final_mask,
                )
                return total
