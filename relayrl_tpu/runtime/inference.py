"""Disaggregated batched-inference serving plane (ROADMAP item 2).

Every actor tier so far holds its own policy replica and swaps full
params — the right shape for rollout throughput, the wrong one for the
"millions of users" serving scenario, where the fleet is wide, stateless,
and latency-bound. TorchBeast (arXiv:1910.03552) showed the answer is a
**dynamic-batching inference server**: accept observation requests, close
a batch on a size-or-deadline trigger, run ONE batched policy step, and
stream the actions back; Podracer's Sebulba split (arXiv:2104.06272)
colocates that service with the learner devices so actors become
near-stateless thin clients.

This module is both halves:

* :class:`InferenceService` — the latency-bounded dynamic-batching queue
  plus ONE ``jit(vmap)`` policy dispatch per closed batch
  (``make_batched_step`` — the exact composition every other actor tier
  jits, so a served action is bit-identical to a locally computed one for
  the same key). Batch shapes are bucketed to a small compiled set
  (``pick_bucket`` over ``serving.buckets``) and padded rows are sliced
  off before replies, so arbitrary occupancies never retrace. The service
  always serves the latest fenced params version: params are read ONCE
  per batch under the shared swap gate (``apply_bundle_swap`` — the same
  attribute contract PolicyActor/VectorActorHost/AnakinActorHost share),
  so a batch is single-model-version by construction even against a
  racing swapper. Overload (queue at ``serving.queue_limit``) answers
  with a typed ``NACK_OVERLOADED`` + retry-after instead of queueing
  unboundedly — a flood of inference clients cannot starve the learner's
  ingest plane.

* :class:`RemoteActorClient` — the thin-client actor
  (``actor.host_mode: "remote"``): no params, no model subscription, no
  swap gate; just a request/response loop carrying its PRNG key (the
  service splits it in-dispatch and returns the successor, so the
  client's action stream IS a PolicyActor's for the same seed). The
  trajectory plane — Trajectory assembly, spool/seq tagging, transport
  envelopes — is byte-identical to a local actor's, so the learner's
  ingest funnel cannot tell the tiers apart.

Colocated mode: the TrainingServer feeds :meth:`install_params` from its
publish path in-process — the service sees every published version with
ZERO wire hops. Standalone mode (dedicated serving devices):
:class:`StandaloneInferenceHost` subscribes over any agent transport like
an actor would and hosts the same service.
"""

from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from relayrl_tpu.data.batching import pick_bucket
from relayrl_tpu.transport.base import (
    NACK_OK,
    NACK_OVERLOADED,
    NACK_UNAVAILABLE,
)
from relayrl_tpu.transport.serving import (
    pack_action_reply,
    pack_infer_nack,
    pack_infer_request,
    unpack_infer_request,
)
from relayrl_tpu.types.action import ActionRecord
from relayrl_tpu.types.model_bundle import ModelBundle, exploration_kwargs
from relayrl_tpu.types.trajectory import Trajectory

CLOSE_SIZE = "size"
CLOSE_DEADLINE = "deadline"


class InferRequest:
    """One queued observation request (decoded, transport-agnostic)."""

    __slots__ = ("agent_id", "req_id", "key", "obs", "mask", "reply",
                 "t_enqueue", "trace", "t_enqueue_ns")

    def __init__(self, agent_id, req_id, key, obs, mask, reply):
        self.agent_id = agent_id
        self.req_id = req_id
        self.key = key
        self.obs = obs
        self.mask = mask
        self.reply = reply
        self.t_enqueue = time.monotonic()
        # Distributed tracing (telemetry/trace.py): a sampled request
        # draws a serve-plane trace id at submit; its queue/dispatch
        # hops record at batch execution.
        self.trace = None
        self.t_enqueue_ns = 0


def default_buckets(max_batch: int) -> list[int]:
    """Powers of two up to ``max_batch`` (inclusive, deduped): at most
    ~log2(max_batch) compiled dispatch shapes serve every occupancy."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return sorted(set(out))


class InferenceService:
    """Latency-bounded dynamic-batching policy server.

    Requests accumulate until ``max_batch`` arrivals (close reason
    ``size``) or ``batch_timeout_ms`` after the FIRST queued request of
    the batch (close reason ``deadline``), whichever fires first — the
    TorchBeast batching-server contract. ``queue_limit`` bounds waiting
    requests; beyond it submissions nack ``NACK_OVERLOADED`` with
    ``retry_after_s`` so clients back off instead of piling on.

    Swap surface: the service exposes the shared actor-host attribute
    contract (``version``/``arch``/``params``/``_explore_kwargs``/
    ``_lock``/``_wire_decoder``) so :func:`apply_bundle_swap` /
    :func:`apply_wire_swap` gate installs exactly as on every other
    actor tier — one params read per batch under ``_lock`` makes a batch
    single-version by construction.
    """

    def __init__(
        self,
        bundle: ModelBundle,
        max_batch: int = 16,
        batch_timeout_ms: float = 5.0,
        buckets=None,
        queue_limit: int = 1024,
        retry_after_s: float = 0.05,
        stale_after_s: float = 5.0,
        validate: bool = True,
    ):
        import jax

        from relayrl_tpu.models import build_policy, validate_policy

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._lock = threading.Lock()
        self.arch = dict(bundle.arch)
        self.policy = build_policy(self.arch)
        if self.policy.step_window is not None:
            raise ValueError(
                "sequence policies are not servable yet: the per-client "
                "rolling window would have to live server-side. Use a "
                "local actor tier (process/vector) for transformer "
                "policies — for token-level RLHF generation specifically, "
                "the RLHF scheduler's vector generation tier "
                "(relayrl_tpu/rlhf/scheduler.py, rlhf.generation_tier: "
                "\"vector\") serves them through the batched step_window "
                "path; see docs/operations.md \"RLHF workload plane\"")
        if validate:
            validate_policy(self.policy, bundle.params)
        self.params = bundle.params
        self.version = bundle.version
        self._explore_kwargs = exploration_kwargs(self.arch)
        self._wire_decoder = None
        from relayrl_tpu.runtime.policy_actor import make_batched_step

        self._batched_fn = make_batched_step(self.policy)
        self._jax = jax

        self.max_batch = int(max_batch)
        self.batch_timeout_s = max(0.0, float(batch_timeout_ms)) / 1000.0
        self.buckets = sorted(set(
            int(b) for b in (buckets or default_buckets(self.max_batch))))
        if self.buckets[-1] < self.max_batch:
            # The largest bucket must cover a size-closed full batch, or
            # pick_bucket would clamp DOWN and the pad computation go
            # negative — every full batch would then fail forever. (The
            # ConfigLoader applies the same clamp; direct constructions
            # get it here.)
            self.buckets.append(self.max_batch)
        self.queue_limit = max(1, int(queue_limit))
        self.retry_after_s = max(0.0, float(retry_after_s))
        # Ghost-work guard: a request older than this has been abandoned
        # by its client (whose per-attempt timeout elapsed and whose
        # retry is already queued behind it) — dispatching it anyway
        # would double-serve every retry round and amplify exactly the
        # backlog that made it stale. Such entries are answered with a
        # retryable nack at batch-gather time instead. 0 disables.
        self.stale_after_s = max(0.0, float(stale_after_s))

        self._queue: deque[InferRequest] = deque()
        self._cond = threading.Condition()
        self._worker: threading.Thread | None = None
        self._stop = threading.Event()
        self._zmq_plane = None
        self._zmq_addr = None

        from relayrl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_requests = reg.counter(
            "relayrl_serving_requests_total",
            "observation requests accepted into the batching queue")
        self._m_rejected = reg.counter(
            "relayrl_serving_rejected_total",
            "requests nacked NACK_OVERLOADED at the queue limit")
        self._m_errors = reg.counter(
            "relayrl_serving_request_errors_total",
            "malformed/unservable requests answered with an error reply")
        self._m_batches = {
            reason: reg.counter(
                "relayrl_serving_batches_total",
                "closed inference batches by close trigger",
                {"reason": reason})
            for reason in (CLOSE_SIZE, CLOSE_DEADLINE)}
        self._m_stale = reg.counter(
            "relayrl_serving_stale_dropped_total",
            "queued requests nacked unserved because they outlived "
            "serving.stale_after_s (their client already timed out and "
            "retried — dispatching them would double-serve ghost work)")
        self._m_occupancy = reg.histogram(
            "relayrl_serving_batch_occupancy",
            "requests per closed batch (occupancy > 1 = batching works)",
            # jaxlint: disable=MET03 - dimensionless request count, not a dimensioned unit
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
        self._m_dispatch_s = reg.histogram(
            "relayrl_serving_dispatch_seconds",
            "one batched policy dispatch (device compute + reply encode)")
        from relayrl_tpu.telemetry.core import LATENCY_BUCKETS_WIDE

        self._m_request_s = reg.histogram(
            "relayrl_serving_request_seconds",
            "request enqueue to reply handoff (queue wait + batch close "
            "wait + dispatch share)",
            # Wide log-spaced grid (ISSUE 14 bucket audit): the old 5 s
            # top bucket pinned overload-backlogged requests in +Inf.
            buckets=LATENCY_BUCKETS_WIDE)
        import weakref

        wref = weakref.ref(self)

        def _depth():
            svc = wref()
            return None if svc is None else len(svc._queue)

        reg.gauge_fn("relayrl_serving_queue_depth", _depth,
                     "observation requests awaiting a batch close")

    @classmethod
    def from_config(cls, bundle: ModelBundle, config,
                    validate: bool = True) -> "InferenceService":
        p = config.get_serving_params()
        return cls(bundle, max_batch=p["max_batch"],
                   batch_timeout_ms=p["batch_timeout_ms"],
                   buckets=p["buckets"], queue_limit=p["queue_limit"],
                   retry_after_s=p["retry_after_s"],
                   stale_after_s=p["stale_after_s"], validate=validate)

    # -- lifecycle --
    def bind_zmq(self, addr: str) -> None:
        """Bind (or re-bind on restart) the ROUTER serving plane at
        ``addr`` — the action channel for zmq fleets AND the native
        passthrough (the C++ core has no request/response action RPC)."""
        self._zmq_addr = addr

    def start(self) -> None:
        if self._worker is not None:
            return
        self._stop.clear()
        if self._zmq_addr is not None:
            from relayrl_tpu.transport.serving import ZmqServingPlane

            self._zmq_plane = ZmqServingPlane(self._zmq_addr,
                                              self.handle_request)
            self._zmq_plane.start()
        self._worker = threading.Thread(
            target=self._serve_loop, name="inference-batcher", daemon=True)
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10)
            self._worker = None
        # Parked requests answer with a retryable nack, not silence: a
        # restarting service must not wedge clients for a full timeout.
        # This must happen BEFORE the zmq plane closes — the nack rides
        # the plane's reply pipe, and a closed PUSH socket would drop it
        # silently (the plane's own stop() drains the pipe).
        with self._cond:
            pending, self._queue = list(self._queue), deque()
        for req in pending:
            self._safe_reply(req, pack_infer_nack(
                req.req_id, NACK_OVERLOADED, "inference service stopping",
                max(self.retry_after_s, 0.05)))
        if self._zmq_plane is not None:
            self._zmq_plane.stop()
            self._zmq_plane = None

    # -- model install --
    def maybe_swap(self, bundle: ModelBundle) -> bool:
        """Install a newer model (shared gate with every actor host):
        in-flight batches finish on the old version, the next batch reads
        the new one — single-version-per-batch either way."""
        from relayrl_tpu.runtime.policy_actor import apply_bundle_swap

        return apply_bundle_swap(self, bundle)

    def swap_from_wire(self, version: int, blob: bytes):
        """Wire-v2-aware swap for standalone hosts subscribing over an
        agent transport (same decode path as every actor)."""
        from relayrl_tpu.runtime.policy_actor import apply_wire_swap

        return apply_wire_swap(self, version, blob)

    def install_params(self, version: int, arch: dict, host_params) -> bool:
        """Colocated feed: the TrainingServer hands the freshly published
        host tree straight in (zero wire hops). The install owns its
        memory (the publisher's buffers keep moving) and lands on the
        serving device where one exists — the same placement rules as
        ``apply_wire_swap``."""
        jax = self._jax
        params = jax.tree.map(np.array, host_params)
        if jax.default_backend() != "cpu":
            params = jax.device_put(params)
        return self.maybe_swap(ModelBundle(version=int(version),
                                           arch=dict(arch), params=params))

    # -- request intake (transport threads) --
    def handle_request(self, payload: bytes, reply) -> InferRequest | None:
        """Transport callback: decode + enqueue (never dispatches here).
        Malformed frames answer code 0; a full queue answers the typed
        overload nack with retry-after. Returns the queued request (None
        when it was answered instead of queued) so blocking adapters can
        retract it on their own timeout. Runs on transport threads."""
        try:
            req = unpack_infer_request(payload)
        except Exception:
            self._m_errors.inc()
            reply(pack_infer_nack(-1, 0, "malformed inference request"))
            return None
        request = InferRequest(req["id"], req["req"], req["key"],
                               req["obs"], req["mask"], reply)
        return request if self.submit(request) else None

    def handle_request_blocking(self, payload: bytes) -> bytes:
        """RPC-thread adapter (grpc ``GetActions``): enqueue, then block
        this thread until its batch executes. The wait bound covers the
        worst batch close + dispatch; beyond it the client gets a
        retryable nack instead of a hung RPC — and the orphaned request
        is RETRACTED from the queue (if still there): under sustained
        overload a timed-out RPC must not leave ghost work behind that
        amplifies the very backlog that timed it out."""
        box: dict = {}
        done = threading.Event()

        def reply(b: bytes) -> None:
            box["reply"] = b
            done.set()

        request = self.handle_request(payload, reply)
        # Park bound: batch close + a stale-sweep interval, NOT a flat
        # 30 s — the caller's RPC deadline is ~request_timeout_s, and a
        # thread still parked long after it has been abandoned occupies
        # a slot in the gRPC pool the trajectory/long-poll planes share
        # (64 retrying clients would exhaust max_workers=128 and stall
        # ingest fleet-wide).
        done.wait(timeout=self.batch_timeout_s
                  + (self.stale_after_s or 5.0) + 2.0)
        if "reply" not in box and request is not None:
            with self._cond:
                try:
                    self._queue.remove(request)
                except ValueError:
                    pass  # already dispatched: its reply lands in the
                    #       abandoned box, a harmless one-off
        return box.get("reply") or pack_infer_nack(
            -1, NACK_OVERLOADED, "inference batch timed out",
            max(self.retry_after_s, 0.05))

    def submit(self, req: InferRequest) -> bool:
        """Queue one decoded request (True), or answer the overload nack
        when the queue is at ``serving.queue_limit`` (False — bounded
        queue = bounded worst-case latency; the client's retry-after
        honor is the backpressure loop)."""
        from relayrl_tpu.telemetry import trace as trace_mod

        tracer = trace_mod.get_tracer()
        if tracer.enabled:
            # Both trace fields must be final BEFORE the request becomes
            # visible to the batch worker — it reads them at gather time.
            req.trace = tracer.sample_id("serve")
            if req.trace is not None:
                req.t_enqueue_ns = time.monotonic_ns()
        with self._cond:
            if len(self._queue) >= self.queue_limit or self._stop.is_set():
                overloaded = True
            else:
                overloaded = False
                self._queue.append(req)
                self._cond.notify()
        if overloaded:
            self._m_rejected.inc()
            self._safe_reply(req, pack_infer_nack(
                req.req_id, NACK_OVERLOADED, "inference queue full",
                self.retry_after_s))
            return False
        self._m_requests.inc()
        return True

    # -- the batching loop (worker thread) --
    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            batch, reason = self._gather_batch()
            if batch:
                self._execute(batch, reason)

    def _gather_batch(self) -> tuple[list[InferRequest], str]:
        """Block for the first request, then accumulate until
        ``max_batch`` (size close) or ``batch_timeout_ms`` past the first
        request's enqueue (deadline close). The deadline anchors at
        ENQUEUE, not batch open: time a request spent queued behind the
        previous dispatch counts against its latency budget, so a loaded
        service degrades to immediate closes instead of stacking
        timeouts."""
        stale: list[InferRequest] = []

        def pop_fresh():
            # Ghost-work guard: entries older than stale_after_s were
            # abandoned by their (timed-out, already-retrying) client —
            # nack them unserved instead of double-serving every retry
            # round under backlog. Collected here, answered outside the
            # lock.
            while self._queue:
                req = self._queue.popleft()
                if (self.stale_after_s
                        and time.monotonic() - req.t_enqueue
                        > self.stale_after_s):
                    stale.append(req)
                    continue
                return req
            return None

        batch: list[InferRequest] = []
        with self._cond:
            first = pop_fresh()
            # Exit the wait as soon as there is ANYTHING to act on —
            # a fresh request to batch, or stale ones to nack (their
            # clients must not wait for unrelated traffic to arrive
            # before learning their request was shed).
            while first is None and not stale:
                if self._stop.is_set():
                    break
                self._cond.wait(0.1)
                first = pop_fresh()
            if first is not None:
                batch = [first]
                deadline = first.t_enqueue + self.batch_timeout_s
                while len(batch) < self.max_batch:
                    if self._queue:
                        got = pop_fresh()
                        if got is not None:
                            batch.append(got)
                        continue
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop.is_set():
                        break
                    self._cond.wait(remaining)
        for req in stale:
            self._m_stale.inc()
            self._safe_reply(req, pack_infer_nack(
                req.req_id, NACK_OVERLOADED, "request went stale in queue",
                self.retry_after_s))
        reason = CLOSE_SIZE if len(batch) >= self.max_batch \
            else CLOSE_DEADLINE
        return batch, reason

    def _execute(self, batch: list[InferRequest], reason: str) -> None:
        t0 = time.monotonic()
        # Close accounting rides AHEAD of the dispatch: a reply observer
        # (test, bench row) reading the counters right after its reply
        # arrives must already see this batch counted — the timing
        # histograms below stay post-dispatch because they measure it.
        self._m_batches[reason].inc()
        self._m_occupancy.observe(len(batch))
        # ONE params/version/explore read under the swap gate for the
        # whole batch: no request in it can ever be served by a different
        # model version than its batchmates (the invariant the vector
        # host enforces per dispatch, test-locked against a racing
        # swapper).
        with self._lock:
            params = self.params
            version = self.version
            explore = self._explore_kwargs
        # Mixed fleets may interleave request shapes (masked vs maskless,
        # pixel vs vector observations): group by signature, one bucketed
        # dispatch per group. Homogeneous fleets — the common case — see
        # exactly one group.
        groups: dict[tuple, list[InferRequest]] = {}
        for req in batch:
            sig = (req.obs.shape, str(req.obs.dtype), req.mask is not None,
                   str(req.key.dtype), req.key.shape)
            groups.setdefault(sig, []).append(req)
        for group in groups.values():
            try:
                self._dispatch_group(group, params, version, explore)
            except Exception as e:
                # One unservable group (bad shapes, dtype surprises) must
                # not take down the worker or its batchmates: every
                # member gets a retryable error reply.
                self._m_errors.inc(len(group))
                for req in group:
                    self._safe_reply(req, pack_infer_nack(
                        req.req_id, 0, f"dispatch failed: {e!r}"))
        now = time.monotonic()
        self._m_dispatch_s.observe(now - t0)
        for req in batch:
            self._m_request_s.observe(now - req.t_enqueue)
        traced = [req for req in batch if req.trace is not None]
        if traced:
            # Serve-plane hop spans for sampled requests: queue (enqueue
            # → batch gather) and dispatch (gather → reply handoff).
            from relayrl_tpu.telemetry import trace as trace_mod

            tracer = trace_mod.get_tracer()
            now_ns = time.monotonic_ns()
            t0_ns = now_ns - int((now - t0) * 1e9)
            for req in traced:
                tracer.span("serve", req.trace, "queue",
                            req.t_enqueue_ns, t0_ns,
                            agent=req.agent_id)
                tracer.span("serve", req.trace, "dispatch", t0_ns,
                            now_ns, occupancy=len(batch))

    def _dispatch_group(self, group: list[InferRequest], params,
                        version: int, explore: dict) -> None:
        jnp = self._jax.numpy
        n = len(group)
        bucket = pick_bucket(n, self.buckets)

        def padded(stack: np.ndarray) -> np.ndarray:
            # Pad to the bucket by repeating the last row: vmap rows are
            # independent, so pad content cannot perturb real rows (the
            # padding-invariance test locks it); repeating a REAL row
            # keeps dtypes/shapes trivially right.
            if bucket == n:
                return stack
            return np.concatenate(
                [stack, np.repeat(stack[-1:], bucket - n, axis=0)])

        keys = padded(np.stack([r.key for r in group]))
        obs = padded(np.stack([r.obs for r in group]))
        masks = None
        if group[0].mask is not None:
            masks = padded(np.stack([r.mask for r in group]))
        acts, aux, next_keys = self._batched_fn(
            params, jnp.asarray(keys), obs, masks, explore)
        acts_np = np.asarray(acts)
        keys_np = np.asarray(next_keys)
        aux_np = {k: np.asarray(v) for k, v in aux.items()}
        for i, req in enumerate(group):
            # np.asarray on the indexed rows: a stacked [N] column
            # indexes to a numpy scalar, and the wire must carry the 0-d
            # ndarray's exact dtype (the vector-host float64 lesson).
            reply = pack_action_reply(
                req.req_id, version, np.asarray(acts_np[i]), keys_np[i],
                {k: np.asarray(v[i]) for k, v in aux_np.items()})
            self._safe_reply(req, reply)

    @staticmethod
    def _safe_reply(req: InferRequest, payload: bytes) -> None:
        """Reply-delivery isolation: one dead client connection must not
        take down the batch that served its neighbors."""
        try:
            req.reply(payload)
        except Exception as e:
            print(f"[InferenceService] reply delivery failed: {e!r}",
                  flush=True)

    def accounting(self) -> dict:
        """Bench/drill evidence block (mirrors the registry counters)."""
        return {
            "queue_depth": len(self._queue),
            "max_batch": self.max_batch,
            "batch_timeout_ms": self.batch_timeout_s * 1000.0,
            "buckets": list(self.buckets),
        }


class RemoteActorClient:
    """Thin-client actor (``actor.host_mode: "remote"``): holds NO
    params, NO model subscription, NO swap gate — every action is a
    request/response round-trip to an :class:`InferenceService`. The
    trajectory plane (Trajectory assembly, spool sequence tags, transport
    envelopes) is the standard actor plane, byte-identical on the wire.

    The client carries its PRNG key and round-trips it through the
    service (which splits it inside the jitted dispatch, exactly
    ``_fuse_rng``), so for the same ``seed`` the served action stream is
    bit-identical to a local ``PolicyActor(seed=seed)`` holding the same
    params version — the parity contract tests/test_serving.py locks.

    Overload nacks honor the server's ``retry_after_s`` without charging
    the circuit breaker (the server is alive and answered — the spool's
    nack lesson); transport failures back off under the shared
    ``transport.retry`` policy behind a breaker, so a killed service
    never wedges the env loop in a hot retry spin.
    """

    def __init__(
        self,
        config_path: str | None = None,
        server_type: str = "zmq",
        seed: int | None = None,
        identity: str | None = None,
        start: bool = True,
        handshake_timeout_s: float = 60.0,
        **addr_overrides,
    ):
        import os

        from relayrl_tpu.config import ConfigLoader

        self.config = ConfigLoader(None, config_path)
        from relayrl_tpu import faults, telemetry

        telemetry.configure_from_config(self.config)
        faults.maybe_install_from_env()
        self._fault_infer = faults.site("agent.infer")
        self.server_type = server_type
        self._addr_overrides = addr_overrides
        self._identity = identity
        self._handshake_timeout_s = handshake_timeout_s
        self._seed = os.getpid() if seed is None else seed
        serving = self.config.get_serving_params()
        self._request_timeout_s = serving["request_timeout_s"]
        self._infer_deadline_s = serving["infer_deadline_s"]
        self._lock = threading.Lock()
        self._req_counter = 0
        self.version = -1  # latest service version that answered us
        self.transport = None
        self.spool = None
        self._serving = None
        self._breaker = None
        self._retry = None
        self._fleet_emitter = None
        self.trajectory = Trajectory(
            max_length=self.config.get_max_traj_length(),
            on_send=self._send_traj)
        import jax

        self._rng = np.asarray(jax.random.PRNGKey(self._seed))
        reg = telemetry.get_registry()
        self._m_steps = reg.counter(
            "relayrl_actor_env_steps_total",
            "policy steps served (one per env step per lane)")
        from relayrl_tpu.telemetry.core import LATENCY_BUCKETS_WIDE

        self._m_request_s = reg.histogram(
            "relayrl_serving_client_request_seconds",
            "one action round-trip on the client (send to decoded reply, "
            "retries included)",
            # Wide grid (ISSUE 14 bucket audit): retries through an open
            # breaker legitimately stack past the old 5 s top bucket.
            buckets=LATENCY_BUCKETS_WIDE)
        self._m_retries = reg.counter(
            "relayrl_serving_client_retries_total",
            "inference request attempts beyond the first")
        self._m_nacked = reg.counter(
            "relayrl_serving_client_nacked_total",
            "overload nacks honored (slept retry_after_s, no breaker "
            "charge)")
        self.active = False
        if start:
            self.enable_agent()

    # -- lifecycle (Agent-compatible surface) --
    def enable_agent(self) -> None:
        if self.active:
            return
        from relayrl_tpu.transport import make_agent_transport
        from relayrl_tpu.transport.retry import (
            RetryPolicy,
            breaker_from_config,
        )
        from relayrl_tpu.transport.serving import make_serving_client

        overrides = dict(self._addr_overrides)
        overrides.setdefault("negotiate_window_s",
                             min(self._handshake_timeout_s * 0.5, 30.0))
        if self._identity is not None:
            overrides.setdefault("identity", self._identity)
        serving_overrides = {
            k: overrides.pop(k)
            for k in ("serving_addr", "serving_plane")
            if k in overrides}
        self.transport = make_agent_transport(
            self.server_type, self.config, **overrides)
        # No fetch_model: the whole point is that this actor never holds
        # a model. Registration still announces the logical agent.
        try:
            self.transport.register(self.transport.identity, timeout_s=10.0)
        except Exception as e:
            print(f"[RemoteActorClient] registration failed (continuing "
                  f"unregistered): {e!r}", flush=True)
        self._bind_spool()
        self.transport.on_reconnect = self._handle_reconnect
        retry_cfg = self.config.get_transport_params()["retry"]
        self._retry = RetryPolicy.from_dict(retry_cfg)
        if self._breaker is None:
            self._breaker = breaker_from_config(
                f"infer:{self._identity or 'remote'}", retry_cfg)
        self._serving = make_serving_client(
            self.server_type, self.config, transport=self.transport,
            **serving_overrides)
        from relayrl_tpu.runtime.agent import _start_fleet_emitter

        self._fleet_emitter = _start_fleet_emitter(self, "client")
        self.active = True
        from relayrl_tpu import telemetry

        telemetry.emit("agent_register", agent_id=self.transport.identity,
                       side="agent", mode="remote")

    def disable_agent(self) -> None:
        if not self.active:
            return
        from relayrl_tpu.runtime.agent import _close_fleet_emitter

        _close_fleet_emitter(self)
        if self.spool is not None:
            self.spool.send_fn = None
        if self._serving is not None:
            self._serving.close()
            self._serving = None
        self.transport.close()
        self.transport = None
        self.active = False

    def _bind_spool(self) -> None:
        from relayrl_tpu.runtime.agent import _bind_spool_impl

        _bind_spool_impl(self, self._identity or "remote")

    def _handle_reconnect(self) -> None:
        from relayrl_tpu.runtime.agent import _handle_reconnect_impl

        _handle_reconnect_impl(self, [self.transport.identity])

    def _send_traj(self, payload: bytes) -> None:
        # Trajectory tracing parity with Agent._send_traj: the thin
        # client's episodes draw trace contexts too (env hop = the
        # round-trip-served production window).
        from relayrl_tpu.runtime.agent import _trace_emit, _trace_send_span

        traj = self.trajectory
        ctx = _trace_emit(self.transport.identity, traj.born_ns,
                          traj.encode_t0_ns, traj.encode_t1_ns,
                          self.version)
        t0 = 0
        if ctx is not None:
            t0 = time.monotonic_ns()
        if self.spool is not None:
            self.spool.send(payload, self.transport.identity,
                            trace=None if ctx is None else ctx.encode())
            _trace_send_span(ctx, self.transport.identity, t0)
        else:
            from relayrl_tpu.transport.base import IngestNack, tag_agent_trace

            try:
                self.transport.send_trajectory(
                    payload,
                    agent_id=(None if ctx is None else tag_agent_trace(
                        self.transport.identity, ctx.encode())))
                _trace_send_span(ctx, self.transport.identity, t0)
            except IngestNack:
                pass  # guardrail verdict, spool-less: drop (see Agent)

    # -- action API (PolicyActor-shaped) --
    def request_for_action(self, obs, mask=None,
                           reward: float = 0.0) -> ActionRecord:
        """One served action: ship the observation + current PRNG key,
        append the returned action to the trajectory. Reward credit
        semantics identical to ``PolicyActor.request_for_action`` (the
        reward lands on the PREVIOUS record)."""
        self._require_active()
        from relayrl_tpu.runtime.policy_actor import normalize_obs

        # Byte frames stay bytes on the wire, everything else float32 —
        # the shared rule every tier uses (the parity contract rides on
        # it staying ONE body).
        obs = normalize_obs(obs)
        mask_arr = None if mask is None else np.asarray(mask, np.float32)
        with self._lock:
            if reward and self.trajectory.get_actions():
                self.trajectory.get_actions()[-1].update_reward(
                    float(reward))
            # jaxlint: disable=LOCK02 - per-client lock; the env loop is serial, blocking here IS the backpressure
            act, aux = self._infer(obs, mask_arr)
            record = ActionRecord(
                obs=obs, act=act, mask=mask_arr,
                rew=0.0,  # filled by the NEXT request / terminal marker
                data=aux, done=False)
            self.trajectory.add_action(record, send_if_done=True)
        self._m_steps.inc()
        return record

    def flag_last_action(self, reward: float = 0.0, truncated: bool = False,
                         final_obs=None, terminated: bool | None = None,
                         final_mask=None) -> None:
        """Terminal marker — same semantics as PolicyActor's (terminated
        beats truncated, the bootstrap final_obs rides the marker); no
        serving state to reset because the client holds none."""
        self._require_active()
        if terminated:
            truncated = False
        with self._lock:
            record = ActionRecord(
                obs=(None if final_obs is None
                     else np.asarray(final_obs, np.float32)),
                mask=(None if final_mask is None
                      else np.asarray(final_mask, np.float32)),
                rew=float(reward), done=True, truncated=bool(truncated))
            self.trajectory.add_action(record, send_if_done=True)

    def record_action(self, action: ActionRecord) -> None:
        self._require_active()
        with self._lock:
            self.trajectory.add_action(action, send_if_done=True)

    def _infer(self, obs: np.ndarray, mask) -> tuple[np.ndarray, dict]:
        """One request/response round-trip with overload + failure
        handling (lock held — the env loop is serial per client):

        * overload nack → honor ``retry_after_s``, no breaker charge;
        * timeout / connection error → breaker charge + jittered backoff
          under ``transport.retry`` (a dead service opens the breaker and
          the loop waits out half-open probes instead of hot-spinning);
        * total budget ``serving.infer_deadline_s`` → RuntimeError (the
          env loop's caller decides; nothing is appended mid-failure).
        """
        self._req_counter += 1
        req_id = self._req_counter
        clean = pack_infer_request(
            self.transport.identity, req_id, self._rng, obs, mask)
        first_attempt = clean
        dropped_first = False
        if self._fault_infer is not None:
            # chaos plane (agent.infer): the injection applies to the
            # FIRST attempt only — drop surfaces as a timeout → retry,
            # corrupt dies in the service's decode guard → retry, delay
            # sleeps here. Retries always carry the clean payload (one
            # fault per op, the plan's per-op contract — a corrupted
            # attempt retried corrupted forever would turn a 20%-corrupt
            # drill into guaranteed deadline exhaustion).
            parts = self._fault_infer.inject(clean)
            if not parts:
                dropped_first = True
            else:
                delay_s, first_attempt = parts[-1]
                if delay_s > 0:
                    time.sleep(delay_s)
        deadline = time.monotonic() + self._infer_deadline_s
        attempt = 0
        t0 = time.monotonic()
        last_error = ""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise RuntimeError(
                    f"inference request exhausted its "
                    f"{self._infer_deadline_s:.0f}s budget "
                    f"(service down? breaker={self._breaker.state}"
                    f"{f'; last error: {last_error}' if last_error else ''})")
            if dropped_first:
                # fault-dropped first attempt: exactly a timeout's shape
                dropped_first = False
                self._note_failure(attempt, remaining)
                attempt += 1
                continue
            if not self._breaker.allow():
                time.sleep(min(0.2, remaining))
                continue
            try:
                reply = self._serving.request(
                    first_attempt if attempt == 0 else clean, req_id,
                    min(self._request_timeout_s, remaining))
            except (TimeoutError, ConnectionError, OSError):
                self._breaker.record_failure()
                self._note_failure(attempt, deadline - time.monotonic())
                attempt += 1
                continue
            self._breaker.record_success()
            code = reply["code"]
            if code == NACK_OVERLOADED:
                # The service is ALIVE and shed us: honor the hint, keep
                # the breaker closed (the IngestNack lesson).
                self._m_nacked.inc()
                time.sleep(min(max(reply["retry_after_s"], 0.001),
                               max(0.0, deadline - time.monotonic())))
                continue
            if code == NACK_UNAVAILABLE:
                # PERMANENT: the endpoint answered but no inference
                # service is installed (serving.enabled false) — a
                # misconfiguration, not an outage; retrying would only
                # bury the pointed error under a deadline exhaustion.
                raise RuntimeError(
                    f"inference unavailable: {reply['error']}")
            if code != NACK_OK or "act" not in reply:
                # code-0 error (malformed/failed dispatch): retryable —
                # the chaos corrupt drill lands here.
                last_error = reply.get("error") or last_error
                self._note_failure(attempt, deadline - time.monotonic())
                attempt += 1
                continue
            self._rng = np.frombuffer(
                reply["key"], dtype=self._rng.dtype).copy()
            self.version = reply["ver"]
            self._m_request_s.observe(time.monotonic() - t0)
            return reply["act"], reply["aux"]

    def _note_failure(self, attempt: int, remaining: float) -> None:
        self._m_retries.inc()
        if remaining > 0:
            time.sleep(min(self._retry.delay(attempt), remaining))

    @property
    def model_version(self) -> int:
        """Latest service-side params version that served this client an
        action (-1 before the first reply) — the thin client's analogue
        of an actor's installed version."""
        return self.version

    def _require_active(self) -> None:
        if not self.active or self._serving is None:
            raise RuntimeError(
                "remote actor client is not active (call enable_agent())")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disable_agent()


class StandaloneInferenceHost:
    """An InferenceService on dedicated devices: subscribes to the model
    plane over any agent transport exactly like an actor (handshake →
    wire-v2 deltas → shared swap gate) and serves the zmq ROUTER action
    plane. The Sebulba "dedicated inference devices" placement; the
    colocated placement lives inside TrainingServer (zero wire hops).
    """

    def __init__(self, config_path: str | None = None,
                 server_type: str = "zmq", serving_addr: str | None = None,
                 handshake_timeout_s: float = 60.0, start: bool = True,
                 **addr_overrides):
        from relayrl_tpu.config import ConfigLoader
        from relayrl_tpu.transport import make_agent_transport

        self.config = ConfigLoader(None, config_path)
        from relayrl_tpu import telemetry

        telemetry.configure_from_config(self.config)
        self.transport = make_agent_transport(server_type, self.config,
                                              **addr_overrides)
        version, bundle_bytes = self.transport.fetch_model(
            handshake_timeout_s)
        bundle = ModelBundle.from_bytes(
            bundle_bytes, params_template=ModelBundle.RAW_TREE)
        bundle.version = version
        self.service = InferenceService.from_config(bundle, self.config)
        self.service.bind_zmq(
            serving_addr or self.config.get_inference_server().address)
        self.transport.on_model = self._on_model
        self.active = False
        if start:
            self.start()

    def _on_model(self, version: int, blob: bytes) -> None:
        from relayrl_tpu.transport.modelwire import WireBaseMismatch

        try:
            self.service.swap_from_wire(version, blob)
        except WireBaseMismatch:
            self.transport.request_resync()
        except Exception as e:
            print(f"[StandaloneInferenceHost] rejected model update: "
                  f"{e!r}", flush=True)

    def start(self) -> None:
        if self.active:
            return
        self.service.start()
        self.transport.start_model_listener()
        self.active = True

    def stop(self) -> None:
        if not self.active:
            return
        self.service.stop()
        self.transport.close()
        self.active = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


__all__ = ["InferenceService", "InferRequest", "RemoteActorClient",
           "StandaloneInferenceHost", "default_buckets",
           "CLOSE_SIZE", "CLOSE_DEADLINE"]
